"""Fleet telemetry plane: membership, federation, trace stitching.

The obs stack below this module (metrics / flightrec / slo / devprof) is
entirely per-process; the ROADMAP's multi-host fleet needs role-aware
health, load, and SLO signals ACROSS processes before any data plane can
route on them (the RTP-LLM lesson — disaggregated serving stands or
falls on this layer). This module is that plane, stdlib-only, riding the
existing obs/http.py endpoint every service already starts:

  * **membership** — each process announces itself to its peers with a
    heartbeat (POST ``/fleet/announce``) carrying host id, role, rank,
    the bound metrics port, per-pool replica/occupancy stats, the
    devprof capacity annotation, and the SLO burn summary. A member that
    stops heartbeating ages ``up -> suspect -> dead`` (the closed
    :data:`MEMBER_STATES` enum); every edge lands in the bounded
    transition journal, on ``aios_tpu_fleet_member_transitions_total``,
    and on the flight recorder's fleet lane. Peers come from
    ``AIOS_TPU_FLEET_PEERS``, are seeded from ``AIOS_TPU_COORDINATOR``,
    and gossip through announce responses (each response carries the
    responder's known peer list, so a chain of seeds converges to a
    full mesh).
  * **federation** — ``/metrics/fleet`` scrapes every live peer's
    ``/metrics`` text exposition and re-exposes the union with a
    ``host`` label injected into every sample; the SLO rollup (worst-
    burn host, per-objective fleet attainment) folds into /healthz via
    ``slo.annotate_health``.
  * **trace stitching** — ``/debug/trace/fleet?trace=<id>`` fetches the
    trace's timelines from each peer's flight recorder (the traceparent
    already crosses the gRPC boundary via the interceptors) and merges
    them into one Chrome-trace JSON with one lane group per host.
  * ``scripts/fleetctl.py`` renders the membership table off
    ``/fleet/members`` — the operator surface RUNBOOK §9 points at.

Locking: ``_lock`` (registry role "fleet") is pure bookkeeping — member
table, journal, peer set. Network I/O (announces, scrapes, stitches)
always runs OUTSIDE it; metric/recorder emission for state edges happens
after the lock is released (no fleet->recorder lock edge).
"""

from __future__ import annotations

import json
import logging
import os
import random
import socket
import threading
import time
import urllib.parse
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

from ..analysis.locks import make_lock
from .metrics import REGISTRY, MetricsRegistry

log = logging.getLogger("aios.fleet")

# Member lifecycle — THE closed enum (pinned by test_obs_lint): a member
# with a fresh heartbeat is "up", one past the suspect window is
# "suspect" (still scraped — a GC pause or a slow box must not instantly
# drop its series from the federation), one past the dead window is
# "dead" (dropped from /metrics/fleet and flagged by fleetctl). A dead
# member that announces again flips straight back to "up" — restarts are
# the common case, not an error.
MEMBER_STATES = ("up", "suspect", "dead")

# Transition journal bound: membership churn is slow (heartbeat-scale);
# 256 edges is hours of history and keeps /fleet/members bounded.
_MAX_JOURNAL = 256

# Announce/scrape bodies are bounded reads: a confused peer must not be
# able to balloon the registry.
_MAX_BODY_BYTES = 4 << 20

# Worst-burn tenants per heartbeat: the announce payload must stay
# small under tenant churn; fleetctl top merges each host's worst few.
_MAX_SLO_TENANTS = 4


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class FleetConfig:
    """Knobs (docs/CONFIG.md "Fleet telemetry" section). Read live from
    the environment at construction so tests and deploy scripts can
    reconfigure per process."""

    def __init__(self) -> None:
        self.enabled = os.environ.get(
            "AIOS_TPU_FLEET", ""
        ).lower() in ("1", "true", "on")
        self.peers = tuple(
            p.strip()
            for p in os.environ.get("AIOS_TPU_FLEET_PEERS", "").split(",")
            if p.strip()
        )
        self.interval_secs = _env_float("AIOS_TPU_FLEET_INTERVAL_SECS", 2.0)
        # suspect/dead windows are absolute seconds since the last
        # heartbeat, not interval multiples — an operator tuning the
        # announce cadence must not silently retune failure detection
        self.suspect_secs = _env_float("AIOS_TPU_FLEET_SUSPECT_SECS", 6.0)
        self.dead_secs = _env_float("AIOS_TPU_FLEET_DEAD_SECS", 15.0)
        self.seed_port = int(_env_float("AIOS_TPU_FLEET_SEED_PORT", 9100))
        self.scrape_timeout = _env_float("AIOS_TPU_FLEET_SCRAPE_TIMEOUT", 2.0)

    def active(self) -> bool:
        return self.enabled or bool(self.peers)

    def seed_peers(self) -> Tuple[str, ...]:
        """AIOS_TPU_FLEET_PEERS, else the coordinator host (the
        multihost env contract) on AIOS_TPU_FLEET_SEED_PORT — one seed
        is enough, announce-response gossip converges the rest."""
        if self.peers:
            return self.peers
        from ..parallel import multihost

        contract = multihost.env_contract()
        if contract is not None and contract.coordinator:
            host = contract.coordinator.rsplit(":", 1)[0]
            return (f"{host}:{self.seed_port}",)
        return ()


def process_identity(role: str = "") -> Dict[str, str]:
    """The per-process identity stamped on every heartbeat, on the
    ``aios_tpu_process_info`` gauge, and on every bench.py JSON line:
    host id (AIOS_TPU_FLEET_HOST, else hostname:pid — unique when many
    processes share one box in tests), role (AIOS_TPU_FLEET_ROLE, else
    the service name passed in), rank from the multihost env contract,
    and the package version."""
    from .. import __version__
    from ..parallel import multihost

    contract = multihost.env_contract()
    rank = contract.process_id if contract is not None else 0
    return {
        "host": os.environ.get("AIOS_TPU_FLEET_HOST", "")
        or f"{socket.gethostname()}:{os.getpid()}",
        "role": os.environ.get("AIOS_TPU_FLEET_ROLE", "") or role or "service",
        "rank": str(rank if rank is not None else 0),
        "version": __version__,
    }


def stamp_process_info(role: str = "") -> Dict[str, str]:
    """Set the ``aios_tpu_process_info`` info-gauge (value 1, identity
    in labels — the Prometheus *_info convention) and return the
    identity dict."""
    from . import instruments

    ident = process_identity(role)
    instruments.PROCESS_INFO.labels(**ident).set(1.0)
    return ident


def default_target() -> str:
    """fleetctl's default endpoint (AIOS_TPU_FLEET_TARGET, host:port of
    any member's metrics endpoint)."""
    return os.environ.get("AIOS_TPU_FLEET_TARGET", "127.0.0.1:9100")


# -- heartbeat payload helpers ----------------------------------------------

# pool-stats providers: serving/runtime layers register callables
# returning {model: {stat: scalar}}; consumed at each heartbeat build.
# Module-level so providers can register before (or without) a registry.
_stats_providers: List[Callable[[], Dict[str, dict]]] = []


def add_stats_provider(fn: Callable[[], Dict[str, dict]]) -> None:
    """Register a per-model pool-stats source for heartbeat payloads
    (e.g. the runtime service's ReplicaPool.heartbeat_stats view)."""
    _stats_providers.append(fn)


def clear_stats_providers() -> None:
    """Test isolation."""
    _stats_providers.clear()


def _self_pools() -> Dict[str, dict]:
    pools: Dict[str, dict] = {}
    for fn in list(_stats_providers):
        try:
            pools.update(fn())
        except Exception as exc:  # noqa: BLE001 - a sick pool must not
            # stop the heartbeat; the failure is the payload
            pools.setdefault("_error", {})["provider"] = repr(exc)[:120]
    return pools


# gossiped prefix digest providers (aios_tpu/fleet/gprefix.py): callables
# returning {model: {"page": page_size, "tails": {hex16: blocks}}},
# consumed at each heartbeat build — same registration pattern as the
# pool-stats providers.
_digest_providers: List[Callable[[], Dict[str, dict]]] = []

# this process's KvTransfer endpoint (host:port), piggybacked on the
# heartbeat so peers know where to Fetch/Push/Handoff; "" = no data plane
_transfer_addr = ""


def add_digest_provider(fn: Callable[[], Dict[str, dict]]) -> None:
    """Register a per-model prefix-digest source for heartbeat payloads
    (the fleet data plane's gossiped prefix index)."""
    _digest_providers.append(fn)


def clear_digest_providers() -> None:
    """Test isolation."""
    _digest_providers.clear()


def set_transfer_addr(addr: str) -> None:
    """Publish this process's KvTransfer gRPC endpoint on the heartbeat
    (the runtime service calls this with its ACTUAL bound port)."""
    global _transfer_addr
    _transfer_addr = addr


def _self_gprefix() -> Dict[str, dict]:
    digest: Dict[str, dict] = {}
    for fn in list(_digest_providers):
        try:
            digest.update(fn())
        except Exception as exc:  # noqa: BLE001 - a sick engine must not
            # stop the heartbeat; the failure is the payload
            digest.setdefault("_error", {})["provider"] = repr(exc)[:120]
    return digest


def _self_slo() -> dict:
    """Compact SLO summary for the heartbeat: worst burn across models
    and objectives (None while no window is evaluable), per-model
    per-objective attainment, and the worst few tenants by TTFT burn
    (bounded — the heartbeat stays announce-sized; fleetctl top ranks
    the fleet-wide union)."""
    from . import slo as slomod

    worst: Optional[float] = None
    models: Dict[str, dict] = {}
    tenants: Dict[str, float] = {}  # "model/tenant" -> TTFT burn
    target = slomod.ENGINE.cfg.target
    for m in slomod.ENGINE.models():
        ev = slomod.ENGINE.evaluate(m)
        att = {}
        for o, v in ev.items():
            att[o] = v.get("attainment", 1.0)
            if v.get("samples", 0) >= slomod.ENGINE.cfg.min_samples:
                b = v.get("burn_rate", 0.0)
                worst = b if worst is None else max(worst, b)
        models[m] = att
        for ten, row in slomod.ENGINE.tenants(m).items():
            if row.get("samples", 0) < slomod.ENGINE.cfg.min_samples:
                continue
            burn = (1.0 - row.get("ttft_attainment", 1.0)) \
                / max(1.0 - target, 1e-9)
            tenants[f"{m}/{ten}"] = round(burn, 4)
    out: dict = {"worst_burn": worst, "attainment": models}
    if tenants:
        out["tenants"] = dict(sorted(
            tenants.items(), key=lambda kv: -kv[1]
        )[:_MAX_SLO_TENANTS])
    return out


def _self_capacity() -> dict:
    """Devprof capacity annotation: per-model device-seconds and best
    observed MFU across graph kinds (empty until devprof is armed)."""
    from . import devprof

    out: Dict[str, dict] = {}
    try:
        snap = devprof.snapshot_all()
    except Exception:  # noqa: BLE001 - devprof absence is data, log it
        log.debug("devprof snapshot unavailable for heartbeat", exc_info=True)
        return out
    for model, ledgers in snap.get("models", {}).items():
        secs, mfu = 0.0, None
        for led in ledgers:
            for g in led.get("graphs", {}).values():
                secs += g.get("device_seconds", 0.0)
                if "mfu" in g:
                    mfu = max(mfu or 0.0, g["mfu"])
        entry: dict = {"device_seconds": round(secs, 4)}
        if mfu is not None:
            entry["mfu"] = mfu
        out[model] = entry
    return out


def _http_json(url: str, payload: Optional[dict] = None,
               timeout: float = 2.0) -> dict:
    # the fleet's HTTP injection choke point (faults/net.py): announce,
    # stitch, and drain traffic all pass here — check_send models the
    # outbound edge, check_drop_response the severed reply
    from ..faults import net

    net.check_send(url, "http")
    data = json.dumps(payload).encode("utf-8") if payload is not None else None
    req = urllib.request.Request(
        url, data=data,
        headers={"Content-Type": "application/json"} if data else {},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        body = json.loads(r.read(_MAX_BODY_BYTES).decode("utf-8"))
    net.check_drop_response(url, "http")
    return body


def _http_text(url: str, timeout: float = 2.0) -> str:
    from ..faults import net

    net.check_send(url, "http")
    with urllib.request.urlopen(url, timeout=timeout) as r:
        body = r.read(_MAX_BODY_BYTES).decode("utf-8")
    net.check_drop_response(url, "http")
    return body


# -- exposition relabeling ---------------------------------------------------

def relabel_exposition(text: str, host: str) -> List[tuple]:
    """Parse one Prometheus text exposition and inject ``host`` into
    every sample -> [(family, help, type, [sample lines])]. Samples
    attach to the most recent # HELP/# TYPE family when their name
    extends it (histogram _bucket/_sum/_count), else to their own name —
    federation must keep each family's samples contiguous."""
    fams: Dict[str, dict] = {}
    order: List[str] = []

    def fam(name: str) -> dict:
        f = fams.get(name)
        if f is None:
            f = fams[name] = {"help": "", "type": "", "samples": []}
            order.append(name)
        return f

    current = ""
    for line in text.splitlines():
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            parts = line.split(" ", 3)
            if len(parts) < 4:
                continue
            current = parts[2]
            fam(current)[parts[1].lower()] = parts[3]
            continue
        if not line or line.startswith("#"):
            continue
        brace = line.find("{")
        space = line.find(" ")
        if brace >= 0 and (space < 0 or brace < space):
            end = brace
        else:
            end = space
        if end <= 0:
            continue
        name = line[:end]
        rest = line[end:]
        if brace == end:
            # name{labels} value — host goes first; a sample already
            # carrying a host label (nested federation) passes through
            close = rest.rfind("}")
            labels = rest[1:close]
            value = rest[close + 1:]
            if 'host="' in labels:
                sample = line
            else:
                sep = "," if labels else ""
                sample = (f'{name}{{host="{host}"{sep}{labels}}}{value}')
        else:
            sample = f'{name}{{host="{host}"}}{rest}'
        owner = current if current and name.startswith(current) else name
        fam(owner)["samples"].append(sample)
    return [(n, fams[n]["help"], fams[n]["type"], fams[n]["samples"])
            for n in order]


def merge_expositions(sources: List[Tuple[str, str]]) -> str:
    """[(host, exposition text)] -> one union exposition with the host
    label injected, families contiguous across hosts, first HELP/TYPE
    text winning."""
    fams: Dict[str, dict] = {}
    order: List[str] = []
    for host, text in sources:
        for name, help_, type_, samples in relabel_exposition(text, host):
            f = fams.get(name)
            if f is None:
                f = fams[name] = {"help": help_, "type": type_, "samples": []}
                order.append(name)
            else:
                f["help"] = f["help"] or help_
                f["type"] = f["type"] or type_
            f["samples"].extend(samples)
    lines: List[str] = []
    for name in order:
        f = fams[name]
        if not f["samples"]:
            continue
        if f["help"]:
            lines.append(f"# HELP {name} {f['help']}")
        if f["type"]:
            lines.append(f"# TYPE {name} {f['type']}")
        lines.extend(f["samples"])
    return "\n".join(lines) + ("\n" if lines else "")


# -- trace stitching ---------------------------------------------------------

# pid stride between host lane groups in the stitched Chrome trace: each
# host's sub-trace keeps its own model-pid numbering inside its block
_PID_STRIDE = 100


def stitch_chrome_traces(host_timelines: Dict[str, list]) -> dict:
    """{host: [timeline dicts]} -> one Chrome-trace JSON with per-host
    lane groups: each host renders through the SAME flightrec renderer
    (snapshot/live parity), then its pids shift into a host-indexed
    block and its process names gain the host prefix — orchestrator,
    runtime, and engine lanes from different processes line up on one
    wall-clock axis."""
    from . import flightrec

    events: List[dict] = []
    for i, host in enumerate(sorted(host_timelines)):
        sub = flightrec.chrome_trace(host_timelines[host])
        offset = i * _PID_STRIDE
        for ev in sub["traceEvents"]:
            ev = dict(ev)
            ev["pid"] = ev.get("pid", 0) + offset
            if ev.get("name") == "process_name":
                args = dict(ev.get("args", {}))
                args["name"] = f"host:{host} {args.get('name', '')}".strip()
                ev["args"] = args
            events.append(ev)
    events.sort(key=lambda e: e.get("ts", 0))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# -- the registry ------------------------------------------------------------

class FleetRegistry:
    """One process's view of the fleet: the member table, the heartbeat
    loop, the failure-detector tick, and the federation/stitch fetches.
    ``clock`` is injectable for deterministic state-machine tests."""

    def __init__(self, identity: Dict[str, str], metrics_addr: str,
                 cfg: Optional[FleetConfig] = None,
                 registry: Optional[MetricsRegistry] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.identity = dict(identity)
        self.metrics_addr = metrics_addr
        self.cfg = cfg or FleetConfig()
        self.registry = registry or REGISTRY
        self.clock = clock
        self._lock = make_lock("fleet")
        self._members: Dict[Tuple[str, str], dict] = {}  #: guarded_by _lock
        self._journal: List[dict] = []  #: guarded_by _lock
        self._peer_addrs: List[str] = []  #: guarded_by _lock
        self._seq = 0  #: guarded_by _lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._register_member_metrics(identity["host"], identity["role"])
        self.self_descriptor()  # seeds the member table with self
        for addr in self.cfg.seed_peers():
            self._add_peer(addr)

    # -- metrics registration -------------------------------------------------

    def _register_member_metrics(self, host: str, role: str) -> None:
        """Pre-register every (host, role, state) transition child by
        iterating the closed MEMBER_STATES enum (the autoscale/SLO
        registration pattern): a new state is a reviewed enum change,
        never a stray label value."""
        from . import instruments

        instruments.FLEET_MEMBER_UP.labels(host=host, role=role)
        for state in MEMBER_STATES:
            instruments.FLEET_TRANSITIONS.labels(
                host=host, role=role, state=state
            )
        instruments.FLEET_SCRAPE_FAILURES.labels(host=host, role=role)

    # -- self descriptor ------------------------------------------------------

    def self_descriptor(self) -> dict:
        """The heartbeat payload: identity + bound metrics endpoint +
        pool stats + prefix digest + devprof capacity + SLO burn. Built
        OUTSIDE the fleet lock (providers may take pool/slo/engine
        locks)."""
        with self._lock:
            self._seq += 1
            seq = self._seq
        from ..fleet import drain

        desc = {
            **self.identity,
            "metrics_addr": self.metrics_addr,
            "kvx_addr": _transfer_addr,
            "pid": os.getpid(),
            "seq": seq,
            "pools": _self_pools(),
            "gprefix": _self_gprefix(),
            "capacity": _self_capacity(),
            "slo": _self_slo(),
            # the drain ladder phase (fleet/drain.py DRAIN_PHASES):
            # peers stop routing to a non-"serving" host before it dies
            "phase": drain.phase(),
        }
        # Every freshly built descriptor also refreshes OUR stored
        # member row. Before this, self's desc was folded in only at
        # construction, so /fleet/members reported the degrade-ladder
        # rung (and every other live pool stat) as of boot — a
        # controller mid-walk between ticks was invisible to fleetctl.
        self._apply_edges(self._observe(desc))
        return desc

    # -- membership state machine --------------------------------------------

    def _key(self, desc: dict) -> Optional[Tuple[str, str]]:
        host, role = desc.get("host"), desc.get("role")
        if not host or not role:
            return None
        return (str(host), str(role))

    def _observe(self, desc: dict) -> List[tuple]:
        """Fold one announce into the member table -> state edges to
        emit. Registers metric children for first-seen members."""
        key = self._key(desc)
        if key is None:
            return []
        now = self.clock()
        edges: List[tuple] = []
        with self._lock:
            m = self._members.get(key)
            if m is None:
                m = self._members[key] = {"state": "", "first_seen": now}
            m["desc"] = desc
            m["last_seen"] = now
            if m["state"] != "up":
                edges.append((key[0], key[1], m["state"], "up"))
                m["state"] = "up"
                self._journal_append(key[0], key[1], edges[-1][2], "up")
        if edges:
            self._register_member_metrics(*key)
        addr = desc.get("metrics_addr")
        if addr and addr != self.metrics_addr:
            self._add_peer(addr)
        # feed the fault layer's edge namer: every descriptor teaches it
        # which fleet host owns which address (outside the fleet lock)
        from ..faults import net

        for k in ("metrics_addr", "kvx_addr"):
            net.map_addr(desc.get(k) or "", key[0])
        return edges

    def receive(self, desc: dict) -> dict:
        """Server side of /fleet/announce: fold the peer's descriptor
        in, answer with OUR descriptor plus the peer addresses we know
        (the gossip that converges seeded membership to a mesh)."""
        reply = self.self_descriptor()
        self._apply_edges(self._observe(desc))
        with self._lock:
            peers = list(self._peer_addrs)
        return {"member": reply, "peers": peers}

    def tick(self, now: Optional[float] = None) -> List[tuple]:
        """Failure detector: age every non-self member through
        up -> suspect -> dead off its last heartbeat. Returns the edges
        (also emitted on metrics/recorder) — tests assert on them."""
        t = self.clock() if now is None else now
        self_key = (self.identity["host"], self.identity["role"])
        edges: List[tuple] = []
        with self._lock:
            for key, m in self._members.items():
                if key == self_key or not m["state"]:
                    continue
                age = t - m["last_seen"]
                if age > self.cfg.dead_secs:
                    want = "dead"
                elif age > self.cfg.suspect_secs:
                    want = "suspect"
                else:
                    want = "up"
                # the detector only ever worsens a state; recovery is an
                # announce (fresh evidence), never the mere passing of time
                if (MEMBER_STATES.index(want)
                        > MEMBER_STATES.index(m["state"])):
                    edges.append((key[0], key[1], m["state"], want))
                    self._journal_append(key[0], key[1], m["state"], want)
                    m["state"] = want
        self._apply_edges(edges)
        return edges

    def _journal_append(self, host: str, role: str, frm: str,
                        to: str) -> None:
        # caller holds _lock
        self._journal.append({
            "host": host, "role": role, "from": frm, "to": to,
            "at": time.time(),
        })
        if len(self._journal) > _MAX_JOURNAL:
            del self._journal[:-_MAX_JOURNAL]

    def _apply_edges(self, edges: List[tuple]) -> None:
        """Emit metric + flight-recorder evidence for state edges —
        outside the fleet lock (no fleet->recorder/metrics lock edge)."""
        from . import flightrec, instruments

        for host, role, frm, to in edges:
            instruments.FLEET_MEMBER_UP.labels(host=host, role=role).set(
                1.0 if to == "up" else 0.0
            )
            instruments.FLEET_TRANSITIONS.labels(
                host=host, role=role, state=to
            ).inc()
            flightrec.RECORDER.model_event(
                "fleet", "fleet_member", host=host, role=role,
                frm=frm or "new", to=to,
            )
            log.info("fleet member %s/%s: %s -> %s", host, role,
                     frm or "new", to)

    def _add_peer(self, addr: str) -> None:
        added = False
        with self._lock:
            if addr not in self._peer_addrs and addr != self.metrics_addr:
                self._peer_addrs.append(addr)
                added = True
        if added:
            # pre-register the announce-failure child so the family
            # renders 0 for a healthy peer (absence-vs-zero discipline);
            # OUTSIDE the fleet lock — registration takes registry locks
            from . import instruments

            instruments.FLEET_ANNOUNCE_FAILURES.labels(peer=addr)

    # -- surfaces -------------------------------------------------------------

    def members(self) -> List[dict]:
        """Membership table rows (JSON-shaped; /fleet/members and
        fleetctl render this)."""
        now = self.clock()
        with self._lock:
            rows = [
                {
                    "host": key[0], "role": key[1], "state": m["state"],
                    "age_secs": round(now - m["last_seen"], 3),
                    "self": key == (self.identity["host"],
                                    self.identity["role"]),
                    **{
                        k: m.get("desc", {}).get(k)
                        for k in ("rank", "version", "metrics_addr",
                                  "kvx_addr", "pid", "seq", "pools",
                                  "gprefix", "capacity", "slo", "phase")
                    },
                }
                for key, m in sorted(self._members.items())
            ]
        # the quarantine overlay (fleet/breaker.py) — computed OUTSIDE
        # the fleet lock (no fleet->quarantine lock edge); orthogonal to
        # "state": a host can be "up" by heartbeat and still gray
        from ..fleet import breaker

        for r in rows:
            r["quarantined"] = (
                not r["self"] and breaker.BOARD.quarantined(r["host"])
            )
        return rows

    def journal(self) -> List[dict]:
        with self._lock:
            return list(self._journal)

    def health_summary(self) -> dict:
        """The /healthz fleet section: member counts by state + SLO
        rollup (worst-burn host, per-objective fleet attainment = the
        minimum any member reports)."""
        rows = self.members()
        counts = {s: 0 for s in MEMBER_STATES}
        worst: Optional[dict] = None
        attain: Dict[str, float] = {}
        for r in rows:
            if r["state"] in counts:
                counts[r["state"]] += 1
            slo = r.get("slo") or {}
            burn = slo.get("worst_burn")
            if burn is not None and (worst is None or burn > worst["burn"]):
                worst = {"host": r["host"], "burn": burn}
            for model_att in (slo.get("attainment") or {}).values():
                for obj, v in model_att.items():
                    attain[obj] = min(attain.get(obj, 1.0), v)
        out: dict = {"size": len(rows), **counts}
        if worst is not None:
            out["worst_burn"] = worst
        if attain:
            out["attainment"] = {k: round(v, 6)
                                 for k, v in sorted(attain.items())}
        return out

    # -- federation -----------------------------------------------------------

    def _scrape_targets(self) -> List[Tuple[str, str, str]]:
        """(host, role, metrics_addr) for every non-dead member with a
        known endpoint, self excluded (rendered locally)."""
        self_key = (self.identity["host"], self.identity["role"])
        with self._lock:
            return [
                (key[0], key[1], m["desc"]["metrics_addr"])
                for key, m in sorted(self._members.items())
                if key != self_key and m["state"] != "dead"
                and m.get("desc", {}).get("metrics_addr")
            ]

    def federate(self) -> str:
        """The /metrics/fleet body: our own registry plus every live
        peer's /metrics, host label injected. A failing scrape drops
        the host from this response and counts on
        aios_tpu_fleet_scrape_failures_total — absence IS the signal."""
        from ..fleet import breaker
        from . import instruments

        sources = [(self.identity["host"], self.registry.render())]
        for host, role, addr in self._scrape_targets():
            # scrapes double as the quarantine's half-open probes: an
            # open breaker skips the scrape (absence IS the signal), a
            # half-open one spends a probe slot on the real fetch — an
            # idle fleet heals through its own federation loop
            if not breaker.BOARD.allow(host):
                continue
            t0 = self.clock()
            try:
                sources.append((host, _http_text(
                    f"http://{addr}/metrics",
                    timeout=self.cfg.scrape_timeout,
                )))
                breaker.BOARD.record_ok(host, self.clock() - t0)
            except Exception as exc:  # noqa: BLE001 - a dead scrape is
                # evidence, not an error; the counter records it
                breaker.BOARD.record_failure(host, "unavailable")
                instruments.FLEET_SCRAPE_FAILURES.labels(
                    host=host, role=role
                ).inc()
                log.debug("fleet scrape of %s (%s) failed: %r",
                          host, addr, exc)
        return merge_expositions(sources)

    def federate_tsdb(self, query: Dict[str, List[str]]) -> dict:
        """The /debug/tsdb/fleet body: every live member answers the
        SAME parsed query against its own ring, keyed by host and
        annotated with role (the federate() discipline — breaker-gated
        scrapes, a failing host is an absent key plus a scrape-failure
        count, never a lost response)."""
        from ..fleet import breaker
        from . import instruments, tsdb as tsdb_mod

        local, _ = tsdb_mod.handle_query(query)
        hosts: Dict[str, dict] = {
            self.identity["host"]: dict(
                local, role=self.identity["role"]
            ),
        }
        qs = urllib.parse.urlencode(query, doseq=True)
        for host, role, addr in self._scrape_targets():
            if not breaker.BOARD.allow(host):
                continue
            t0 = self.clock()
            try:
                got = _http_json(
                    f"http://{addr}/debug/tsdb" + (f"?{qs}" if qs else ""),
                    timeout=self.cfg.scrape_timeout,
                )
                breaker.BOARD.record_ok(host, self.clock() - t0)
            except Exception as exc:  # noqa: BLE001 - an absent host IS
                # the signal; the counter records the failed range read
                breaker.BOARD.record_failure(host, "unavailable")
                instruments.FLEET_SCRAPE_FAILURES.labels(
                    host=host, role=role
                ).inc()
                log.debug("fleet tsdb fetch from %s failed: %r", host, exc)
                continue
            hosts[host] = dict(got, role=role)
        return {"hosts": hosts}

    # -- trace stitching ------------------------------------------------------

    def stitch(self, trace_id: str, limit: int = 64) -> dict:
        """One Chrome trace for ``trace_id`` across the fleet: local
        recorder timelines plus each live peer's, one lane group per
        host."""
        from . import flightrec

        host_tls: Dict[str, list] = {}
        local = [
            t.to_dict()
            for t in flightrec.RECORDER.recent(limit=limit * 4)
            if t.trace_id == trace_id
        ]
        if local:
            host_tls[self.identity["host"]] = local[:limit]
        from ..fleet import breaker

        for host, role, addr in self._scrape_targets():
            if not breaker.BOARD.allow(host):
                continue
            t0 = self.clock()
            try:
                got = _http_json(
                    f"http://{addr}/debug/requests?trace={trace_id}"
                    f"&limit={limit}",
                    timeout=self.cfg.scrape_timeout,
                )
                breaker.BOARD.record_ok(host, self.clock() - t0)
            except Exception as exc:  # noqa: BLE001 - a peer missing from
                # the stitch is visible as a missing lane; count it
                from . import instruments

                breaker.BOARD.record_failure(host, "unavailable")
                instruments.FLEET_SCRAPE_FAILURES.labels(
                    host=host, role=role
                ).inc()
                log.debug("fleet stitch fetch from %s failed: %r", host, exc)
                continue
            tls = got.get("requests", [])
            if tls:
                host_tls[host] = tls
        return stitch_chrome_traces(host_tls)

    # -- heartbeat loop -------------------------------------------------------

    def announce_once(self) -> None:
        """One announce round: POST our descriptor to every known peer,
        fold each response's member + gossip in, then run the failure
        detector. All network I/O outside the lock."""
        desc = self.self_descriptor()
        with self._lock:
            targets = list(self._peer_addrs)
        from . import instruments

        for addr in targets:
            try:
                reply = _http_json(
                    f"http://{addr}/fleet/announce", payload=desc,
                    timeout=self.cfg.scrape_timeout,
                )
            except Exception as exc:  # noqa: BLE001 - unreachable peers
                # age out through the state machine; the counter makes a
                # silently-failing edge visible BEFORE suspect/dead does
                instruments.FLEET_ANNOUNCE_FAILURES.labels(peer=addr).inc()
                log.debug("fleet announce to %s failed: %r", addr, exc)
                continue
            member = reply.get("member")
            if isinstance(member, dict):
                self._apply_edges(self._observe(member))
            for peer in reply.get("peers", ()):
                if isinstance(peer, str) and peer:
                    self._add_peer(peer)
        self.tick()

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="fleet-heartbeat", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        # seeded per-host jitter: N workers booted by one supervisor
        # would otherwise announce in lockstep forever, synchronizing
        # their scrape bursts; +/-25% desynchronizes them while staying
        # deterministic per host (no global-RNG draw on the hot loop)
        rng = random.Random(f"announce:{self.identity['host']}")
        while not self._stop.is_set():
            try:
                self.announce_once()
            except Exception:  # noqa: BLE001 - the heartbeat must outlive
                # any single bad round; the log carries the evidence
                log.exception("fleet heartbeat round failed")
            self._stop.wait(
                self.cfg.interval_secs * (0.75 + 0.5 * rng.random())
            )

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None


# -- process-wide instance ---------------------------------------------------

# The one registry obs/http.py routes and slo.annotate_health read;
# None until maybe_start() arms it (single-process deployments never do).
FLEET: Optional[FleetRegistry] = None


def maybe_start(service_name: str, bound_port: int,
                host: str = "127.0.0.1") -> Optional[FleetRegistry]:
    """Arm the fleet plane for this process when configured
    (AIOS_TPU_FLEET=1 or AIOS_TPU_FLEET_PEERS non-empty) — called by
    maybe_start_metrics_server with the service name and the ACTUAL
    bound port, so ephemeral-port processes announce a reachable
    endpoint. Idempotent; always stamps aios_tpu_process_info."""
    global FLEET
    ident = stamp_process_info(service_name)
    cfg = FleetConfig()
    if FLEET is not None or not cfg.active():
        return FLEET
    reach = "127.0.0.1" if host in ("", "0.0.0.0", "::") else host
    FLEET = FleetRegistry(ident, f"{reach}:{bound_port}", cfg=cfg)
    FLEET.start()
    log.info(
        "fleet telemetry armed: host=%s role=%s metrics_addr=%s peers=%s",
        ident["host"], ident["role"], FLEET.metrics_addr,
        ",".join(cfg.seed_peers()) or "(none yet)",
    )
    return FLEET


def install(reg: Optional[FleetRegistry]) -> Optional[FleetRegistry]:
    """Swap the process-wide registry (tests); returns the previous."""
    global FLEET
    prev, FLEET = FLEET, reg
    return prev
