"""The autonomy loop: tick-driven task execution with a fallback ladder.

Reference parity (agent-core/src/autonomy.rs — the densest component, SURVEY
section 2e). Semantics preserved:

  * tick every 500 ms (AutonomyConfig, autonomy.rs:22-36): decompose pending
    goals -> take <=3 unblocked tasks -> dispatch each through the ladder
    agent-route -> cluster spillover -> heuristic direct-execute -> AI
    reasoning loop (autonomy_tick:331-691);
  * the reasoning loop is multi-round observe->think->act with per-level
    caps: max rounds 1/1/3/5 and token budgets 2048/2048/8192/16384 for
    reactive/operational/tactical/strategic (596-607); the model signals
    completion with {"done": true} (279-286); a malformed-JSON reply gets
    one self-correction round (290-328); each round's prompt embeds prior
    tool results truncated at 1000 chars (230-276);
  * AI backend chain: api-gateway (preferred provider qwen3, 544-546) then
    the local runtime as fallback;
  * prompts include the live tool catalog fetched over gRPC with a static
    fallback list (988-1055), memory context chunks (848-880), the goal's
    conversation history (884-900), a self-evolution instruction to
    plugin.create missing tools (906-910), and a strict JSON tool_calls
    format spec (912-927);
  * heuristic executor bypasses AI entirely for cpu/memory/disk/ping/dns/
    fs-read/service-status/email and explicit tool_calls in the task input
    (1149-1248);
  * result recording: zero tool calls -> awaiting_input with a question to
    the user, max 3 assistant messages then fail (2431-2480); ANY failed
    tool call fails the task (2488-2528); parallel dispatch capped at 3
    concurrent AI tasks (Semaphore(3), 376,632);
  * housekeeping: requeue tasks from dead agents, detect goal completion
    (695-733).

Lock discipline mirrors the reference: shared state is touched only for
selection/recording; inference and tool execution run unlocked
(autonomy.rs:335,588-590,619).
"""

from __future__ import annotations

import json
import logging
import re
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from .agent_router import AgentRouter
from .cluster import ClusterManager, RemoteExecutor, cluster_enabled
from .goal_engine import GoalEngine, Task
from .task_planner import (
    OPERATIONAL,
    REACTIVE,
    STRATEGIC,
    TACTICAL,
    TaskPlanner,
    extract_json_array,
    strip_think_tags,
)
from .telemetry import Decision, DecisionLogger, ResultAggregator, TaskOutcome

log = logging.getLogger("aios.autonomy")

MAX_ROUNDS = {REACTIVE: 1, OPERATIONAL: 1, TACTICAL: 3, STRATEGIC: 5}
TOKEN_BUDGETS = {REACTIVE: 2048, OPERATIONAL: 2048, TACTICAL: 8192,
                 STRATEGIC: 16384}


class InferenceCancelled(Exception):
    """An in-flight AI inference was aborted on purpose (its goal was
    cancelled) — not a backend failure: no fallback, no task failure."""


def _call_with_budget(
    backend, prompt: str, level: str, budget: int, json_schema: str = "",
    cancel_event=None,
) -> str:
    """Invoke an infer backend, passing the token budget when it takes one
    and the structured-output schema / cancel event when accepted.

    Production closures (orchestrator/main.py) have signature
    (prompt, level, max_tokens, json_schema="", cancel_event=None);
    two-arg callables are grandfathered so injected fakes keep working.
    """
    import inspect

    takes_schema = False
    takes_cancel = False
    try:
        sig = inspect.signature(backend)
        params = sig.parameters.values()
        # json_schema/cancel_event are always passed BY KEYWORD, so they
        # must not count toward the positional-budget slot (a backend like
        # f(prompt, level, json_schema="") takes no budget)
        positional = [
            p for p in params
            if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
            and p.name not in ("json_schema", "cancel_event")
        ]
        takes_budget = len(positional) >= 3 or any(
            p.kind is p.VAR_POSITIONAL for p in params
        )
        var_kw = any(p.kind is p.VAR_KEYWORD for p in params)
        takes_schema = "json_schema" in sig.parameters or var_kw
        takes_cancel = "cancel_event" in sig.parameters or var_kw
    except (TypeError, ValueError):
        takes_budget = True
    kw = {}
    if json_schema and takes_schema:
        kw["json_schema"] = json_schema
    if cancel_event is not None and takes_cancel:
        kw["cancel_event"] = cancel_event
    if takes_budget:
        return backend(prompt, level, budget, **kw)
    return backend(prompt, level, **kw)
TOOL_RESULT_TRUNCATE = 1000
MAX_AI_MESSAGES = 3  # awaiting_input cap (autonomy.rs:2431-2480)
MAX_PARALLEL_AI = 3

STATIC_TOOL_CATALOG = [
    "fs.read", "fs.write", "fs.list", "fs.search", "fs.disk_usage",
    "process.list", "process.info", "service.status", "service.restart",
    "net.ping", "net.dns", "net.interfaces", "monitor.cpu", "monitor.memory",
    "monitor.disk", "monitor.logs", "sec.scan", "pkg.search", "web.http_request",
    "plugin.create", "email.send",
]

TOOL_CALL_FORMAT = """\
Respond with ONLY a JSON object in this exact format:
{"thought": "short reasoning",
 "tool_calls": [{"tool": "namespace.name", "args": {...}}],
 "done": false}
Set "done": true with empty tool_calls when the task is complete, and put
your final answer in "thought". If no listed tool fits, you may create one
with {"tool": "plugin.create", "args": {"name": "...", "code": "def main(input_data): ..."}}.
"""


def guided_toolcalls() -> bool:
    """AIOS_TPU_GUIDED_TOOLCALLS=1: reasoning-round replies are
    grammar-guided to the tool_calls shape (tool names constrained to the
    live catalog) via the gateway/runtime json_schema field — the first
    round parses by construction instead of relying on the JSON-repair
    round. Opt-in: the reference has no equivalent (it re-prompts,
    autonomy.rs:290-328), and cloud providers ignore the schema."""
    import os

    return os.environ.get("AIOS_TPU_GUIDED_TOOLCALLS", "").lower() in (
        "1", "true", "on",
    )


def _enum_safe(name: str) -> bool:
    """The engine's schema compiler rejects enum values needing JSON string
    escapes (jsonschema._check_enum_value); a single unsafe tool name must
    not poison every guided reasoning call."""
    return bool(name) and '"' not in name and "\\" not in name and all(
        ord(c) >= 0x20 for c in name
    )


def toolcalls_schema(catalog: List[str]) -> dict:
    """The reasoning-reply schema (engine/jsonschema.py subset): thought,
    tool_calls with catalog-enum tool names + free-form args, done.
    Unsafe names are dropped from the enum; if none survive, the tool
    field degrades to a free string (still shape-guided, not name-guided).
    """
    safe = [t for t in catalog if _enum_safe(t)]
    if len(safe) < len(catalog):
        log.warning(
            "guided tool_calls: %d catalog names unsafe for the enum",
            len(catalog) - len(safe),
        )
    tool_node = (
        {"type": "string", "enum": safe} if safe else {"type": "string"}
    )
    return {
        "type": "object",
        "properties": {
            "thought": {"type": "string"},
            "tool_calls": {
                "type": "array",
                "items": {
                    "type": "object",
                    "properties": {
                        "tool": tool_node,
                        "args": {"type": "object"},
                    },
                    "required": ["tool"],
                },
            },
            "done": {"type": "boolean"},
        },
        "required": ["done"],
    }


@dataclass
class AutonomyConfig:
    tick_interval: float = 0.5
    max_tasks_per_tick: int = 3
    max_parallel_ai: int = MAX_PARALLEL_AI
    preferred_provider: str = "qwen3"  # autonomy.rs:544-546


# ---------------------------------------------------------------------------
# Tool-call parsing (autonomy.rs parse_tool_calls:1538, extract_json:1711,
# natural-language fallback:1973)
# ---------------------------------------------------------------------------


def extract_json_object(text: str) -> Optional[dict]:
    text = strip_think_tags(text)
    candidates = [text.strip()]
    fence = re.search(r"```(?:json)?\s*(.*?)```", text, flags=re.S)
    if fence:
        candidates.insert(0, fence.group(1).strip())
    brace = re.search(r"\{.*\}", text, flags=re.S)
    if brace:
        candidates.append(brace.group(0))
    for cand in candidates:
        try:
            parsed = json.loads(cand)
            if isinstance(parsed, dict):
                return parsed
        except ValueError:
            continue
    return None


def parse_tool_calls(text: str) -> Tuple[List[dict], bool, str]:
    """-> (tool_calls, done, thought). Tolerates several reply shapes."""
    obj = extract_json_object(text)
    # only treat it as a structured reply if it has reply-shaped keys —
    # otherwise fall through (incidental braces in prose must not short-
    # circuit the natural-language fallback)
    if obj is not None and not (
        obj.keys() & {"tool_calls", "calls", "done", "thought", "answer"}
    ):
        obj = None
    if obj is not None:
        raw_calls = obj.get("tool_calls") or obj.get("calls") or []
        calls = []
        for c in raw_calls:
            if isinstance(c, dict) and (c.get("tool") or c.get("name")):
                calls.append(
                    {
                        "tool": c.get("tool") or c.get("name"),
                        "args": c.get("args") or c.get("input") or {},
                    }
                )
        done = bool(obj.get("done"))
        thought = str(obj.get("thought") or obj.get("answer") or "")
        return calls, done, thought

    arr = extract_json_array(text)
    if arr:
        calls = [
            {"tool": c.get("tool") or c.get("name"),
             "args": c.get("args") or c.get("input") or {}}
            for c in arr
            if isinstance(c, dict) and (c.get("tool") or c.get("name"))
        ]
        if calls:
            return calls, False, ""

    # natural-language fallback: `namespace.name({...})` or `use X`
    nl_calls = []
    for m in re.finditer(r"\b([a-z]+\.[a-z_.]+)\s*\(\s*(\{.*?\})?\s*\)", text):
        args = {}
        if m.group(2):
            try:
                args = json.loads(m.group(2))
            except ValueError:
                pass
        nl_calls.append({"tool": m.group(1), "args": args})
    return nl_calls, False, ""


# ---------------------------------------------------------------------------
# Heuristic direct execution (autonomy.rs try_heuristic_execution:1149-1248)
# ---------------------------------------------------------------------------


def heuristic_tool_calls(task: Task) -> Optional[List[dict]]:
    """Direct tool mapping for trivial requests; None -> needs AI."""
    if isinstance(task.input, dict) and task.input.get("tool_calls"):
        return [
            {"tool": c.get("tool"), "args": c.get("args", {})}
            for c in task.input["tool_calls"]
            if isinstance(c, dict) and c.get("tool")
        ]
    low = task.description.lower()
    if "cpu" in low and ("check" in low or "usage" in low or "load" in low):
        return [{"tool": "monitor.cpu", "args": {}}]
    if "memory" in low and ("check" in low or "usage" in low):
        return [{"tool": "monitor.memory", "args": {}}]
    if ("disk" in low and ("usage" in low or "space" in low or "check" in low)):
        return [{"tool": "monitor.disk", "args": {}}]
    m = re.search(r"\bping\s+([a-zA-Z0-9_.:-]+)", low)
    if m:
        return [{"tool": "net.ping", "args": {"host": m.group(1)}}]
    m = re.search(r"\b(?:dns|resolve)\s+(?:for\s+)?([a-zA-Z0-9_.-]+\.[a-z]{2,})", low)
    if m:
        return [{"tool": "net.dns", "args": {"host": m.group(1)}}]
    m = re.search(r"\bread\s+(?:the\s+)?file\s+(\S+)", task.description,
                  flags=re.I)
    if m:
        return [{"tool": "fs.read", "args": {"path": m.group(1).strip("'\"`")}}]
    m = re.search(r"\bstatus\s+of\s+(?:service\s+)?([a-zA-Z0-9_.@-]+)", low)
    if m and "service" in low:
        return [{"tool": "service.status", "args": {"name": m.group(1)}}]
    if "send" in low and "email" in low and task.input.get("to"):
        return [{"tool": "email.send", "args": dict(task.input)}]
    return None


# ---------------------------------------------------------------------------
# The loop
# ---------------------------------------------------------------------------


class AutonomyLoop:
    def __init__(
        self,
        engine: GoalEngine,
        planner: TaskPlanner,
        router: AgentRouter,
        execute_tool: Callable[[str, str, dict], dict],
        gateway_infer: Optional[Callable[..., str]] = None,
        runtime_infer: Optional[Callable[..., str]] = None,
        memory_context: Optional[Callable[[str, int], str]] = None,
        tool_catalog: Optional[Callable[[], List[str]]] = None,
        aggregator: Optional[ResultAggregator] = None,
        decisions: Optional[DecisionLogger] = None,
        cluster: Optional[ClusterManager] = None,
        remote: Optional[RemoteExecutor] = None,
        config: Optional[AutonomyConfig] = None,
    ):
        """Dependencies are injected as callables so the loop is fully
        testable without sockets:
          execute_tool(tool_name, agent_id, args) -> {"success", "output",
          "error"}; gateway/runtime_infer(prompt, level) -> text.
        """
        self.engine = engine
        self.planner = planner
        self.router = router
        self.execute_tool = execute_tool
        self.gateway_infer = gateway_infer
        self.runtime_infer = runtime_infer
        self.memory_context = memory_context
        self.tool_catalog = tool_catalog
        self.aggregator = aggregator or ResultAggregator()
        self.decisions = decisions or DecisionLogger()
        self.cluster = cluster
        self.remote = remote or RemoteExecutor()
        self.config = config or AutonomyConfig()
        self._ai_semaphore = threading.Semaphore(self.config.max_parallel_ai)
        self._pool = ThreadPoolExecutor(
            max_workers=self.config.max_parallel_ai * 2,
            thread_name_prefix="autonomy",
        )
        self._in_flight: set = set()
        # task_id -> (goal_id, Event): in-flight AI inferences abortable
        # by CancelGoal (notify_goal_cancelled); registered per reasoning
        # task for its loop's duration
        self._cancel_watch: Dict[str, Tuple[str, threading.Event]] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.ticks = 0

    # -- tick ---------------------------------------------------------------

    def tick(self) -> int:
        """One autonomy tick; returns the number of tasks dispatched."""
        self.ticks += 1
        # 1. decompose pending goals
        for goal in self.engine.list_goals(status_filter="pending"):
            self.engine.set_goal_status(goal.id, "planning")
            try:
                tasks = self.planner.decompose_goal(goal)
                self.engine.add_tasks(goal.id, tasks)
            except Exception as exc:  # noqa: BLE001
                log.error("decomposition failed for %s: %s", goal.id, exc)
                self.engine.set_goal_status(goal.id, "failed")

        # 2. pick unblocked tasks and dispatch through the ladder
        dispatched = 0
        for task in self.engine.unblocked_pending_tasks(
            limit=self.config.max_tasks_per_tick
        ):
            with self._lock:
                if task.id in self._in_flight:
                    continue
                self._in_flight.add(task.id)
            self._dispatch(task)
            dispatched += 1

        # 3. housekeeping
        self.run_housekeeping()
        return dispatched

    def _dispatch(self, task: Task) -> None:
        # ladder step 1: a live registered agent that covers the namespaces
        agent_id = self.router.route_task(task)
        if agent_id is not None:
            self.engine.set_task_status(task.id, "assigned", agent=agent_id)
            self.decisions.log(Decision(
                context=f"dispatch {task.description[:60]}",
                options=["agent", "cluster", "heuristic", "ai"],
                chosen=f"agent:{agent_id}",
                reasoning="capable live agent available",
            ))
            with self._lock:
                self._in_flight.discard(task.id)
            return

        # ladder step 2: cluster spillover
        if cluster_enabled() and self.cluster is not None:
            node = self.cluster.least_loaded()
            if node is not None:
                try:
                    self.remote.submit_remote_goal(
                        node.address, task.description,
                    )
                    self.engine.complete_task(
                        task.id, output={"delegated_to_node": node.node_id}
                    )
                    self.decisions.log(Decision(
                        context=f"dispatch {task.description[:60]}",
                        options=["cluster", "heuristic", "ai"],
                        chosen=f"cluster:{node.node_id}",
                        reasoning="no local agent; least-loaded node",
                    ))
                    with self._lock:
                        self._in_flight.discard(task.id)
                    return
                except Exception as exc:  # noqa: BLE001
                    log.warning("cluster spillover failed: %s", exc)

        # ladder step 3: heuristics (no AI)
        calls = heuristic_tool_calls(task)
        if calls is not None:
            self.engine.set_task_status(task.id, "in_progress")
            self._pool.submit(self._run_heuristic, task, calls)
            return

        # ladder step 4: AI reasoning loop
        self.engine.set_task_status(task.id, "in_progress")
        self._pool.submit(self._run_reasoning_guarded, task)

    # -- heuristic path -----------------------------------------------------

    def _run_heuristic(self, task: Task, calls: List[dict]) -> None:
        try:
            results, any_failure = self._execute_calls(task, calls)
            if any_failure:
                error = "; ".join(
                    r.get("error", "") for r in results if not r.get("success")
                )
                self._record_failure(task, f"heuristic tool failure: {error}")
            else:
                self._record_success(task, {"tool_results": results},
                                     model="heuristic")
        finally:
            with self._lock:
                self._in_flight.discard(task.id)

    # -- AI reasoning loop --------------------------------------------------

    def _run_reasoning_guarded(self, task: Task) -> None:
        with self._ai_semaphore:  # Semaphore(3), autonomy.rs:376,632
            try:
                self.run_reasoning_loop(task)
            except Exception as exc:  # noqa: BLE001
                log.exception("reasoning loop crashed for %s", task.id)
                self._record_failure(task, f"reasoning loop error: {exc}")
            finally:
                with self._lock:
                    self._in_flight.discard(task.id)

    def _ai_infer(
        self, prompt: str, level: str, json_schema: str = "",
        cancel_event=None,
    ) -> Optional[str]:
        """gateway (preferred qwen3) -> runtime fallback chain.

        Every call carries the per-level reasoning token budget
        (TOKEN_BUDGETS; autonomy.rs:596-607 enforces 2048/2048/8192/16384
        max_tokens by level) — backends forward it as the InferRequest /
        ApiInferRequest max_tokens field. Two-arg backends (legacy tests,
        simple fakes) are still accepted. ``cancel_event`` aborts an
        in-flight inference when its goal is cancelled (no fallback then —
        a deliberate abort is not a backend failure).
        """
        budget = TOKEN_BUDGETS.get(level, TOKEN_BUDGETS[OPERATIONAL])
        for backend in (self.gateway_infer, self.runtime_infer):
            if backend is None:
                continue
            try:
                return _call_with_budget(
                    backend, prompt, level, budget, json_schema,
                    cancel_event=cancel_event,
                )
            except InferenceCancelled:
                return None
            except Exception as exc:  # noqa: BLE001
                log.warning("AI backend failed: %s", exc)
                continue
        return None

    def _catalog(self) -> List[str]:
        if self.tool_catalog is not None:
            try:
                catalog = self.tool_catalog()
                if catalog:
                    return catalog
            except Exception:  # noqa: BLE001
                pass
        return STATIC_TOOL_CATALOG  # autonomy.rs:1039-1055

    def _build_prompt(self, task: Task, round_results: List[dict],
                      round_idx: int, catalog: Optional[List[str]] = None) -> str:
        parts = [
            "You are the aiOS autonomy loop executing a system task.",
            f"Task: {task.description}",
            f"Intelligence level: {task.intelligence_level}",
        ]
        if self.memory_context is not None:
            try:
                ctx = self.memory_context(task.description, 512)
                if ctx:
                    parts.append(f"Relevant memory:\n{ctx}")
            except Exception:  # noqa: BLE001
                pass
        history = self.engine.messages_for_goal(task.goal_id, limit=6)
        if history:
            rendered = "\n".join(f"{m.role}: {m.content[:300]}" for m in history)
            parts.append(f"Conversation so far:\n{rendered}")
        parts.append(
            "Available tools: " + ", ".join(catalog or self._catalog())
        )
        if round_results:
            rendered = json.dumps(round_results)[:TOOL_RESULT_TRUNCATE * 3]
            parts.append(
                "Results of your previous tool calls (truncated):\n" + rendered
            )
            parts.append(
                'Continue the task, or finish with {"done": true, "thought": "<final answer>"}.'
            )
        parts.append(TOOL_CALL_FORMAT)
        return "\n\n".join(parts)

    def notify_goal_cancelled(self, goal_id: str) -> None:
        """CancelGoal hook: abort any IN-FLIGHT AI inference working for
        the dead goal right now (the between-rounds is_abandoned check
        only stops FUTURE rounds; this stops the current one)."""
        with self._lock:
            events = [
                ev for gid, ev in self._cancel_watch.values()
                if gid == goal_id
            ]
        for ev in events:
            ev.set()

    def run_reasoning_loop(self, task: Task) -> None:
        """Multi-round observe->think->act (autonomy.rs:100-224)."""
        cancel_event = threading.Event()
        with self._lock:
            self._cancel_watch[task.id] = (task.goal_id, cancel_event)
        try:
            self._run_reasoning_rounds(task, cancel_event)
        finally:
            with self._lock:
                self._cancel_watch.pop(task.id, None)

    def _run_reasoning_rounds(
        self, task: Task, cancel_event: threading.Event
    ) -> None:
        level = task.intelligence_level or OPERATIONAL
        max_rounds = MAX_ROUNDS.get(level, 1)
        all_results: List[dict] = []
        made_any_call = False
        final_thought = ""

        guided = guided_toolcalls()
        for round_idx in range(max_rounds):
            if self.engine.is_abandoned(task.id, task.goal_id):
                # the goal was cancelled (or the task externally
                # terminated) between rounds: stop burning AI tokens and
                # executing tools for a dead goal — a strategic task would
                # otherwise run up to 5 more rounds against its 16k budget
                log.info(
                    "reasoning loop for task %s stops: goal %s is "
                    "cancelled/terminal", task.id, task.goal_id,
                )
                return
            # ONE catalog fetch per round, shared by the schema enum and
            # the prompt's tool list (plugin.create can add tools
            # mid-loop; the enum must match what the prompt advertises)
            catalog = self._catalog()
            schema_json = (
                json.dumps(toolcalls_schema(catalog)) if guided else ""
            )
            prompt = self._build_prompt(task, all_results, round_idx, catalog)
            reply = self._ai_infer(prompt, level, schema_json,
                                   cancel_event=cancel_event)
            if reply is None:
                if self.engine.is_abandoned(task.id, task.goal_id):
                    # the in-flight inference was ABORTED by CancelGoal
                    # (notify_goal_cancelled), not a backend failure
                    return
                self._record_failure(task, "no AI backend available")
                return

            calls, done, thought = parse_tool_calls(reply)
            if not calls and not done and thought == "":
                # malformed reply: one JSON self-correction round
                # (autonomy.rs:290-328)
                correction = (
                    "Your previous reply was not valid JSON.\n"
                    f"Previous reply:\n{reply[:800]}\n\n" + TOOL_CALL_FORMAT
                )
                reply = self._ai_infer(correction, level, schema_json,
                                       cancel_event=cancel_event)
                if reply is None:
                    if self.engine.is_abandoned(task.id, task.goal_id):
                        return
                    self._record_failure(task, "no AI backend available")
                    return
                calls, done, thought = parse_tool_calls(reply)

            if thought:
                final_thought = thought

            if cancel_event.is_set():
                # the cancel raced the reply's arrival (result landed in
                # the same poll window): do NOT execute this round's tool
                # calls — they may side-effect (fs.write, email, plugins)
                # for a goal the user just killed
                return

            if calls:
                made_any_call = True
                results, any_failure = self._execute_calls(task, calls)
                all_results.extend(results)
                if any_failure:
                    # ANY tool failure fails the task (autonomy.rs:2488-2528)
                    error = "; ".join(
                        r.get("error", "") for r in results if not r.get("success")
                    )
                    self._record_failure(task, f"tool call failed: {error}")
                    return

            if done:
                break

        if not made_any_call:
            # zero tool calls across all rounds -> awaiting input
            self._record_awaiting_input(task, final_thought)
            return

        self._record_success(
            task,
            {"tool_results": all_results[-10:], "answer": final_thought},
            model="ai",
        )

    def _execute_calls(
        self, task: Task, calls: List[dict]
    ) -> Tuple[List[dict], bool]:
        results = []
        any_failure = False
        for call in calls[:10]:
            tool = call.get("tool", "")
            args = call.get("args", {}) or {}
            try:
                res = self.execute_tool(tool, "autonomy-loop", args)
            except Exception as exc:  # noqa: BLE001
                res = {"success": False, "output": {}, "error": str(exc)}
            ok = bool(res.get("success"))
            any_failure = any_failure or not ok
            out = json.dumps(res.get("output", {}))[:TOOL_RESULT_TRUNCATE]
            results.append(
                {"tool": tool, "success": ok, "output": out,
                 "error": res.get("error", "")}
            )
        return results, any_failure

    # -- result recording (autonomy.rs record_ai_result:2380-2583) ----------

    def _record_success(self, task: Task, output: dict, model: str) -> None:
        self.engine.complete_task(task.id, output=output)
        self.engine.add_message(
            task.goal_id, "assistant",
            str(output.get("answer") or f"completed: {task.description[:80]}"),
        )
        self.aggregator.record(
            task.goal_id,
            TaskOutcome(task_id=task.id, success=True, output=output,
                        model_used=model),
        )
        self.engine.check_goal_completion(task.goal_id)

    def _record_failure(self, task: Task, error: str) -> None:
        self.engine.set_task_status(task.id, "failed", error=error)
        self.aggregator.record(
            task.goal_id,
            TaskOutcome(task_id=task.id, success=False, error=error),
        )
        self.engine.check_goal_completion(task.goal_id)

    def _record_awaiting_input(self, task: Task, question: str) -> None:
        """Zero tool calls -> ask the user; 3 strikes then fail."""
        n_assistant = self.engine.count_messages(task.goal_id, role="assistant")
        if n_assistant >= MAX_AI_MESSAGES:
            self._record_failure(
                task, "no actionable tool calls after repeated attempts"
            )
            return
        self.engine.add_message(
            task.goal_id, "assistant",
            question or f"Need more information to proceed with: {task.description}",
        )
        self.engine.set_metadata(task.goal_id, "awaiting_input", True)
        self.engine.set_task_status(task.id, "pending")  # retried after reply

    # -- housekeeping (autonomy.rs:695-733) ---------------------------------

    def run_housekeeping(self) -> None:
        for agent in self.router.dead_agents():
            for task in self.router.requeue_from(agent.agent_id):
                self.engine.set_task_status(task.id, "pending")
                log.info("requeued task %s from dead agent %s", task.id,
                         agent.agent_id)
        # tasks assigned to agents that died mid-flight
        for task in list(self.engine.tasks.values()):
            if task.status == "assigned" and task.assigned_agent:
                agent = self.router.get(task.assigned_agent)
                if agent is None or not agent.alive:
                    self.engine.set_task_status(task.id, "pending")
        for goal in self.engine.active_goals():
            self.engine.check_goal_completion(goal.id)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        def loop():
            while not self._stop.wait(self.config.tick_interval):
                try:
                    self.tick()
                except Exception:  # noqa: BLE001
                    log.exception("autonomy tick failed")

        self._thread = threading.Thread(target=loop, name="autonomy-loop",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        self._pool.shutdown(wait=False)
