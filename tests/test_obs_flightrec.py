"""Flight recorder + SLO engine (ISSUE 8).

Four layers under test:
  * e2e: a request served through the LIVE gRPC surface yields one
    complete ordered timeline (route -> admit -> queue -> prefill ->
    decode -> retire) retrievable from ``/debug/trace`` as valid Chrome
    trace-event JSON, with shed and abort paths recorded too;
  * recorder mechanics: ring bound, disable switch, span folding,
    anomaly snapshots (abort / shed spike) with cooldown;
  * SLO window math: attainment / burn rate / breach edges / window
    pruning with injected clocks;
  * the PR 6/7 invariant extended to observability: with the recorder
    ON, compile counters stay flat after warmup and dispatch counts are
    identical to recorder OFF (host-side-only instrumentation).
"""

import json
import time
import urllib.request

import grpc
import jax
import jax.numpy as jnp
import pytest

from aios_tpu import rpc, services
from aios_tpu.engine import model as M
from aios_tpu.engine.batching import ContinuousBatcher, Request
from aios_tpu.engine.config import TINY_TEST
from aios_tpu.engine.engine import TPUEngine
from aios_tpu.obs import flightrec, slo
from aios_tpu.obs.flightrec import FlightRecorder, Timeline
from aios_tpu.obs.http import start_metrics_server
from aios_tpu.obs.slo import SLOConfig, SLOEngine
from aios_tpu.proto_gen import runtime_pb2
from aios_tpu.runtime.model_manager import ModelManager
from aios_tpu.runtime.service import serve

MODEL = "flight-test"


# ---------------------------------------------------------------------------
# live gRPC surface (the acceptance-criteria path)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def flight_server():
    """Tiny pool behind a live gRPC server + the obs HTTP endpoint."""
    mp = pytest.MonkeyPatch()
    mp.setenv("AIOS_TPU_PAGED_KV", "auto")
    manager = ModelManager(num_slots=2, warm_compile=False)
    manager.load_model(MODEL, "synthetic://tiny-test", context_length=256)
    server, service, port = serve(
        address="127.0.0.1:0", manager=manager, block=False, metrics_port=0
    )
    channel = rpc.insecure_channel(f"127.0.0.1:{port}")
    yield services.AIRuntimeStub(channel), manager, service
    channel.close()
    server.stop(grace=None)
    if service.metrics_server is not None:
        service.metrics_server.shutdown()
    manager.unload_model(MODEL)
    mp.undo()


def _timeline_for(request_id, model=MODEL, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for tl in flightrec.RECORDER.recent(model=model, limit=256):
            if tl.request_id == request_id:
                return tl
        time.sleep(0.02)
    raise AssertionError(f"no timeline for {request_id!r}")


def test_e2e_timeline_through_live_grpc(flight_server):
    """One Infer through the live socket -> one complete ordered
    timeline: route -> admit -> queue -> prefill -> decode -> retire,
    with summary fields filled and the RPC trace id attached."""
    stub, _, _ = flight_server
    resp = stub.Infer(runtime_pb2.InferRequest(
        prompt="flight recorder check", max_tokens=8, temperature=0.0,
        requesting_agent="flight-agent", task_id="flight-e2e-1",
    ))
    assert resp.model_used == MODEL
    tl = _timeline_for("flight-e2e-1")
    assert tl.state == "retired"
    assert tl.tenant == "flight-agent"
    assert tl.trace_id, "timeline must carry the RPC's trace id"
    assert tl.tokens_out > 0
    assert tl.ttft_ms > 0
    assert tl.prompt_tokens > 0
    kinds = [k for _, k, _ in tl.events]
    # ordering: first occurrence of each lifecycle stage is monotonic
    order = ["route", "admit", "queue", "prefill", "decode", "retire"]
    positions = [kinds.index(k) for k in order]
    assert positions == sorted(positions), (order, kinds)
    assert kinds.count("retire") == 1
    # per-dispatch decode ticks carry occupancy + step count
    decode = [f for _, k, f in tl.events if k == "decode"]
    assert decode and all("n" in f and "occ" in f for f in decode)


def test_spans_fold_into_timeline(flight_server):
    """The previously-dormant tracing exporter feeds finished spans into
    the timeline sharing their trace id (the runtime.decode span at
    minimum — the RPC server span may close after the client returns)."""
    stub, _, _ = flight_server
    stub.Infer(runtime_pb2.InferRequest(
        prompt="span folding", max_tokens=4, temperature=0.0,
        task_id="flight-span-1",
    ))
    tl = _timeline_for("flight-span-1")
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        spans = [f for _, k, f in tl.events if k == "span"]
        if any(s.get("name") == "runtime.decode" for s in spans):
            return
        time.sleep(0.02)
    raise AssertionError(
        f"no runtime.decode span folded in: {[e for e in tl.events]}"
    )


def test_debug_routes_serve_trace_and_requests(flight_server):
    """/debug/trace parses as Chrome trace-event JSON containing the
    served request; /debug/requests and /debug/spans answer too."""
    stub, _, service = flight_server
    stub.Infer(runtime_pb2.InferRequest(
        prompt="debug route check", max_tokens=4, temperature=0.0,
        task_id="flight-debug-1",
    ))
    _timeline_for("flight-debug-1")
    base = f"http://127.0.0.1:{service.metrics_port}"

    trace = json.loads(urllib.request.urlopen(
        f"{base}/debug/trace?model={MODEL}", timeout=5).read().decode())
    assert isinstance(trace["traceEvents"], list) and trace["traceEvents"]
    for ev in trace["traceEvents"]:
        assert {"ph", "pid", "tid", "name"} <= set(ev)
        if ev["ph"] in ("X", "i"):
            assert "ts" in ev
    names = {e["name"] for e in trace["traceEvents"]}
    assert "request[retired]" in names
    tids = {
        e["tid"] for e in trace["traceEvents"]
        if e.get("cat") == "request"
        and e["args"].get("request_id") == "flight-debug-1"
    }
    assert tids, "served request missing from /debug/trace"

    reqs = json.loads(urllib.request.urlopen(
        f"{base}/debug/requests?model={MODEL}", timeout=5
    ).read().decode())
    assert any(
        r["request_id"] == "flight-debug-1" for r in reqs["requests"]
    )

    spans = json.loads(urllib.request.urlopen(
        f"{base}/debug/spans?name=runtime", timeout=5).read().decode())
    assert spans["spans"], "finished-span ring unreadable"

    slo_view = json.loads(urllib.request.urlopen(
        f"{base}/debug/slo", timeout=5).read().decode())
    assert MODEL in slo_view["models"]
    assert set(slo_view["models"][MODEL]["objectives"]) == set(
        slo.OBJECTIVES
    )

    # an aged-out / unknown snapshot id is a 404, not a 200-with-error
    # body a `curl -f` runbook script would archive as a capture
    with pytest.raises(urllib.error.HTTPError) as err:
        urllib.request.urlopen(
            f"{base}/debug/trace?snapshot=99999", timeout=5
        )
    assert err.value.code == 404


def test_shed_path_records_timeline(flight_server):
    """A request shed at the front door finishes as state=shed with the
    closed-enum cause + retry-after recorded."""
    _, manager, _ = flight_server
    pool = manager.models[MODEL].pool
    shed_before = flightrec.RECORDER.recent(model=MODEL, limit=256)
    pool._draining = True
    try:
        with pytest.raises(Exception) as err:
            pool.submit(
                Request(prompt_ids=[5, 6, 7], max_tokens=4,
                        temperature=0.0, request_id="flight-shed-1"),
                tenant="shed-tenant",
            )
        assert getattr(err.value, "cause", "") == "draining"
    finally:
        pool._draining = False
    tl = _timeline_for("flight-shed-1")
    assert tl.state == "shed"
    assert tl.shed_cause == "draining"
    assert tl.retry_after_ms > 0
    assert tl.tenant == "shed-tenant"
    kinds = [k for _, k, _ in tl.events]
    assert "shed" in kinds and "retire" not in kinds
    assert len(flightrec.RECORDER.recent(model=MODEL, limit=256)) == \
        len(shed_before) + 1


# ---------------------------------------------------------------------------
# abort path + anomaly snapshots (direct batcher — no pool needed)
# ---------------------------------------------------------------------------


def test_abort_records_closed_cause_and_snapshots():
    """A shutdown mid-request aborts its stream: the timeline finishes
    aborted with the normalized closed-enum cause, and the abort freezes
    an anomaly snapshot holding the evidence."""
    params = M.init_params(TINY_TEST, jax.random.PRNGKey(0),
                           dtype=jnp.float32)
    eng = TPUEngine(TINY_TEST, params, num_slots=2, max_context=128,
                    cache_dtype=jnp.float32)
    b = ContinuousBatcher(eng, chunk_steps=4, admit_chunk_steps=2)
    try:
        h = b.submit(Request(prompt_ids=[3, 5, 7], max_tokens=512,
                             temperature=0.0, request_id="flight-abort-1"))
    finally:
        b.shutdown()  # terminates the outstanding request
        eng.close()
    h.tokens()  # stream ended
    assert h.aborted
    tl = _timeline_for("flight-abort-1", model=TINY_TEST.name)
    assert tl.state == "aborted"
    assert tl.abort_cause == "model_unloading"
    assert tl.abort_cause in flightrec.ABORT_CAUSES
    # auto-triggered snapshots build on a background thread (the freeze
    # must not stall the scheduler): poll for the snapshot CONTAINING
    # this request — the global 8-deep snapshot store can already hold a
    # stale (tiny-test, abort) snapshot from an earlier suite file, and
    # exiting on the first (model, cause) match would assert against
    # that stale freeze while this abort's build is still running
    deadline = time.monotonic() + 10.0
    snaps = []
    while time.monotonic() < deadline and not snaps:
        snaps = [
            s for s in flightrec.RECORDER.snapshots()
            if s["model"] == TINY_TEST.name and s["cause"] == "abort"
            and any(
                t["request_id"] == "flight-abort-1"
                for t in s["timelines"]
            )
        ]
        time.sleep(0.02)
    assert snaps, (
        "abort must freeze an anomaly snapshot holding this request"
    )


def test_shed_spike_triggers_snapshot_with_cooldown():
    rec = FlightRecorder(ring=8, enabled=True)

    def spike_snaps():
        return [s for s in rec.snapshots() if s["cause"] == "shed_spike"]

    for _ in range(flightrec.SHED_SPIKE_N):
        rec.finish_shed(None, "queue_full", 100, model="spike-model")
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and not spike_snaps():
        time.sleep(0.02)  # spike snapshots build on a background thread
    assert len(spike_snaps()) == 1
    # a second burst inside the cooldown must NOT thrash the store (the
    # cooldown stamp is claimed synchronously, so this is race-free)
    for _ in range(flightrec.SHED_SPIKE_N):
        rec.finish_shed(None, "queue_full", 100, model="spike-model")
    time.sleep(0.1)
    assert len(spike_snaps()) == 1


# ---------------------------------------------------------------------------
# recorder mechanics (private instances)
# ---------------------------------------------------------------------------


def _fake_timeline(rec, model, rid, ttft=10.0, state="retired"):
    tl = rec.begin(model, rid, "t", prompt_tokens=4)
    tl.ttft_ms = ttft
    tl.tokens_out = 8
    rec.finish(tl, state)
    return tl


def test_ring_buffer_bound_respected():
    rec = FlightRecorder(ring=4, enabled=True)
    for i in range(10):
        _fake_timeline(rec, "ring-model", f"r{i}")
    recent = rec.recent(model="ring-model", limit=100)
    assert len(recent) == 4
    assert [t.request_id for t in recent] == ["r6", "r7", "r8", "r9"]


def test_disabled_recorder_is_inert():
    rec = FlightRecorder(ring=4, enabled=False)
    assert rec.begin("m", "r") is None
    rec.finish(None)  # no-ops, no raise
    rec.finish_shed(None, "quota", 100, model="m")
    assert rec.recent() == []


def test_event_cap_counts_drops():
    rec = FlightRecorder(ring=4, enabled=True)
    tl = rec.begin("cap-model", "r")
    for i in range(flightrec.MAX_EVENTS + 50):
        tl.event("decode", n=1)
    assert len(tl.events) == flightrec.MAX_EVENTS
    assert tl.dropped_events == 50
    rec.finish(tl)  # the terminal retire event also lands in the cap
    assert tl.to_dict()["dropped_events"] == 51


def test_chrome_trace_shape_unit():
    rec = FlightRecorder(ring=8, enabled=True)
    tl = rec.begin("trace-model", "req-x", "tenant-z", trace_id="ab" * 16)
    tl.event("route", replica=1, reason="prefix", overlap_rows=128)
    tl.queue_wait_ms = 2.5
    tl.event("prefill", tokens=64, dur_ms=3.0, cached_rows=128)
    tl.event("decode", n=16, occ=3, dur_ms=5.0, gap_ms=0.2)
    tl.ttft_ms, tl.tpot_ms, tl.tokens_out = 12.0, 1.5, 33
    rec.finish(tl)
    rec.model_event("trace-model", "spill", pages=3)
    doc = flightrec.chrome_trace(
        rec.recent(model="trace-model"), rec.model_events("trace-model")
    )
    doc = json.loads(json.dumps(doc))  # must be JSON-serializable
    evs = doc["traceEvents"]
    assert [e for e in evs if e["ph"] == "M"], "metadata events missing"
    xs = [e for e in evs if e["ph"] == "X"]
    assert {"request[retired]", "queue", "prefill", "decode"} <= {
        e["name"] for e in xs
    }
    for e in xs:
        assert e["dur"] > 0 and e["ts"] > 0
    spills = [e for e in evs if e["name"] == "spill"]
    assert spills and spills[0]["tid"] == 0  # model lane rides tid 0
    # a frozen snapshot renders through the SAME path: durations and the
    # engine lane survive the freeze instead of degrading to instants
    snap = rec.snapshot("trace-model", "manual")
    frozen = json.loads(json.dumps(flightrec.snapshot_trace(snap)))
    fx = {e["name"] for e in frozen["traceEvents"] if e["ph"] == "X"}
    assert {"request[retired]", "queue", "prefill", "decode"} <= fx
    assert any(e["name"] == "spill" and e["tid"] == 0
               for e in frozen["traceEvents"])


def test_span_folding_by_trace_id():
    rec = FlightRecorder(ring=8, enabled=True)
    tl = rec.begin("span-model", "r1", trace_id="cd" * 16)
    rec.finish(tl)

    class FakeSpan:
        trace_id = "cd" * 16
        span_id = "ef" * 8
        name = "rpc.server/Infer"
        status = "ok"
        duration_s = 0.012

    rec.export_span(FakeSpan())
    spans = [f for _, k, f in tl.events if k == "span"]
    assert spans and spans[0]["name"] == "rpc.server/Infer"
    rec.export_span(type("S", (FakeSpan,), {"trace_id": "99" * 16})())
    assert len([1 for _, k, _ in tl.events if k == "span"]) == 1


def test_abort_cause_normalization():
    assert flightrec.abort_cause("evicted: KV pool exhausted") == "evicted"
    assert flightrec.abort_cause(
        "prompt exceeds the KV page pool") == "prompt_too_large"
    assert flightrec.abort_cause(
        "scheduler failed: ValueError('x')") == "scheduler_failed"
    assert flightrec.abort_cause("model unloading") == "model_unloading"
    assert flightrec.abort_cause("???") == "other"


# ---------------------------------------------------------------------------
# SLO window math (private engines, injected clocks)
# ---------------------------------------------------------------------------


def _slo(target=0.9, min_samples=5, window=60.0):
    return SLOEngine(SLOConfig(
        ttft_ms=100.0, tpot_ms=10.0, target=target,
        window_secs=window, min_samples=min_samples,
    ))


def test_slo_attainment_and_burn_rate():
    eng = _slo()
    for i in range(8):
        eng.record("slo-a", "t1", ttft_ms=50.0, tpot_ms=5.0, now=100.0)
    for i in range(2):
        eng.record("slo-a", "t2", ttft_ms=500.0, tpot_ms=5.0, now=100.0)
    ev = eng.evaluate("slo-a", now=100.0)
    assert ev["ttft"]["attainment"] == pytest.approx(0.8)
    # burn rate: (1 - 0.8) / (1 - 0.9) = 2x budget
    assert ev["ttft"]["burn_rate"] == pytest.approx(2.0)
    assert ev["ttft"]["breached"] is True
    assert ev["tpot"]["attainment"] == 1.0
    assert ev["tpot"]["breached"] is False
    assert ev["availability"]["attainment"] == 1.0


def test_slo_min_samples_gate_and_breach_edges():
    eng = _slo(min_samples=5)
    b0 = eng.breaches
    for _ in range(4):  # under min_samples: terrible but never breaches
        eng.record("slo-b", ttft_ms=999.0, now=10.0)
    assert eng.evaluate("slo-b", now=10.0)["ttft"]["breached"] is False
    assert eng.breaches == b0
    eng.record("slo-b", ttft_ms=999.0, now=10.0)  # 5th sample: breach edge
    assert eng.evaluate("slo-b", now=10.0)["ttft"]["breached"] is True
    assert eng.breaches == b0 + 1
    # staying breached is NOT a new edge
    eng.record("slo-b", ttft_ms=999.0, now=11.0)
    eng.evaluate("slo-b", now=11.0)
    assert eng.breaches == b0 + 1


def test_slo_window_prunes_old_samples():
    eng = _slo(window=60.0)
    for _ in range(6):
        eng.record("slo-c", ttft_ms=999.0, now=10.0)
    assert eng.evaluate("slo-c", now=20.0)["ttft"]["samples"] == 6
    ev = eng.evaluate("slo-c", now=200.0)  # window slid past everything
    assert ev["ttft"]["samples"] == 0
    assert ev["ttft"]["attainment"] == 1.0  # empty window never degrades


def test_slo_availability_counts_sheds_and_aborts():
    eng = _slo()
    for _ in range(3):
        eng.record("slo-d", ok=True, ttft_ms=10.0, now=5.0)
    eng.record("slo-d", ok=False, now=5.0)  # shed: no ttft sample
    ev = eng.evaluate("slo-d", now=5.0)
    assert ev["availability"]["attainment"] == pytest.approx(0.75)
    assert ev["availability"]["samples"] == 4
    assert ev["ttft"]["samples"] == 3  # latency objectives skip no-token


def test_slo_tenant_breakdown_and_health():
    # real clock here: health() evaluates with time.monotonic(), so the
    # samples must sit inside the real window
    now = time.monotonic()
    eng = _slo(min_samples=2)
    for _ in range(3):
        eng.record("slo-e", "good", ttft_ms=10.0, now=now)
        eng.record("slo-e", "bad", ttft_ms=999.0, now=now)
    tenants = eng.tenants("slo-e", now=now)
    assert tenants["good"]["ttft_attainment"] == 1.0
    assert tenants["bad"]["ttft_attainment"] == 0.0
    h = eng.health()
    assert h["status"] == "degraded"
    assert "slo-e" in h["slo_breached"]
    # annotate_health flips a healthy payload only on breach
    payload = {"status": "ok", "service": "x"}
    out = dict(payload)
    out.update({k: v for k, v in h.items() if k != "slo"})
    assert out["status"] == "degraded"


def test_timeline_observe_maps_states_to_samples():
    eng = _slo()
    tl = Timeline("slo-f", "r1", "tx", "", 4, 0)
    tl.state, tl.ttft_ms, tl.tpot_ms, tl.tokens_out = "retired", 5.0, 1.0, 9
    eng.observe(tl)
    aborted = Timeline("slo-f", "r2", "tx", "", 4, 0)
    aborted.state = "aborted"
    eng.observe(aborted)
    cancelled = Timeline("slo-f", "r3", "tx", "", 4, 0)
    cancelled.state = "cancelled"
    eng.observe(cancelled)  # client's choice: not a plane failure
    ev = eng.evaluate("slo-f", now=time.monotonic())
    assert ev["availability"]["samples"] == 2
    assert ev["availability"]["attainment"] == pytest.approx(0.5)


# ---------------------------------------------------------------------------
# /healthz status-code satellite
# ---------------------------------------------------------------------------


def test_healthz_returns_503_when_degraded():
    server, port = start_metrics_server(
        port=0, health_fn=lambda: {"status": "degraded", "why": "test"}
    )
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=5
            )
        assert err.value.code == 503
        body = json.loads(err.value.read().decode())
        assert body["status"] == "degraded" and body["why"] == "test"
    finally:
        server.shutdown()


def test_healthz_returns_503_when_health_fn_raises():
    def boom():
        raise RuntimeError("probe failure")

    server, port = start_metrics_server(port=0, health_fn=boom)
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=5
            )
        assert err.value.code == 503
        assert json.loads(err.value.read().decode())["status"] == "degraded"
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# the extended PR 6/7 invariant: recorder is host-side only
# ---------------------------------------------------------------------------


def test_recorder_no_compile_and_dispatch_identical(monkeypatch):
    """With the recorder ON, compile counters stay FLAT after warmup and
    dispatch counts + token streams are identical to recorder OFF —
    single-request waves so the dispatch count is deterministic (no
    admission-timing variance in the chunk-size choice)."""
    params = M.init_params(TINY_TEST, jax.random.PRNGKey(0),
                           dtype=jnp.float32)

    def wave(enabled):
        monkeypatch.setattr(flightrec.RECORDER, "enabled", enabled)
        eng = TPUEngine(TINY_TEST, params, num_slots=2, max_context=128,
                        cache_dtype=jnp.float32)
        eng.warmup(step_sizes=(2, 4), prefill_chunk=0)
        compiles_after_warmup = eng.stats()["xla_compiles"]
        b = ContinuousBatcher(eng, chunk_steps=4, admit_chunk_steps=4)
        try:
            outs = []
            for i in range(3):  # sequential: deterministic dispatch count
                outs.append(b.submit(Request(
                    prompt_ids=[3 + i, 17, 91], max_tokens=13,
                    temperature=0.0,
                )).tokens())
            return {
                "outs": outs,
                # decode_steps counts every dispatched step at the engine
                # — deterministic for sequential single-request waves.
                # (batcher.decode_dispatches is NOT compared: that
                # counter skips the first dispatch after an idle gap,
                # and whether an idle tick lands between sequential
                # requests is a race on this 2-core box.)
                "decode_steps": eng.stats()["decode_steps"],
                "compile_delta":
                    eng.stats()["xla_compiles"] - compiles_after_warmup,
            }
        finally:
            b.shutdown()
            eng.close()

    on, off = wave(True), wave(False)
    assert on["compile_delta"] == 0, (
        "recorder ON compiled post-warmup — it must be host-side only"
    )
    assert off["compile_delta"] == 0
    assert on["decode_steps"] == off["decode_steps"]
    assert on["outs"] == off["outs"]
    # and the ON wave actually recorded: 3 retired timelines with decode
    # ticks, the OFF wave recorded nothing new for those ids
    tls = [
        t for t in flightrec.RECORDER.recent(model=TINY_TEST.name,
                                             limit=256)
        if t.tokens_out == 13
    ]
    assert len(tls) >= 3
