"""Functional JAX implementation of the Llama-family decoder.

One code path serves TinyLlama-1.1B, Mistral-7B (GQA + sliding window),
DeepSeek-R1-Distill-8B and Qwen3-14B (QK-norm) — the four local tiers of the
reference intelligence hierarchy (SURVEY.md section 2.3). The design is
TPU-first:

  * layer parameters are stacked on a leading axis and the block stack runs
    under `jax.lax.scan` — one traced layer, fast compiles, XLA-friendly;
  * all matmuls are bf16 einsums (MXU), normalization/softmax accumulate in
    fp32;
  * masks are computed from positions with static shapes — no dynamic shapes
    anywhere, so prefill/decode jit cleanly onto the MXU;
  * three entry points: `forward_full` (training/parity), `prefill`
    (returns per-layer K/V for cache insertion), `decode_step` (batched
    single-token step over a slot cache — the continuous-batching hot loop).

Params pytree layout (E=hidden, Q=heads*head_dim, K=kv_heads*head_dim,
F=intermediate, L=layers, V=vocab, D=head_dim):

  embed      [V, E]
  layers/attn_norm [L, E]   layers/ffn_norm [L, E]
  layers/wq  [L, E, Q]      layers/wk [L, E, K]   layers/wv [L, E, K]
  layers/wo  [L, Q, E]
  layers/w_gate [L, E, F]   layers/w_up [L, E, F] layers/w_down [L, F, E]
  layers/q_norm [L, D]      layers/k_norm [L, D]      (only if cfg.qk_norm)
  final_norm [E]
  lm_head    [E, V]                                   (absent if tied)
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import ops
from .config import ModelConfig

Params = Dict[str, jnp.ndarray]

# weights that get the int8 serving treatment (contraction dim is axis -2);
# we_* are the expert-stacked MoE leaves (the router stays bf16 — tiny)
QUANT_KEYS = (
    "wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
    "we_gate", "we_up", "we_down",
)


def matmul(x: jnp.ndarray, w, qmm=None, kind: str = "col") -> jnp.ndarray:
    """x @ w where w is a dense array, an int8 leaf {"q", "s"} or an int4
    leaf {"q4", "s4"}.

    Quantized leaves stream their narrow format from HBM (int8 via XLA's
    mixed dot or the Pallas qmm; int4 via the packed-nibble Pallas kernel —
    a quarter of the bf16 decode bandwidth); elsewhere they dequantize
    inline.

    ``qmm`` — explicit int4 matmul callable f(x, leaf, kind), overriding
    the kernel ladder for q4 leaves; the tensor-parallel engine passes
    ShardingPlan.int4_matmul_impl so each device runs the packed-nibble
    kernel on its own shard under shard_map. ``kind`` names the Megatron
    role of this matmul ("col" | "row" | "head") so the impl picks the
    right specs + collective.
    """
    if isinstance(w, dict):
        if "q4" in w:
            if qmm is not None:
                return qmm(x, w, kind)
            from ..ops.int4_matmul import (
                infer_group,
                int4_matmul,
                int4_matmul_reference,
                kernel_supported,
            )

            p4, s4 = w["q4"], w["s4"]
            g = infer_group(p4, s4)
            if ops.use_pallas() and kernel_supported(
                p4.shape[-2] * 2, p4.shape[-1], g
            ):
                return int4_matmul(x, p4, s4)
            return int4_matmul_reference(x, p4, s4)
        w_q, s = w["q"], w["s"]
        if ops.use_pallas():
            import os

            from ..ops.quantized_matmul import supports_pallas_qmm

            if os.environ.get(
                "AIOS_TPU_PALLAS_QMM"
            ) == "1" and supports_pallas_qmm(w_q.shape[-2], w_q.shape[-1]):
                return ops.quantized_matmul(x, w_q, s)
            # XLA's mixed int8xbf16 dot streams the int8 operand directly
            # (measured faster than per-op Pallas launches at decode sizes)
            y = jax.lax.dot_general(
                x,
                w_q,
                (((x.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return (y * s).astype(x.dtype)
        return (x.astype(jnp.float32) @ (w_q.astype(jnp.float32) * s)).astype(
            x.dtype
        )
    return x @ w


def quantize_params(
    params: Params, include_head: bool = True, fuse: bool = True,
    mode: str = "int8", target: str = "auto", tp: int = 1,
) -> Params:
    """Convert matmul weights to int8 serving leaves {"q": int8, "s": f32}.

    Serving-format transformations applied together:
      * symmetric per-output-channel int8 — halves the weight bytes streamed
        from HBM per decode step (the measured bottleneck);
      * matmul fusion (``fuse=True``) — wq|wk|wv concatenate into one
        [E, Q+2KV] ``w_qkv`` and w_gate|w_up into one [E, 2F] ``w_gateup``,
        so each decode step issues 4 weight matmuls per layer instead of 7;
      * a tied lm_head is materialized as its own quantized [E, V] matrix so
        the logits matmul streams int8 too.

    ``fuse=False`` keeps the seven per-layer weights separate — required
    under a tensor-parallel sharding plan, where each projection's output
    dim shards on the tp axis and a fused concat would interleave q/k/v
    columns across shards (sharding.py quantized-leaf rules).

    Norms and the embedding gather stay bf16 (negligible bandwidth). The
    dense layout is untouched — training and sharding plans use it.

    ``mode="int4"`` emits group-wise int4 leaves {"q4": packed nibbles,
    "s4": [G, 1, N] scales} instead (ops/int4_matmul.py) — half the int8
    bytes, matching the reference's Q4-class GGUF serving precision.
    Leaves whose dims don't fit the int4 layout, and the expert-stacked
    MoE leaves (their gathered-decode path is int8-specialized), fall
    back to int8.
    """
    if mode not in ("int8", "int4"):
        raise ValueError(f"unknown weight quantization mode {mode!r}")
    if target not in ("auto", "tpu"):
        raise ValueError(f"unknown quantization target {target!r}")
    out = dict(params)
    src = params["layers"]
    layers = {
        k: v
        for k, v in src.items()
        if k not in QUANT_KEYS
    }
    moe = "w_router" in src
    if fuse:
        qkv = jnp.concatenate([src["wq"], src["wk"], src["wv"]], axis=-1)
        to_quant = (("w_qkv", qkv), ("wo", src["wo"]))
        if moe:
            gateup = jnp.concatenate([src["we_gate"], src["we_up"]], axis=-1)
            to_quant += (("we_gateup", gateup), ("we_down", src["we_down"]))
        else:
            gateup = jnp.concatenate([src["w_gate"], src["w_up"]], axis=-1)
            to_quant += (("w_gateup", gateup), ("w_down", src["w_down"]))
    else:
        to_quant = tuple((k, src[k]) for k in QUANT_KEYS if k in src)
    def quant_leaf(key, w):
        if mode == "int4" and not key.startswith("we_"):
            from ..ops.int4_matmul import (
                kernel_supported,
                pick_group,
                quantize_int4,
                supports_int4,
            )

            K, N = w.shape[-2], w.shape[-1]
            # Under a tp-sharded plan the kernel runs per device on a
            # [K, N/tp] (column-parallel) or [K/tp, N] (row-parallel)
            # shard, so eligibility — and the scale-group size — must
            # hold for the SHARD dims, not the global ones. lm_head
            # shards its vocab like a column projection.
            local_K, local_N = K, N
            if tp > 1:
                if key in ("wo", "w_down"):
                    local_K = K // tp if K % tp == 0 else 0
                else:
                    local_N = N // tp if N % tp == 0 else 0
            group = pick_group(local_K)
            # On TPU a q4 leaf the kernel can't serve would dequantize to
            # bf16 in HBM every step — strictly worse than int8 — so
            # kernel-ineligible dims fall back to int8 there. Off-TPU every
            # quantized leaf dequantizes inline anyway, so storage
            # eligibility is enough (keeps tiny test geometries on int4).
            # ``target="tpu"`` forces the strict kernel rule regardless of
            # the local backend — prepare_model uses it so a checkpoint
            # prepared on a CPU build box never bakes in leaves a TPU
            # can only serve through the HBM-dequant path.
            eligible = (
                local_K > 0
                and local_N > 0
                and supports_int4(K, N, group)
                and (
                    kernel_supported(local_K, local_N, group)
                    or (target != "tpu" and not ops.use_pallas())
                )
            )
            if eligible:
                p, s = quantize_int4(w, group=group)
                return {"q4": p, "s4": s}
        q, s = ops.quantize_int8(w, axis=-2)
        return {"q": q, "s": s}

    for key, w in to_quant:
        layers[key] = quant_leaf(key, w)
    out["layers"] = layers
    if include_head:
        head = params.get("lm_head")
        if head is None:
            head = params["embed"].T
        out["lm_head"] = quant_leaf("lm_head", head)
    return out


# ---------------------------------------------------------------------------
# Primitives
# ---------------------------------------------------------------------------


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float) -> jnp.ndarray:
    """RMSNorm with fp32 accumulation, output in x.dtype."""
    xf = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * scale).astype(x.dtype) * weight


def rope_tables(
    positions: jnp.ndarray, head_dim: int, theta: float
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin tables for the given absolute positions.

    Returns arrays of shape positions.shape + (head_dim,) using the
    half-rotation (HF transformers) convention: the frequency vector is
    duplicated across the two halves of the head dimension.
    """
    half = head_dim // 2
    inv_freq = 1.0 / (theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # [..., half]
    angles = jnp.concatenate([angles, angles], axis=-1)
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray) -> jnp.ndarray:
    """Rotate q or k. x: [B, T, H, D]; cos/sin: [B, T, D]."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    cos = cos[..., None, :]  # broadcast over heads
    sin = sin[..., None, :]
    return (x.astype(jnp.float32) * cos + rotated.astype(jnp.float32) * sin).astype(
        x.dtype
    )


def gqa_attention(
    q: jnp.ndarray,  # [B, T, H, D]
    k: jnp.ndarray,  # [B, S, KH, D]
    v: jnp.ndarray,  # [B, S, KH, D]
    mask: jnp.ndarray,  # bool [B, T, S] or [T, S]
) -> jnp.ndarray:
    """Grouped-query attention, fp32 softmax. Returns [B, T, H, D]."""
    B, T, H, D = q.shape
    KH = k.shape[2]
    G = H // KH
    q = q.reshape(B, T, KH, G, D)
    scores = jnp.einsum("btkgd,bskd->bkgts", q, k).astype(jnp.float32)
    scores = scores / np.sqrt(D)
    if mask.ndim == 2:
        mask = mask[None]
    scores = jnp.where(mask[:, None, None, :, :], scores, jnp.float32(-1e30))
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bkgts,bskd->btkgd", probs, v)
    return out.reshape(B, T, H, D)


def blockwise_cache_attention(
    q: jnp.ndarray,  # [1, Tc, H, D]
    k: jnp.ndarray,  # [1, C, KH, D]
    v: jnp.ndarray,  # [1, C, KH, D]
    abs_pos: jnp.ndarray,  # [Tc] absolute position of each query row
    window: Optional[int],
    block: int = 512,
    live_from: Optional[jnp.ndarray] = None,  # scalar: live window start
    sink: int = 0,  # static sink rows (window+sink KV compression)
) -> jnp.ndarray:
    """Chunk-vs-cache attention via an online softmax over KV blocks.

    The [Tc, C] score matrix never materializes: each [Tc, block] tile is
    folded into running (max, denom, accumulator) stats under ``lax.scan``
    (the flash recurrence in plain XLA, so it runs on every backend). This
    is what keeps chunked admission of an 8k prompt from allocating
    hundreds of MB of fp32 scores per layer. Query row i sees cache col j
    iff j <= abs_pos[i] (and inside the sliding window) — the row's own
    K/V was written to the cache before this is called, so the diagonal is
    always visible and the denominator can't be zero.
    """
    B, Tc, H, D = q.shape
    C = k.shape[1]
    KH = k.shape[2]
    G = H // KH
    qf = q[0].reshape(Tc, KH, G, D).astype(jnp.float32) / np.sqrt(D)
    nb = C // block
    kb = k[0].astype(jnp.float32).reshape(nb, block, KH, D)
    vb = v[0].astype(jnp.float32).reshape(nb, block, KH, D)
    colsb = jnp.arange(C).reshape(nb, block)

    def fold(carry, xs):
        m, l, acc = carry
        kblk, vblk, cols = xs
        s = jnp.einsum("tkgd,ckd->kgtc", qf, kblk)  # [KH, G, Tc, block]
        visible = cols[None, :] <= abs_pos[:, None]  # [Tc, block]
        if window is not None:
            visible = visible & (cols[None, :] > abs_pos[:, None] - window)
        if live_from is not None:
            # window+sink KV compression: cache rows in [sink, live_from)
            # were pruned mid-admission; the chunk must not attend them
            visible = visible & (
                (cols[None, :] < sink) | (cols[None, :] >= live_from)
            )
        s = jnp.where(visible[None, None], s, jnp.float32(-1e30))
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)  # rescale of previous stats
        l = l * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum("kgtc,ckd->kgtd", p, vblk)
        return (m_new, l, acc), None

    init = (
        jnp.full((KH, G, Tc), -1e30, jnp.float32),
        jnp.zeros((KH, G, Tc), jnp.float32),
        jnp.zeros((KH, G, Tc, D), jnp.float32),
    )
    (m, l, acc), _ = jax.lax.scan(fold, init, (kb, vb, colsb))
    out = acc / l[..., None]
    # [KH, G, Tc, D] -> [1, Tc, H, D]
    return out.transpose(2, 0, 1, 3).reshape(B, Tc, H, D).astype(q.dtype)


def causal_mask(T: int, window: Optional[int]) -> jnp.ndarray:
    """[T, T] causal (optionally sliding-window) mask."""
    rows = jnp.arange(T)[:, None]
    cols = jnp.arange(T)[None, :]
    m = cols <= rows
    if window is not None:
        m = m & (cols > rows - window)
    return m


# ---------------------------------------------------------------------------
# One transformer block (shared by all entry points)
# ---------------------------------------------------------------------------


def _project_qkv(x, lp, cfg: ModelConfig, cos, sin, qmm=None):
    B, T, E = x.shape
    h = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
    if "w_qkv" in lp:  # fused serving layout (quantize_params)
        Q, KV = cfg.q_dim, cfg.kv_dim
        qkv = matmul(h, lp["w_qkv"], qmm)
        q, k, v = (
            qkv[..., :Q],
            qkv[..., Q : Q + KV],
            qkv[..., Q + KV :],
        )
    else:
        q = matmul(h, lp["wq"], qmm)
        k = matmul(h, lp["wk"], qmm)
        v = matmul(h, lp["wv"], qmm)
    q = q.reshape(B, T, cfg.num_heads, cfg.head_dim)
    k = k.reshape(B, T, cfg.num_kv_heads, cfg.head_dim)
    v = v.reshape(B, T, cfg.num_kv_heads, cfg.head_dim)
    if cfg.qk_norm:
        q = rms_norm(q, lp["q_norm"], cfg.rms_norm_eps)
        k = rms_norm(k, lp["k_norm"], cfg.rms_norm_eps)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    return q, k, v


def apply_block(x, lp, cfg: ModelConfig, cos, sin, mask, attention=None,
                with_aux: bool = False, qmm=None):
    """One transformer block on [B, T, E]; returns (x', (k, v)) — or
    (x', (k, v, moe_aux)) when ``with_aux``.

    The single source of truth for block structure — the prefill/training
    forward, the decode step, and the pipeline-parallel stage all build on
    it (pipeline.py discards the returned k/v).
    """
    attention = attention or gqa_attention
    B, T = x.shape[0], x.shape[1]
    q, k, v = _project_qkv(x, lp, cfg, cos, sin, qmm)
    attn = attention(q, k, v, mask)
    x = x + matmul(attn.reshape(B, T, -1), lp["wo"], qmm, "row")
    mlp_out, aux = _mlp_aux(x, lp, cfg, allow_dispatch=with_aux, qmm=qmm)
    x = x + mlp_out
    if with_aux:
        return x, (k, v, aux)
    return x, (k, v)


def _mlp(x, lp, cfg: ModelConfig, moe_impl: Optional[str] = None, qmm=None):
    return _mlp_aux(x, lp, cfg, moe_impl=moe_impl, qmm=qmm)[0]


def _mlp_aux(
    x,
    lp,
    cfg: ModelConfig,
    allow_dispatch: bool = False,
    moe_impl: Optional[str] = None,
    qmm=None,
):
    """FFN sublayer; returns (out, moe_aux) — aux is the router
    load-balancing term (0.0 for dense models), consumed only by the
    training forward (forward_full with_aux=True).

    ``moe_impl`` — explicit MoE path ("dense" | "gather" | "dispatch"),
    normally chosen statically by the engine (TPUEngine picks "gather" for
    unsharded decode when slots*k < num_experts); None falls back to the
    AIOS_TPU_MOE_IMPL env var, then auto.
    """
    h = rms_norm(x, lp["ffn_norm"], cfg.rms_norm_eps)
    if "w_router" in lp:  # mixture-of-experts FFN (engine/moe.py)
        import os

        from . import moe as moe_mod

        # the env var stays the operator's escape hatch: it overrides the
        # engine's static choice (e.g. AIOS_TPU_MOE_IMPL=dense to A/B or
        # disable the gathered decode path)
        impl = os.environ.get("AIOS_TPU_MOE_IMPL") or moe_impl or "auto"
        n_tok = h.shape[0] * h.shape[1]
        if impl == "dispatch":
            return moe_mod.moe_ffn_dispatch(h, lp, cfg)
        if impl == "gather":
            return moe_mod.moe_ffn_gather(h, lp, cfg)
        if impl == "auto" and allow_dispatch and n_tok >= 1024:
            # The capacity-based dispatch path may DROP overflow picks, so
            # auto only selects it on the training forward
            # (``allow_dispatch``, i.e. with_aux) at large token counts —
            # every serving path (decode, chunked/bucketed prefill) stays
            # on an exact path unless the env explicitly forces dispatch.
            return moe_mod.moe_ffn_dispatch(h, lp, cfg)
        return moe_mod.moe_ffn_dense(h, lp, cfg)
    if "w_gateup" in lp:  # fused serving layout (quantize_params)
        F = cfg.intermediate_size
        gu = matmul(h, lp["w_gateup"], qmm)
        gate_pre, up = gu[..., :F], gu[..., F:]
    else:
        gate_pre = matmul(h, lp["w_gate"], qmm)
        up = matmul(h, lp["w_up"], qmm)
    gate = jax.nn.silu(gate_pre.astype(jnp.float32)).astype(h.dtype)
    return matmul(gate * up, lp["w_down"], qmm, "row"), jnp.float32(0.0)


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------


def forward_full(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,
    attn_fn=None,
    kernels: Optional[bool] = None,
    with_aux: bool = False,
) -> jnp.ndarray:
    """Full-sequence causal forward; logits [B, T, V] in fp32.

    Used for training, numeric-parity testing and as the prefill core.
    ``attn_fn`` swaps the attention implementation (e.g. ring attention for
    sequence-parallel training); it defaults to in-core GQA attention.
    ``kernels=False`` forces the pure-XLA path — required under autodiff:
    the Pallas flash kernel is forward-only (no VJP rule yet).
    ``with_aux`` additionally returns the mean per-layer MoE
    load-balancing loss (0.0 for dense models): (logits, aux).
    """
    if with_aux:
        logits, _, _, aux = _forward_with_kv(
            params, cfg, tokens, attn_fn, kernels, with_aux=True
        )
        return logits, aux
    logits, _, _ = _forward_with_kv(params, cfg, tokens, attn_fn, kernels)
    return logits


def prefill(
    params: Params, cfg: ModelConfig, tokens: jnp.ndarray, kernels=None,
    qmm=None, attn_fn=None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Causal forward returning (logits [B,T,V], k [L,B,T,KH,D], v [...]).

    The engine copies the returned K/V into the request's cache slot.
    ``attn_fn`` swaps the attention implementation — the sequence-sharded
    prefill path passes the ring/Ulysses adapter here so one huge
    prompt's forward spreads over the mesh's sp axis.
    """
    return _forward_with_kv(
        params, cfg, tokens, attn_fn=attn_fn, kernels=kernels, qmm=qmm
    )


def _use_kernels(kernels: Optional[bool]) -> bool:
    return ops.use_pallas() if kernels is None else bool(kernels)


def _final_logits(x: jnp.ndarray, params: Params, cfg: ModelConfig, qmm=None):
    """Shared tail of every entry point: final RMSNorm + (possibly tied,
    possibly int8) lm_head matmul; logits in fp32."""
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    head = params.get("lm_head")
    if head is None:
        head = params["embed"].T
    return matmul(x, head, qmm, "head").astype(jnp.float32)


def _ragged_min_c() -> int:
    """Cache length where the ragged decode kernel starts winning over
    XLA's fused full-cache read (measured crossover on v5e ~2k rows;
    AIOS_TPU_RAGGED_MIN_C overrides for A/B runs, read at trace time)."""
    import os

    try:
        return int(os.environ.get("AIOS_TPU_RAGGED_MIN_C", "2048"))
    except ValueError:
        return 2048


def _int8_ragged_enabled() -> bool:
    """Gate for the int8-KV ragged decode kernel (read at trace time):
    interpret-mode-verified, but OFF by default until its crossover is
    measured on a real chip (the dequantizing XLA path is the baseline)."""
    import os

    return os.environ.get("AIOS_TPU_INT8_RAGGED", "").lower() in (
        "1", "true", "on",
    )


def _use_ragged_kernel(
    kernels: Optional[bool],
    C: int,
    cfg: ModelConfig,
    quant_cache: bool,
    quant_kernel_ok: bool = False,
) -> bool:
    """The ragged-attention crossover, shared by decode_step and
    verify_step: the kernel's DMA-only-valid-rows win beats its per-layer
    launch cost either on a long cache outright (>= _ragged_min_c rows,
    the TinyLlama-measured crossover) or on a large-model cache whose
    C x (KH x D) slab is >= 1 MiB of rows per slot (Mistral-7B at 1k rows
    measures +11% whole-step throughput on v5e).

    ``quant_kernel_ok`` — whether the CALLER has an int8-capable kernel
    for this path: decode_step and verify_step pass
    _int8_ragged_enabled() (their ladders include the int8 kernel
    variants, env-gated until measured on chip); callers without one pass
    False and their int8-KV paths stay on XLA. decode_step_paged does NOT
    use this crossover at all — like its bf16 path, the paged kernel is
    always preferable to the gather fallback, so it gates only on
    _use_kernels + the env flag."""
    kv_row = cfg.num_kv_heads * cfg.head_dim
    # the int8 kernel variants DMA-slice the cache axis on lanes, so they
    # need 128-aligned kv blocks (C % 128 == 0 makes pick_block_kv choose
    # >= 128); ineligible geometries stay on the XLA dequant path instead
    # of tripping the kernels' alignment guard
    int8_geometry_ok = C % 128 == 0
    return (
        _use_kernels(kernels)
        and (C >= _ragged_min_c() or C * kv_row >= 1 << 20)
        and (not quant_cache or (quant_kernel_ok and int8_geometry_ok))
    )


def _forward_with_kv(params, cfg: ModelConfig, tokens, attn_fn=None, kernels=None,
                     with_aux: bool = False, qmm=None):
    B, T = tokens.shape
    x = params["embed"][tokens]
    positions = jnp.broadcast_to(jnp.arange(T), (B, T))
    cos, sin = rope_tables(positions, cfg.head_dim, cfg.rope_theta)

    # Attention implementation ladder: explicit attn_fn (ring attention for
    # sequence parallelism) > Pallas flash kernel (TPU, block-aligned T) >
    # naive masked GQA. Flash is what keeps 8k-token prefills inside HBM —
    # it never materializes the [T, T] score matrix.
    if attn_fn is None and _use_kernels(kernels) and T >= 128 and T % 128 == 0:
        def attention(q, k, v, mask):
            return ops.flash_attention(
                q, k, v, causal=True, window=cfg.sliding_window
            )
    else:
        attention = attn_fn or gqa_attention
    mask = causal_mask(T, cfg.sliding_window)

    def block(x, lp):
        return apply_block(x, lp, cfg, cos, sin, mask, attention, with_aux,
                           qmm=qmm)

    if with_aux:
        x, (ks, vs, auxs) = jax.lax.scan(block, x, params["layers"])
        logits = _final_logits(x, params, cfg, qmm)
        return logits, ks, vs, jnp.mean(auxs)
    x, (ks, vs) = jax.lax.scan(block, x, params["layers"])
    logits = _final_logits(x, params, cfg, qmm)
    return logits, ks, vs


def prefill_chunk(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [1, Tc] int32 — one chunk of one prompt
    slot: jnp.ndarray,  # scalar int32 — destination cache slot
    start: jnp.ndarray,  # scalar int32 — absolute position of tokens[0]
    k_cache: jnp.ndarray,  # [L, S, C, KH, D]
    v_cache: jnp.ndarray,  # [L, S, C, KH, D]
    cache_scales: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,
    qmm=None,  # int4 matmul impl (x, leaf, kind) -> y; see matmul()
):
    """One chunk of an incremental prefill against the slot cache.

    Writes the chunk's K/V at rows [start, start+Tc) of ``slot`` and attends
    each chunk token over all cache rows written so far (causal within the
    chunk, everything before ``start`` visible, sliding window honoured) —
    so an 8k prompt can be admitted as 16 x 512-token chunks with decode
    dispatches for the other slots interleaved between them, instead of one
    monolithic prefill that stalls every active request (the head-of-line
    block the reference inherits from llama-server's serial queue,
    SURVEY.md section 7 hard-part #1).

    Returns (logits [1, Tc, V] fp32, k_cache', v_cache'[, scales']).
    Rows past ``start+Tc`` are garbage and masked; the caller samples from
    the logits row of the prompt's true last token on the final chunk.
    """
    B, Tc = tokens.shape
    C = k_cache.shape[2]
    quant_cache = cache_scales is not None
    x = params["embed"][tokens]  # [1, Tc, E]
    positions = start + jnp.arange(Tc)[None, :]  # [1, Tc]
    cos, sin = rope_tables(positions, cfg.head_dim, cfg.rope_theta)

    kv_tile = min(512, C)  # NB: local `block` below would shadow this
    if C % kv_tile == 0:
        mask = None  # blockwise online-softmax path; mask built per tile

        def attend(q, k_all, v_all):
            return blockwise_cache_attention(
                q, k_all, v_all, positions[0], cfg.sliding_window, kv_tile
            )
    else:
        # chunk row i (abs pos start+i) sees cache col j iff j <= start+i
        cols = jnp.arange(C)[None, :]  # [1, C]
        abs_pos = positions[0][:, None]  # [Tc, 1]
        mask = cols <= abs_pos
        if cfg.sliding_window is not None:
            mask = mask & (cols > abs_pos - cfg.sliding_window)
        mask = mask[None]  # [1, Tc, C]

        def attend(q, k_all, v_all):
            return gqa_attention(q, k_all, v_all, mask)

    write_at = (slot, start, jnp.int32(0), jnp.int32(0))

    def block(x, layer):
        if quant_cache:
            lp, k_l, v_l, k_s, v_s = layer
        else:
            lp, k_l, v_l = layer
            k_s = v_s = None
        q, k_new, v_new = _project_qkv(x, lp, cfg, cos, sin, qmm)
        # k_new/v_new [1, Tc, KH, D] drop straight into the slot-cache layout
        # [S, C, KH, D] at (slot, start, 0, 0)
        if quant_cache:
            kq, ks_new = quantize_kv(k_new)
            vq, vs_new = quantize_kv(v_new)
            k_l = jax.lax.dynamic_update_slice(k_l, kq, write_at)
            v_l = jax.lax.dynamic_update_slice(v_l, vq, write_at)
            k_s = jax.lax.dynamic_update_slice(k_s, ks_new, write_at[:-1])
            v_s = jax.lax.dynamic_update_slice(v_s, vs_new, write_at[:-1])
            k_all = dequantize_kv(
                jax.lax.dynamic_slice_in_dim(k_l, slot, 1, axis=0),
                jax.lax.dynamic_slice_in_dim(k_s, slot, 1, axis=0),
                q.dtype,
            )
            v_all = dequantize_kv(
                jax.lax.dynamic_slice_in_dim(v_l, slot, 1, axis=0),
                jax.lax.dynamic_slice_in_dim(v_s, slot, 1, axis=0),
                q.dtype,
            )
        else:
            k_l = jax.lax.dynamic_update_slice(
                k_l, k_new.astype(k_l.dtype), write_at
            )
            v_l = jax.lax.dynamic_update_slice(
                v_l, v_new.astype(v_l.dtype), write_at
            )
            k_all = jax.lax.dynamic_slice_in_dim(k_l, slot, 1, axis=0)
            v_all = jax.lax.dynamic_slice_in_dim(v_l, slot, 1, axis=0)
        attn = attend(q, k_all.astype(q.dtype), v_all.astype(q.dtype))
        x = x + matmul(attn.reshape(B, Tc, -1), lp["wo"], qmm, "row")
        x = x + _mlp(x, lp, cfg, qmm=qmm)
        if quant_cache:
            return x, (k_l, v_l, k_s, v_s)
        return x, (k_l, v_l)

    if quant_cache:
        k_scales, v_scales = cache_scales
        x, (k_cache, v_cache, k_scales, v_scales) = jax.lax.scan(
            block, x, (params["layers"], k_cache, v_cache, k_scales, v_scales)
        )
    else:
        x, (k_cache, v_cache) = jax.lax.scan(
            block, x, (params["layers"], k_cache, v_cache)
        )
    logits = _final_logits(x, params, cfg, qmm)
    if quant_cache:
        return logits, k_cache, v_cache, (k_scales, v_scales)
    return logits, k_cache, v_cache


def decode_step(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B] int32 — one new token per slot
    lengths: jnp.ndarray,  # [B] int32 — tokens already in each slot's cache
    k_cache: jnp.ndarray,  # [L, B, C, KH, D]
    v_cache: jnp.ndarray,  # [L, B, C, KH, D]
    kernels: Optional[bool] = None,
    cache_scales: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,
    active: Optional[jnp.ndarray] = None,  # [B] bool
    attn_impl=None,  # (q [B,H,D], k_l, v_l, lengths) -> [B,H,D]
    moe_impl: Optional[str] = None,
    qmm=None,  # int4 matmul impl (x, leaf, kind) -> y; see matmul()
):
    """One batched decode step over the slot cache.

    Writes the new K/V at row ``lengths[b]`` of each slot, attends over all
    valid rows (with sliding window if configured), and returns
    (logits [B, V] fp32, k_cache', v_cache'[, (k_scales', v_scales')]).
    Intended to be jitted with the caches donated so XLA updates them in
    place. Besides the single-dispatch scan, this is the body the
    multi-tick decode megagraph (TPUEngine._mega_impl) iterates under
    lax.while_loop — keep it free of host callbacks and shape-dependent
    Python branching on traced values, or the K-tick window stops
    lowering to one device program.

    ``active`` — slots marked False write their (ignored) K/V to the
    sacrificial last cache row and attend over zero rows, so an inactive or
    mid-chunked-prefill slot costs no cache bandwidth and cannot corrupt
    rows an incremental admission has already written. The fixed-shape
    graph still computes every slot's matmuls; only the cache traffic and
    writes are gated. None means all slots active.

    ``kernels`` — None picks the Pallas ragged-attention kernel on TPU
    (reads only rows [0, length] per slot from HBM); False forces the naive
    full-cache path (required when the cache is sharded over a mesh — the
    kernel is per-device).

    ``cache_scales`` — (k_scales, v_scales) [L, B, C, KH] f32 marks an int8
    KV cache: new rows are quantized per (row, head) on write and the cache
    dequantizes while being read — half the cache HBM traffic and footprint
    of bf16 (the attention math itself stays bf16/fp32).

    ``attn_impl`` — explicit attention callable, overriding the kernel
    ladder; used by the tensor-parallel engine to run the ragged kernel
    per-device under shard_map (ShardingPlan.ragged_attention). bf16
    caches only.
    """
    B = tokens.shape[0]
    C = k_cache.shape[2]
    quant_cache = cache_scales is not None
    use_kernel = attn_impl is None and _use_ragged_kernel(
        kernels, C, cfg, quant_cache
    )
    # int8-KV ragged kernel: scales fold into the score/value dots so the
    # cache streams as int8 (half the bytes) AND only valid rows DMA
    use_int8_kernel = (
        attn_impl is None
        and quant_cache
        and _use_ragged_kernel(
            kernels, C, cfg, quant_cache,
            quant_kernel_ok=_int8_ragged_enabled(),
        )
    )
    if active is None:
        write_rows = lengths
        read_lengths = lengths
    else:
        write_rows = jnp.where(active, lengths, C - 1)
        # read length -1 would be ideal; 0 exposes one (overwritten-before-
        # read for active slots, garbage-but-ignored otherwise) row, which
        # keeps the mask/kernel contract "row `length` was just written"
        read_lengths = jnp.where(active, lengths, 0)
    x = params["embed"][tokens][:, None, :]  # [B, 1, E]
    cos, sin = rope_tables(lengths[:, None], cfg.head_dim, cfg.rope_theta)

    batch_idx = jnp.arange(B)
    if use_kernel or use_int8_kernel or attn_impl is not None:
        mask = None
    else:
        cols = jnp.arange(C)[None, :]
        # col j is visible if it holds a written token (j <= lengths, since
        # the new token is written before attending) and is inside the window
        mask = cols <= read_lengths[:, None]
        if cfg.sliding_window is not None:
            mask = mask & (cols > (read_lengths[:, None] - cfg.sliding_window))
        mask = mask[:, None, :]  # [B, 1, C]

    def block(x, layer):
        if quant_cache:
            lp, k_l, v_l, k_s, v_s = layer
        else:
            lp, k_l, v_l = layer
            k_s = v_s = None
        q, k_new, v_new = _project_qkv(x, lp, cfg, cos, sin, qmm)
        if quant_cache:
            kq, ks_new = quantize_kv(k_new[:, 0])
            vq, vs_new = quantize_kv(v_new[:, 0])
            k_l = k_l.at[batch_idx, write_rows].set(kq)
            v_l = v_l.at[batch_idx, write_rows].set(vq)
            k_s = k_s.at[batch_idx, write_rows].set(ks_new)
            v_s = v_s.at[batch_idx, write_rows].set(vs_new)
            if use_int8_kernel:
                attn = ops.decode_attention_int8(
                    q[:, 0], k_l, v_l, k_s, v_s, read_lengths,
                    window=cfg.sliding_window,
                )[:, None]
            else:
                attn = gqa_attention(
                    q,
                    dequantize_kv(k_l, k_s, q.dtype),
                    dequantize_kv(v_l, v_s, q.dtype),
                    mask,
                )
        else:
            k_l = k_l.at[batch_idx, write_rows].set(k_new[:, 0].astype(k_l.dtype))
            v_l = v_l.at[batch_idx, write_rows].set(v_new[:, 0].astype(v_l.dtype))
            if attn_impl is not None:
                attn = attn_impl(q[:, 0], k_l, v_l, read_lengths)[:, None]
            elif use_kernel:
                attn = ops.decode_attention(
                    q[:, 0], k_l, v_l, read_lengths, window=cfg.sliding_window
                )[:, None]
            else:
                attn = gqa_attention(q, k_l, v_l, mask)
        x = x + matmul(attn.reshape(B, 1, -1), lp["wo"], qmm, "row")
        x = x + _mlp(x, lp, cfg, moe_impl, qmm)
        if quant_cache:
            return x, (k_l, v_l, k_s, v_s)
        return x, (k_l, v_l)

    if quant_cache:
        k_scales, v_scales = cache_scales
        x, (k_cache, v_cache, k_scales, v_scales) = jax.lax.scan(
            block, x, (params["layers"], k_cache, v_cache, k_scales, v_scales)
        )
    else:
        x, (k_cache, v_cache) = jax.lax.scan(
            block, x, (params["layers"], k_cache, v_cache)
        )
    logits = _final_logits(x[:, 0], params, cfg, qmm)
    if quant_cache:
        return logits, k_cache, v_cache, (k_scales, v_scales)
    return logits, k_cache, v_cache


def prefill_chunk_paged(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [1, Tc] int32 — one chunk of one prompt
    start: jnp.ndarray,  # scalar int32 — absolute position of tokens[0]
    k_pool: jnp.ndarray,  # [L, N, P, KH, D]
    v_pool: jnp.ndarray,  # [L, N, P, KH, D]
    table_row: jnp.ndarray,  # [MB] int32 — the slot's block->page map
    cache_scales: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,
    qmm=None,  # int4 matmul impl (x, leaf, kind) -> y; see matmul()
    win_start: Optional[jnp.ndarray] = None,  # scalar: live window start
    sink_rows: int = 0,  # static sink rows (window+sink KV compression)
):
    """One chunk of an incremental prefill against the PAGED cache.

    Same contract as ``prefill_chunk`` (write rows [start, start+Tc) of the
    slot, attend each chunk token over everything written so far), with the
    rows scattered into the page pool through ``table_row``. Because chunk
    sizes and page sizes are both powers of two, a chunk either spans whole
    pages (Tc >= P, start page-aligned) or sits inside one page (Tc < P) —
    the write indices are static repeats, never an index-array gather.
    Chunk attention gathers the slot's logical view from the pool per layer
    (a copy, but prefill is compute-bound; the decode hot path reads pages
    in place via the kernel). The caller must have backed rows
    [0, start+Tc) — unbacked blocks map the sacrificial page 0, which the
    mask never exposes below ``start+Tc``.

    ``cache_scales`` marks an int8 pool (rows quantize on write, the
    gathered view dequantizes). Returns (logits [1, Tc, V] fp32, k_pool',
    v_pool'[, scales']).
    """
    B, Tc = tokens.shape
    MB = table_row.shape[0]
    P = k_pool.shape[2]
    C_log = MB * P
    quant_pool = cache_scales is not None
    x = params["embed"][tokens]  # [1, Tc, E]
    positions = start + jnp.arange(Tc)[None, :]  # [1, Tc]
    cos, sin = rope_tables(positions, cfg.head_dim, cfg.rope_theta)

    if Tc >= P:  # page-aligned chunk spanning Tc/P whole pages
        nb = Tc // P
        # pad with sacrificial entries so a final bucket whose padding
        # overruns max_context (possible when a prefix match de-aligns
        # chunk starts) slices cleanly: overflow rows land on page 0
        # instead of dynamic_slice clamping the start a block early and
        # corrupting the previous chunk's rows
        table_ext = jnp.concatenate(
            [table_row, jnp.zeros((nb,), table_row.dtype)]
        )
        pages_blk = jax.lax.dynamic_slice(table_ext, (start // P,), (nb,))
        pages = jnp.repeat(pages_blk, P)  # [Tc]
        offs = jnp.arange(Tc) % P
    else:  # chunk inside one page
        page = jax.lax.dynamic_slice(table_row, (start // P,), (1,))[0]
        pages = jnp.broadcast_to(page, (Tc,))
        offs = (start % P) + jnp.arange(Tc)

    t = min(512, C_log)
    kv_tile = t if C_log % t == 0 else P

    def block(x, layer):
        if quant_pool:
            lp, k_l, v_l, k_s, v_s = layer
        else:
            lp, k_l, v_l = layer
            k_s = v_s = None
        q, k_new, v_new = _project_qkv(x, lp, cfg, cos, sin, qmm)
        if quant_pool:
            k_l, k_s = scatter_quant(k_l, k_s, pages, offs, k_new[0])
            v_l, v_s = scatter_quant(v_l, v_s, pages, offs, v_new[0])
            k_all = gather_dequant(k_l, k_s, table_row, q.dtype)[None]
            v_all = gather_dequant(v_l, v_s, table_row, q.dtype)[None]
        else:
            k_l = k_l.at[pages, offs].set(k_new[0].astype(k_l.dtype))
            v_l = v_l.at[pages, offs].set(v_new[0].astype(v_l.dtype))
            k_all = k_l[table_row].reshape(1, C_log, *k_l.shape[2:])
            v_all = v_l[table_row].reshape(1, C_log, *v_l.shape[2:])
        attn = blockwise_cache_attention(
            q,
            k_all.astype(q.dtype),
            v_all.astype(q.dtype),
            positions[0],
            cfg.sliding_window,
            kv_tile,
            live_from=win_start,
            sink=sink_rows,
        )
        x = x + matmul(attn.reshape(B, Tc, -1), lp["wo"], qmm, "row")
        x = x + _mlp(x, lp, cfg, qmm=qmm)
        if quant_pool:
            return x, (k_l, v_l, k_s, v_s)
        return x, (k_l, v_l)

    if quant_pool:
        k_scales, v_scales = cache_scales
        x, (k_pool, v_pool, k_scales, v_scales) = jax.lax.scan(
            block, x, (params["layers"], k_pool, v_pool, k_scales, v_scales)
        )
        logits = _final_logits(x, params, cfg, qmm)
        return logits, k_pool, v_pool, (k_scales, v_scales)
    x, (k_pool, v_pool) = jax.lax.scan(
        block, x, (params["layers"], k_pool, v_pool)
    )
    logits = _final_logits(x, params, cfg, qmm)
    return logits, k_pool, v_pool


def decode_step_paged(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B] int32 — one new token per slot
    lengths: jnp.ndarray,  # [B] int32 — logical rows already in each slot
    k_pool: jnp.ndarray,  # [L, N, P, KH, D] — shared page pool
    v_pool: jnp.ndarray,  # [L, N, P, KH, D]
    tables: jnp.ndarray,  # [B, MB] int32 — logical block -> physical page
    kernels: Optional[bool] = None,
    cache_scales: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,
    active: Optional[jnp.ndarray] = None,  # [B] bool
    moe_impl: Optional[str] = None,
    qmm=None,  # int4 matmul impl (x, leaf, kind) -> y; see matmul()
    pool_impl=None,  # per-device pool write+attend; see ShardingPlan
    win_starts: Optional[jnp.ndarray] = None,  # [B] int32 live-window start
    sink_rows: int = 0,  # static sink rows (window+sink KV compression)
):
    """One batched decode step over the PAGED slot cache.

    Identical contract to ``decode_step`` except K/V rows live in a shared
    page pool read through per-slot tables (ops/paged_attention.py): the
    new row is scattered to (page ``tables[b, lengths[b] // P]``, offset
    ``lengths[b] % P``), and attention reads only the pages that hold valid
    rows. Inactive slots write the sacrificial page 0 (paged.py) and read
    zero rows. The caller must have BACKED row ``lengths[b]`` for every
    active slot (PageAllocator.ensure) — an unbacked entry maps page 0 and
    would silently cross-talk through the sacrificial page.

    ``cache_scales`` — (k_scales, v_scales) [L, N, P, KH] f32 marks an
    int8 POOL: rows quantize on write; attention either streams the int8
    pages through the paged kernel with scales folded into the dots
    (AIOS_TPU_INT8_RAGGED=1, ops.paged_decode_attention_int8) or
    dequantizes a gathered per-slot view on the XLA path. Returns
    (logits [B, V] fp32, k_pool', v_pool'[, (k_scales', v_scales')]).

    ``win_starts``/``sink_rows`` (window+sink KV compression,
    docs/ENGINE_PERF.md "Long-context tier"): slot b attends only rows
    < sink_rows or >= win_starts[b]; its pruned middle pages were
    released back to the pool and the stale table entries map the
    sacrificial page. win_starts[b] = 0 makes the mask a no-op.
    Unsupported with ``pool_impl`` (the dp-replicated shard_map twin —
    the engine never arms compression there).
    """
    B = tokens.shape[0]
    P = k_pool.shape[2]
    quant_pool = cache_scales is not None
    if win_starts is not None and pool_impl is not None:
        raise ValueError(
            "window+sink KV compression has no dp-replicated pool twin"
        )
    use_kernel = _use_kernels(kernels) and not quant_pool
    # int8 pool through the paged kernel (same env gate as the dense int8
    # ragged kernel): pages stream as int8 with scales folded into the dots
    use_int8_kernel = (
        _use_kernels(kernels) and quant_pool and _int8_ragged_enabled()
    )
    if active is None:
        write_pages_of = lengths
        read_lengths = lengths
        act = jnp.ones((B,), jnp.bool_)
    else:
        act = active
        write_pages_of = jnp.where(active, lengths, 0)
        read_lengths = jnp.where(active, lengths, 0)
    blk = write_pages_of // P
    pages = jnp.where(
        act, jnp.take_along_axis(tables, blk[:, None], axis=1)[:, 0], 0
    )
    offs = jnp.where(act, write_pages_of % P, P - 1)
    if win_starts is not None:
        # inactive slots read zero rows; a stale window start must not
        # survive into their (ignored) mask either
        win_starts = jnp.where(act, win_starts, 0)

    x = params["embed"][tokens][:, None, :]  # [B, 1, E]
    cos, sin = rope_tables(lengths[:, None], cfg.head_dim, cfg.rope_theta)

    def block(x, layer):
        if quant_pool:
            lp, k_l, v_l, k_s, v_s = layer
        else:
            lp, k_l, v_l = layer
            k_s = v_s = None
        q, k_new, v_new = _project_qkv(x, lp, cfg, cos, sin, qmm)
        if quant_pool and pool_impl is not None:
            attn, k_l, v_l, k_s, v_s = pool_impl(
                q[:, 0], k_new[:, 0], v_new[:, 0], k_l, v_l, k_s, v_s,
                tables, read_lengths, pages, offs,
            )
            attn = attn[:, None]
        elif quant_pool:
            k_l, k_s = scatter_quant(k_l, k_s, pages, offs, k_new[:, 0])
            v_l, v_s = scatter_quant(v_l, v_s, pages, offs, v_new[:, 0])
            attn = paged_int8_attend(
                q[:, 0], k_l, v_l, k_s, v_s, tables, read_lengths,
                window=cfg.sliding_window,
                use_int8_kernel=use_int8_kernel,
                win_starts=win_starts, sink=sink_rows,
            )[:, None]
        elif pool_impl is not None:
            attn, k_l, v_l = pool_impl(
                q[:, 0], k_new[:, 0], v_new[:, 0], k_l, v_l, tables,
                read_lengths, pages, offs,
            )
            attn = attn[:, None]
        else:
            k_l = k_l.at[pages, offs].set(k_new[:, 0].astype(k_l.dtype))
            v_l = v_l.at[pages, offs].set(v_new[:, 0].astype(v_l.dtype))
            if use_kernel:
                attn = ops.paged_decode_attention(
                    q[:, 0], k_l, v_l, tables, read_lengths,
                    window=cfg.sliding_window,
                    win_starts=win_starts,
                    sink=sink_rows if win_starts is not None else None,
                )[:, None]
            else:
                attn = ops.paged_decode_attention_reference(
                    q[:, 0], k_l, v_l, tables, read_lengths,
                    window=cfg.sliding_window,
                    win_starts=win_starts, sink=sink_rows,
                )[:, None]
        x = x + matmul(attn.reshape(B, 1, -1), lp["wo"], qmm, "row")
        x = x + _mlp(x, lp, cfg, moe_impl, qmm)
        if quant_pool:
            return x, (k_l, v_l, k_s, v_s)
        return x, (k_l, v_l)

    if quant_pool:
        k_scales, v_scales = cache_scales
        x, (k_pool, v_pool, k_scales, v_scales) = jax.lax.scan(
            block, x, (params["layers"], k_pool, v_pool, k_scales, v_scales)
        )
        logits = _final_logits(x[:, 0], params, cfg, qmm)
        return logits, k_pool, v_pool, (k_scales, v_scales)
    x, (k_pool, v_pool) = jax.lax.scan(
        block, x, (params["layers"], k_pool, v_pool)
    )
    logits = _final_logits(x[:, 0], params, cfg, qmm)
    return logits, k_pool, v_pool


def verify_step_paged(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B, T] int32 — [last_token, draft_0..draft_{T-2}]
    lengths: jnp.ndarray,  # [B] int32
    k_pool: jnp.ndarray,  # [L, N, P, KH, D]
    v_pool: jnp.ndarray,  # [L, N, P, KH, D]
    tables: jnp.ndarray,  # [B, MB] int32
    cache_scales: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,
    active: Optional[jnp.ndarray] = None,  # [B] bool
    moe_impl: Optional[str] = None,
    qmm=None,  # int4 matmul impl (x, leaf, kind) -> y; see matmul()
    win_starts: Optional[jnp.ndarray] = None,  # [B] int32 live-window start
    sink_rows: int = 0,  # static sink rows (window+sink KV compression)
):
    """``verify_step`` over the PAGED cache: the T in-flight rows scatter
    through the page tables (inactive slots -> sacrificial page 0), and
    attention reads each slot's gathered logical view with the same causal
    mask. Same saturated-slot caveat as the dense version: rows clamped at
    the cache end collide, so callers must not consume tokens from
    saturated slots. The caller must have BACKED rows
    ``lengths[b] .. lengths[b]+T-1`` for every active slot.
    ``cache_scales`` marks an int8 pool.

    Returns (logits [B, T, V] fp32, k_pool', v_pool'[, scales']).
    """
    B, T = tokens.shape
    MB = tables.shape[1]
    P = k_pool.shape[2]
    C = MB * P
    quant_pool = cache_scales is not None
    if active is None:
        active = jnp.ones((B,), jnp.bool_)
    offs_t = jnp.arange(T)[None, :]
    positions = lengths[:, None] + offs_t  # [B, T]
    rows = jnp.minimum(positions, C - 1)
    blk = rows // P
    pages = jnp.take_along_axis(tables, blk, axis=1)  # [B, T] (tiny gather)
    pages = jnp.where(active[:, None], pages, 0)
    offs = jnp.where(active[:, None], rows % P, P - 1)
    qpos = jnp.where(active[:, None], positions, 0)
    cols = jnp.arange(C)[None, None, :]
    mask = cols <= qpos[..., None]  # [B, T, C]
    if cfg.sliding_window is not None:
        mask = mask & (cols > (qpos[..., None] - cfg.sliding_window))
    if win_starts is not None:
        # window+sink KV compression: the pruned middle [sink, win_start)
        # must not score — the verify rows themselves always land past
        # the live window start (they extend the trailing window)
        ws = jnp.where(active, win_starts, 0)
        mask = mask & (
            (cols < sink_rows) | (cols >= ws[:, None, None])
        )

    x = params["embed"][tokens]  # [B, T, E]
    cos, sin = rope_tables(positions, cfg.head_dim, cfg.rope_theta)

    def block(x, layer):
        if quant_pool:
            lp, k_l, v_l, k_s, v_s = layer
        else:
            lp, k_l, v_l = layer
            k_s = v_s = None
        q, k_new, v_new = _project_qkv(x, lp, cfg, cos, sin, qmm)
        if quant_pool:
            k_l, k_s = scatter_quant(k_l, k_s, pages, offs, k_new)
            v_l, v_s = scatter_quant(v_l, v_s, pages, offs, v_new)
            k_all = gather_dequant(k_l, k_s, tables, q.dtype)
            v_all = gather_dequant(v_l, v_s, tables, q.dtype)
        else:
            k_l = k_l.at[pages, offs].set(k_new.astype(k_l.dtype))
            v_l = v_l.at[pages, offs].set(v_new.astype(v_l.dtype))
            # logical per-slot views; same HBM bytes as the dense masked
            # read
            k_all = k_l[tables].reshape(B, C, *k_l.shape[2:])
            v_all = v_l[tables].reshape(B, C, *v_l.shape[2:])
        attn = gqa_attention(q, k_all, v_all, mask)
        x = x + matmul(attn.reshape(B, T, -1), lp["wo"], qmm, "row")
        x = x + _mlp(x, lp, cfg, moe_impl, qmm)
        if quant_pool:
            return x, (k_l, v_l, k_s, v_s)
        return x, (k_l, v_l)

    if quant_pool:
        k_scales, v_scales = cache_scales
        x, (k_pool, v_pool, k_scales, v_scales) = jax.lax.scan(
            block, x, (params["layers"], k_pool, v_pool, k_scales, v_scales)
        )
        logits = _final_logits(x, params, cfg, qmm)
        return logits, k_pool, v_pool, (k_scales, v_scales)
    x, (k_pool, v_pool) = jax.lax.scan(
        block, x, (params["layers"], k_pool, v_pool)
    )
    logits = _final_logits(x, params, cfg, qmm)
    return logits, k_pool, v_pool


def verify_step(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B, T] int32 — [last_token, draft_0..draft_{T-2}]
    lengths: jnp.ndarray,  # [B] int32 — tokens already in each slot's cache
    k_cache: jnp.ndarray,  # [L, B, C, KH, D]
    v_cache: jnp.ndarray,  # [L, B, C, KH, D]
    kernels: Optional[bool] = None,
    cache_scales: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,
    active: Optional[jnp.ndarray] = None,  # [B] bool
    moe_impl: Optional[str] = None,
    qmm=None,  # int4 matmul impl (x, leaf, kind) -> y; see matmul()
):
    """Batched multi-token decode for speculative verification.

    The T tokens per slot are the pending ``last_token`` followed by T-1
    draft tokens; all T K/V rows are written at rows
    ``lengths[b] .. lengths[b]+T-1`` in one pass and every row of logits
    comes back, so the caller can accept the longest draft prefix that
    matches the model's own predictions (engine/spec.py). Because batched
    decode is weight-bandwidth-bound, verifying T positions costs roughly
    the same HBM traffic as a 1-token decode step — accepted drafts are
    nearly free tokens. This is the TPU replacement for the speculative /
    lookahead decoding the reference's llama.cpp backend exposes via
    llama-server's ``--draft`` options (SURVEY.md section 2.3).

    Same conventions as ``decode_step``: ``active`` gating writes inactive
    slots' rows to the sacrificial last cache row and exposes zero cache
    rows to them; ``cache_scales`` marks an int8 KV cache. Queries attend
    causally: query t of slot b sees cache cols ``<= lengths[b]+t`` (its own
    row included — written before the read), inside the sliding window.

    Rows written past ``C-2`` collapse onto the last cache row (scatter
    order is undefined there) — callers must clamp draft counts so accepted
    rows stay ``<= C-2``; unaccepted garbage rows are masked by ``lengths``
    afterwards. A slot already AT ``lengths == C-1`` collapses all T writes
    (including row 0's) onto the raced last row, so its outputs are
    indeterminate: callers must not consume tokens from saturated slots
    (the batcher retires them at the cache end; ``generate`` stops
    consuming mid-dispatch). Returns (logits [B, T, V] fp32, k_cache',
    v_cache'[, scales']).
    """
    B, T = tokens.shape
    C = k_cache.shape[2]
    quant_cache = cache_scales is not None
    if active is None:
        active = jnp.ones((B,), jnp.bool_)
    offs = jnp.arange(T)[None, :]  # [1, T]
    # absolute position of each query row (garbage for inactive slots)
    positions = lengths[:, None] + offs  # [B, T]
    write_rows = jnp.where(
        active[:, None], jnp.minimum(positions, C - 1), C - 1
    )  # [B, T]
    # inactive slots expose only (overwritten-before-read) col 0, matching
    # the decode_step convention
    qpos = jnp.where(active[:, None], positions, 0)  # [B, T]
    # Ragged multi-query kernel: DMAs only the blocks holding valid rows,
    # same crossover rule as decode_step's single-query kernel
    # (_use_ragged_kernel). bf16 caches take the plain kernel; int8-KV
    # routes through the int8 variant (scales folded into the dots, same
    # AIOS_TPU_INT8_RAGGED gate as decode — drafts score at half the
    # cache bandwidth). Saturated slots run through whichever path the
    # batch takes with clamped/colliding rows — their outputs are
    # unconsumed by the saturation contract above; the kernel clamps its
    # DMA bound at the cache end so the VALID slots stay exact.
    routed = _use_ragged_kernel(
        kernels, C, cfg, quant_cache,
        quant_kernel_ok=_int8_ragged_enabled(),
    )
    use_kernel = routed and not quant_cache
    use_int8_kernel = routed and quant_cache
    if use_kernel or use_int8_kernel:
        mask = None
        strides = active.astype(jnp.int32)
        read_base = jnp.where(active, lengths, 0)
    else:
        cols = jnp.arange(C)[None, None, :]  # [1, 1, C]
        mask = cols <= qpos[..., None]  # [B, T, C]
        if cfg.sliding_window is not None:
            mask = mask & (cols > (qpos[..., None] - cfg.sliding_window))

    x = params["embed"][tokens]  # [B, T, E]
    cos, sin = rope_tables(positions, cfg.head_dim, cfg.rope_theta)
    batch_idx = jnp.arange(B)[:, None]  # [B, 1] pairs with write_rows [B, T]

    def block(x, layer):
        if quant_cache:
            lp, k_l, v_l, k_s, v_s = layer
        else:
            lp, k_l, v_l = layer
            k_s = v_s = None
        q, k_new, v_new = _project_qkv(x, lp, cfg, cos, sin, qmm)
        if quant_cache:
            kq, ks_new = quantize_kv(k_new)  # [B, T, KH, D], [B, T, KH]
            vq, vs_new = quantize_kv(v_new)
            k_l = k_l.at[batch_idx, write_rows].set(kq)
            v_l = v_l.at[batch_idx, write_rows].set(vq)
            k_s = k_s.at[batch_idx, write_rows].set(ks_new)
            v_s = v_s.at[batch_idx, write_rows].set(vs_new)
            if use_int8_kernel:
                attn = ops.multiquery_decode_attention_int8(
                    q, k_l, v_l, k_s, v_s, read_base, strides,
                    window=cfg.sliding_window,
                )
            else:
                attn = gqa_attention(
                    q,
                    dequantize_kv(k_l, k_s, q.dtype),
                    dequantize_kv(v_l, v_s, q.dtype),
                    mask,
                )
        else:
            k_l = k_l.at[batch_idx, write_rows].set(k_new.astype(k_l.dtype))
            v_l = v_l.at[batch_idx, write_rows].set(v_new.astype(v_l.dtype))
            if use_kernel:
                attn = ops.multiquery_decode_attention(
                    q, k_l, v_l, read_base, strides,
                    window=cfg.sliding_window,
                )
            else:
                attn = gqa_attention(q, k_l, v_l, mask)
        x = x + matmul(attn.reshape(B, T, -1), lp["wo"], qmm, "row")
        x = x + _mlp(x, lp, cfg, moe_impl, qmm)
        if quant_cache:
            return x, (k_l, v_l, k_s, v_s)
        return x, (k_l, v_l)

    if quant_cache:
        k_scales, v_scales = cache_scales
        x, (k_cache, v_cache, k_scales, v_scales) = jax.lax.scan(
            block, x, (params["layers"], k_cache, v_cache, k_scales, v_scales)
        )
    else:
        x, (k_cache, v_cache) = jax.lax.scan(
            block, x, (params["layers"], k_cache, v_cache)
        )
    logits = _final_logits(x, params, cfg, qmm)
    if quant_cache:
        return logits, k_cache, v_cache, (k_scales, v_scales)
    return logits, k_cache, v_cache


# ---------------------------------------------------------------------------
# Initialization
# ---------------------------------------------------------------------------


def init_params(
    cfg: ModelConfig, key: jax.Array, dtype=jnp.bfloat16
) -> Params:
    """Random params (scaled-normal init) — for tests, benches and training."""
    keys = iter(jax.random.split(key, 16))

    def normal(shape, scale=0.02):
        return (jax.random.normal(next(keys), shape, jnp.float32) * scale).astype(
            dtype
        )

    L, E, F, D = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size, cfg.head_dim
    layers = {
        "attn_norm": jnp.ones((L, E), dtype),
        "ffn_norm": jnp.ones((L, E), dtype),
        "wq": normal((L, E, cfg.q_dim)),
        "wk": normal((L, E, cfg.kv_dim)),
        "wv": normal((L, E, cfg.kv_dim)),
        "wo": normal((L, cfg.q_dim, E)),
    }
    if cfg.moe:
        X, Fm = cfg.num_experts, cfg.expert_dim
        layers["w_router"] = normal((L, E, X))
        layers["we_gate"] = normal((L, X, E, Fm))
        layers["we_up"] = normal((L, X, E, Fm))
        layers["we_down"] = normal((L, X, Fm, E))
    else:
        layers["w_gate"] = normal((L, E, F))
        layers["w_up"] = normal((L, E, F))
        layers["w_down"] = normal((L, F, E))
    if cfg.qk_norm:
        layers["q_norm"] = jnp.ones((L, D), dtype)
        layers["k_norm"] = jnp.ones((L, D), dtype)
    params: Params = {
        "embed": normal((cfg.vocab_size, E)),
        "layers": layers,
        "final_norm": jnp.ones((E,), dtype),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = normal((E, cfg.vocab_size))
    return params


def init_quantized_params(
    cfg: ModelConfig, key: jax.Array, fuse: bool = True, dtype=jnp.bfloat16,
    mode: str = "int8",
) -> Params:
    """Random params built DIRECTLY in the quantized serving layout
    (``quantize_params`` output shapes) — the bf16 weights never
    materialize, so a 7B model inits in ~7 GB of HBM instead of ~22 GB
    (int4: ~3.5 GB). Benchmarks and dry-runs only: decode throughput is
    weight-value-independent (same bytes streamed, same FLOPs), and each
    quantized tensor tiles one random 2-D block over the layer axis to
    keep the init's own peak memory at one layer's worth.
    """
    keys = iter(jax.random.split(key, 16))
    L, E, F, D = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size, cfg.head_dim
    V = cfg.vocab_size

    def qleaf(shape, force_int8: bool = False):
        if mode == "int4" and not force_int8:
            from ..ops.int4_matmul import (
                kernel_supported,
                pick_group,
                supports_int4,
            )

            K, N = shape[-2], shape[-1]
            # same eligibility rule as quantize_params.quant_leaf
            if supports_int4(K, N) and (
                kernel_supported(K, N, pick_group(K)) or not ops.use_pallas()
            ):
                g = pick_group(K)
                block = jax.random.randint(
                    next(keys), (K // 2, N), 0, 256, jnp.int32
                ).astype(jnp.uint8)
                q = jnp.asarray(jnp.broadcast_to(block, shape[:-2] + (K // 2, N)))
                s_shape = shape[:-2] + (K // g, 1, N)
                return {"q4": q, "s4": jnp.full(s_shape, 0.02 / 7.0, jnp.float32)}
        block = jax.random.randint(
            next(keys), shape[-2:], -127, 128, jnp.int32
        ).astype(jnp.int8)
        q = jnp.asarray(jnp.broadcast_to(block, shape))
        s_shape = shape[:-2] + (1, shape[-1])
        return {"q": q, "s": jnp.full(s_shape, 0.02 / 127.0, jnp.float32)}

    layers = {
        "attn_norm": jnp.ones((L, E), dtype),
        "ffn_norm": jnp.ones((L, E), dtype),
    }
    if cfg.qk_norm:
        layers["q_norm"] = jnp.ones((L, D), dtype)
        layers["k_norm"] = jnp.ones((L, D), dtype)
    if fuse:
        layers["w_qkv"] = qleaf((L, E, cfg.q_dim + 2 * cfg.kv_dim))
        layers["wo"] = qleaf((L, cfg.q_dim, E))
    else:
        layers["wq"] = qleaf((L, E, cfg.q_dim))
        layers["wk"] = qleaf((L, E, cfg.kv_dim))
        layers["wv"] = qleaf((L, E, cfg.kv_dim))
        layers["wo"] = qleaf((L, cfg.q_dim, E))
    if cfg.moe:
        X, Fm = cfg.num_experts, cfg.expert_dim
        layers["w_router"] = (
            jax.random.normal(next(keys), (L, E, X), jnp.float32) * 0.02
        ).astype(dtype)
        # expert leaves stay int8 in int4 mode (the gathered-expert decode
        # path is int8-specialized, matching quantize_params)
        if fuse:
            layers["we_gateup"] = qleaf((L, X, E, 2 * Fm), force_int8=True)
            layers["we_down"] = qleaf((L, X, Fm, E), force_int8=True)
        else:
            layers["we_gate"] = qleaf((L, X, E, Fm), force_int8=True)
            layers["we_up"] = qleaf((L, X, E, Fm), force_int8=True)
            layers["we_down"] = qleaf((L, X, Fm, E), force_int8=True)
    elif fuse:
        layers["w_gateup"] = qleaf((L, E, 2 * F))
        layers["w_down"] = qleaf((L, F, E))
    else:
        layers["w_gate"] = qleaf((L, E, F))
        layers["w_up"] = qleaf((L, E, F))
        layers["w_down"] = qleaf((L, F, E))
    return {
        "embed": (
            jax.random.normal(next(keys), (V, E), jnp.float32) * 0.02
        ).astype(dtype),
        "layers": layers,
        "final_norm": jnp.ones((E,), dtype),
        "lm_head": qleaf((E, V)),
    }


def serving_weight_bytes(params: Params) -> int:
    """Bytes of weight data streamed from HBM per decode step (every
    matmul weight + scales; embedding gather excluded — one row)."""
    total = 0
    for leaf in jax.tree.leaves(params["layers"]) + jax.tree.leaves(
        params.get("lm_head", [])
    ):
        total += leaf.size * leaf.dtype.itemsize
    return total


def init_kv_cache(
    cfg: ModelConfig, num_slots: int, max_len: int, dtype=jnp.bfloat16
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    shape = (cfg.num_layers, num_slots, max_len, cfg.num_kv_heads, cfg.head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def init_kv_scales(
    cfg: ModelConfig, num_slots: int, max_len: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-(row, kv-head) scales for an int8 KV cache."""
    shape = (cfg.num_layers, num_slots, max_len, cfg.num_kv_heads)
    return jnp.ones(shape, jnp.float32), jnp.ones(shape, jnp.float32)


def paged_int8_attend(q, k_l, v_l, k_s, v_s, tables, lengths, *, window,
                      use_int8_kernel, win_starts=None, sink=0):
    """Decode attention over an int8 page pool for ONE layer ([B,H,D] ->
    [B,H,D]): the kernel path streams int8 pages with scales folded into
    the dots; the XLA path dequantizes a gathered per-slot view. The single
    source of truth for the int8-pool read — decode_step_paged AND the
    dp-replicated shard_map body (sharding.paged_pool_impl) both call it,
    so mask/window semantics cannot drift between them.
    ``win_starts``/``sink`` apply the window+sink compressed mask."""
    if use_int8_kernel:
        return ops.paged_decode_attention_int8(
            q, k_l, v_l, k_s, v_s, tables, lengths, window=window,
            win_starts=win_starts,
            sink=sink if win_starts is not None else None,
        )
    C = tables.shape[1] * k_l.shape[1]
    cols = jnp.arange(C)[None, :]
    mask = cols <= lengths[:, None]
    if window is not None:
        mask = mask & (cols > (lengths[:, None] - window))
    if win_starts is not None:
        mask = mask & ((cols < sink) | (cols >= win_starts[:, None]))
    return gqa_attention(
        q[:, None],
        gather_dequant(k_l, k_s, tables, q.dtype),
        gather_dequant(v_l, v_s, tables, q.dtype),
        mask[:, None, :],
    )[:, 0]


def scatter_quant(
    pool: jnp.ndarray,  # [N, P, KH, D] int8
    scales: jnp.ndarray,  # [N, P, KH] f32
    pages: jnp.ndarray,
    offs: jnp.ndarray,
    rows: jnp.ndarray,  # [..., KH, D] new rows (pages/offs broadcast-match)
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Quantize rows and scatter values + scales into an int8 page pool —
    the single write-side quantization contract for every paged path."""
    q, s = quantize_kv(rows)
    return pool.at[pages, offs].set(q), scales.at[pages, offs].set(s)


def gather_dequant(
    pool: jnp.ndarray,  # [N, P, KH, D] int8
    scales: jnp.ndarray,  # [N, P, KH] f32
    tables: jnp.ndarray,  # [..., MB] int32
    dtype,
) -> jnp.ndarray:
    """Materialize dequantized logical views [..., MB*P, KH, D] from an
    int8 page pool — the read-side twin of ``scatter_quant``."""
    out = dequantize_kv(pool[tables], scales[tables], dtype)
    MB = tables.shape[-1]
    P, KH, D = pool.shape[1], pool.shape[2], pool.shape[3]
    return out.reshape(*tables.shape[:-1], MB * P, KH, D)


def quantize_kv(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric int8 over the head dim. x [..., D] -> (int8 [..., D], f32 [...])."""
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(
        jnp.round(xf / scale[..., None]), -127, 127
    ).astype(jnp.int8)
    return q, scale


def dequantize_kv(q: jnp.ndarray, scale: jnp.ndarray, dtype=jnp.bfloat16):
    return (q.astype(jnp.float32) * scale[..., None].astype(jnp.float32)).astype(
        dtype
    )
