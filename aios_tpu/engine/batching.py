"""Continuous batching: many concurrent requests over one decode graph.

The reference serializes requests per model into llama-server's HTTP queue
and caps concurrent AI work at 3 (autonomy.rs Semaphore(3), SURVEY.md
section 2.4); here the 8+ agents' requests land in ONE batched decode step —
the scheduler assigns each request a cache slot, prefills it, and every
decode dispatch advances all active slots together. Tokens stream to each
caller through a per-request queue as dispatches complete.

Scheduling policy (single background thread, dispatch-level granularity):
  * admit waiting requests whenever slots are free (prefill immediately);
  * decode in chunks of `chunk_steps` tokens per dispatch (amortizes
    host<->device round trips); a smaller chunk is used when requests are
    waiting so admission latency stays low;
  * requests retire on EOS/stop token, max_tokens, or a full cache slot.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from .engine import TPUEngine

_END = object()


@dataclass
class Request:
    prompt_ids: List[int]
    max_tokens: int = 256
    temperature: float = 0.7
    top_p: float = 0.95
    stop_ids: Tuple[int, ...] = ()
    request_id: str = ""


@dataclass
class _Live:
    req: Request
    slot: int
    produced: int = 0
    out_q: "queue.Queue" = field(default_factory=queue.Queue)
    first_token_at: float = 0.0
    submitted_at: float = 0.0
    done: bool = False


class RequestHandle:
    """Caller-side view of an in-flight request (blocking token iterator)."""

    def __init__(self, live: _Live):
        self._live = live

    def __iter__(self):
        while True:
            item = self._live.out_q.get()
            if item is _END:
                return
            yield item

    def tokens(self) -> List[int]:
        return list(self)

    @property
    def ttft_ms(self) -> float:
        if not self._live.first_token_at:
            return 0.0
        return (self._live.first_token_at - self._live.submitted_at) * 1000.0


class ContinuousBatcher:
    """Background scheduler marrying a request queue to engine slots."""

    def __init__(
        self,
        engine: TPUEngine,
        chunk_steps: int = 8,
        admit_chunk_steps: int = 2,
    ) -> None:
        self.engine = engine
        self.chunk_steps = chunk_steps
        self.admit_chunk_steps = admit_chunk_steps
        self._waiting: "queue.Queue[_Live]" = queue.Queue()
        self._live: Dict[int, _Live] = {}  # slot -> request
        self._wake = threading.Event()
        self._stop = False
        self._ids = itertools.count()
        self._lock = threading.Lock()
        self.completed = 0
        self._thread = threading.Thread(
            target=self._run, name="continuous-batcher", daemon=True
        )
        self._thread.start()

    # -- public API ---------------------------------------------------------

    def submit(self, req: Request) -> RequestHandle:
        if not req.request_id:
            req.request_id = f"req-{next(self._ids)}"
        live = _Live(req=req, slot=-1, submitted_at=time.monotonic())
        self._waiting.put(live)
        self._wake.set()
        return RequestHandle(live)

    def generate(self, prompt_ids: Sequence[int], **kw) -> List[int]:
        return self.submit(Request(prompt_ids=list(prompt_ids), **kw)).tokens()

    def shutdown(self) -> None:
        self._stop = True
        self._wake.set()
        self._thread.join(timeout=10)

    @property
    def active_count(self) -> int:
        with self._lock:
            return len(self._live)

    # -- scheduler loop -----------------------------------------------------

    def _admit(self) -> None:
        while True:
            free = self.engine.free_slots()
            if not free:
                return
            try:
                live = self._waiting.get_nowait()
            except queue.Empty:
                return
            slot = free[0]
            live.slot = slot
            first = self.engine.prefill(
                slot,
                live.req.prompt_ids,
                temperature=live.req.temperature,
                top_p=live.req.top_p,
            )
            live.first_token_at = time.monotonic()
            with self._lock:
                self._live[slot] = live
            self._emit(live, first)

    def _emit(self, live: _Live, token: int) -> None:
        live.produced += 1
        live.out_q.put(token)
        hit_stop = token in live.req.stop_ids
        out_of_budget = live.produced >= live.req.max_tokens
        out_of_cache = (
            self.engine.slot_length(live.slot) >= self.engine.max_context - 1
        )
        if hit_stop or out_of_budget or out_of_cache:
            self._finish(live)

    def _finish(self, live: _Live) -> None:
        live.done = True
        with self._lock:
            self._live.pop(live.slot, None)
        self.engine.release(live.slot)
        self.completed += 1
        # _END goes last: when a consumer unblocks, all scheduler-side state
        # (slot freed, counters bumped) is already final
        live.out_q.put(_END)

    def _run(self) -> None:
        while not self._stop:
            self._admit()
            with self._lock:
                slots = {s: l for s, l in self._live.items()}
            if not slots:
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            # keep admission latency low when someone is waiting
            n = self.admit_chunk_steps if not self._waiting.empty() else self.chunk_steps
            max_budget = min(
                (l.req.max_tokens - l.produced for l in slots.values()),
                default=n,
            )
            n = max(1, min(n, max_budget))
            tokens = self.engine.step(n)  # [n, num_slots]
            for step_row in tokens:
                for slot, live in list(slots.items()):
                    if live.done:
                        continue
                    self._emit(live, int(step_row[slot]))
