"""Mesh construction and parameter/cache sharding plans.

Megatron-style tensor parallelism expressed as GSPMD annotations: we place
NamedShardings on params and KV caches, and XLA inserts the ICI collectives
(all-reduce after row-parallel matmuls, all-gather for the vocab-sharded
embedding) — no hand-written collective calls on the decode path, per the
scaling-book recipe: pick a mesh, annotate, let XLA do the rest.

Axes:
  dp — data/replica axis: batch slots in decode, batch in training
  sp — sequence axis: ring-attention sequence parallelism (long context)
  ep — expert axis: MoE experts sharded across chips (engine/moe.py); the
       dense-MoE einsum contracts the expert axis, so GSPMD inserts one
       psum over ep per MoE layer — expert parallelism with no explicit
       dispatch collectives
  tp — model axis: attention heads + FFN hidden sharded across chips
       (innermost: the per-matmul allreduce rides the fastest ICI links)

Equivalent role in the reference: none (single-process llama.cpp); this is
the "Mistral-7B tensor-parallel decode across 4 chips (ICI all-reduce)"
benchmark config of BASELINE.md.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..engine.config import ModelConfig


def build_mesh(
    n_devices: Optional[int] = None,
    *,
    dp: int = 1,
    sp: int = 1,
    ep: int = 1,
    tp: Optional[int] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a (dp, sp, ep, tp) mesh. Unspecified tp absorbs the rest."""
    devices = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    if tp is None:
        assert n % (dp * sp * ep) == 0, (n, dp, sp, ep)
        tp = n // (dp * sp * ep)
    assert dp * sp * ep * tp == n, f"mesh {dp}x{sp}x{ep}x{tp} != {n} devices"
    arr = np.asarray(devices).reshape(dp, sp, ep, tp)
    return Mesh(arr, axis_names=("dp", "sp", "ep", "tp"))


# Partition rules for the engine params pytree (path suffix -> spec).
# Column-parallel projections shard the output dim on tp; row-parallel ones
# shard the input dim, and GSPMD inserts the psum on their outputs.
PARAM_RULES: Dict[str, P] = {
    "embed": P("tp", None),  # vocab-sharded
    "layers/attn_norm": P(None, None),
    "layers/ffn_norm": P(None, None),
    "layers/q_norm": P(None, None),
    "layers/k_norm": P(None, None),
    "layers/wq": P(None, None, "tp"),
    "layers/wk": P(None, None, "tp"),
    "layers/wv": P(None, None, "tp"),
    "layers/wo": P(None, "tp", None),
    "layers/w_gate": P(None, None, "tp"),
    "layers/w_up": P(None, None, "tp"),
    "layers/w_down": P(None, "tp", None),
    # MoE leaves [L, X, in, out]: experts over ep, expert-FFN hidden over tp
    # (the router is tiny and stays replicated)
    "layers/w_router": P(None, None, None),
    "layers/we_gate": P(None, "ep", None, "tp"),
    "layers/we_up": P(None, "ep", None, "tp"),
    "layers/we_gateup": P(None, "ep", None, "tp"),
    "layers/we_down": P(None, "ep", "tp", None),
    "final_norm": P(None),
    "lm_head": P(None, "tp"),
}

# KV cache [L, slots, C, KH, D]: slots over dp, kv heads over tp.
CACHE_SPEC = P(None, "dp", None, "tp", None)
# int8 KV-cache scales [L, slots, C, KH] ride the same placement.
CACHE_SCALE_SPEC = P(None, "dp", None, "tp")
# Context-sharded variant: the C axis additionally splits over sp, so one
# slot's KV can exceed a single chip's HBM (long-context serving). XLA
# partitions the decode attention over the sharded contraction itself —
# per-shard partial max/denominator/accumulator with psums over sp, the
# flash-decoding-across-chips pattern — while row writes stay local to the
# owning shard (verified: no cache-sized all-gathers in the lowered HLO).
CACHE_SPEC_SEQ = P(None, "dp", "sp", "tp", None)
CACHE_SCALE_SPEC_SEQ = P(None, "dp", "sp", "tp")


@dataclass
class ShardingPlan:
    """Placement helper handed to TPUEngine / the trainer."""

    mesh: Mesh

    def spec_for(self, path: str) -> P:
        if path in PARAM_RULES:
            return PARAM_RULES[path]
        # int8 serving leaves {"q", "s"} (model.quantize_params fuse=False):
        # the int8 tensor shards exactly like the dense weight it replaces;
        # the per-output-channel scale is size 1 on the contraction dim
        # (axis -2), so its spec is the weight's with that axis unsharded.
        if path.endswith(("/q", "/s")):
            base = PARAM_RULES.get(path[:-2])
            if base is not None:
                if path.endswith("/q"):
                    return base
                return P(*base[:-2], None, base[-1])
        raise KeyError(f"no partition rule for param {path!r}")

    def params_shardings(self, params) -> Dict:
        def walk(tree, prefix=""):
            out = {}
            for k, v in tree.items():
                path = f"{prefix}{k}"
                if isinstance(v, dict):
                    out[k] = walk(v, path + "/")
                else:
                    out[k] = NamedSharding(self.mesh, self.spec_for(path))
            return out

        return walk(params)

    def put_params(self, params):
        shardings = self.params_shardings(params)
        return jax.tree.map(
            lambda x, s: jax.device_put(jax.numpy.asarray(x), s), params, shardings
        )

    def put_cache(self, cache, seq_shard: bool = False):
        spec = CACHE_SPEC_SEQ if seq_shard else CACHE_SPEC
        return jax.device_put(cache, NamedSharding(self.mesh, spec))

    def put_cache_scales(self, scales, seq_shard: bool = False):
        spec = CACHE_SCALE_SPEC_SEQ if seq_shard else CACHE_SCALE_SPEC
        return jax.device_put(scales, NamedSharding(self.mesh, spec))

    def ragged_attention(self, window: Optional[int], use_kernel: bool):
        """Per-device ragged decode attention under shard_map.

        Attention is head- and slot-local, so with q sharded (dp, tp) and
        the per-layer cache (dp, none, tp) every device attends its own
        [B/dp, C, KH/tp, D] shard with ZERO collectives — the Pallas ragged
        kernel (ops/decode_attention.py) runs per device exactly as on one
        chip. ``use_kernel=False`` swaps in the jnp reference body (CPU
        virtual meshes; numerics identical), which is how the dryrun and the
        test suite exercise this path without TPU hardware.

        Returns attn(q [B,H,D], k_l [B,C,KH,D], v_l [B,C,KH,D], lengths [B])
        -> [B, H, D], for model.decode_step's ``attn_impl`` hook.
        """
        from jax.experimental.shard_map import shard_map

        from .. import ops

        def local(q, k_l, v_l, lengths):
            if use_kernel:
                return ops.decode_attention(q, k_l, v_l, lengths, window=window)
            return ops.decode_attention_reference(
                q, k_l, v_l, lengths, window=window
            )

        return shard_map(
            local,
            mesh=self.mesh,
            in_specs=(
                P("dp", "tp", None),
                P("dp", None, "tp", None),
                P("dp", None, "tp", None),
                P("dp"),
            ),
            out_specs=P("dp", "tp", None),
            check_rep=False,
        )

    @property
    def tp(self) -> int:
        return self.mesh.shape["tp"]

    @property
    def dp(self) -> int:
        return self.mesh.shape["dp"]

    @property
    def sp(self) -> int:
        return self.mesh.shape["sp"]

    @property
    def ep(self) -> int:
        return self.mesh.shape.get("ep", 1)

    def validate(self, cfg: ModelConfig, num_slots: int) -> None:
        tp, dp, ep = self.tp, self.dp, self.ep
        assert cfg.num_kv_heads % tp == 0, (
            f"kv heads {cfg.num_kv_heads} not divisible by tp={tp}"
        )
        assert cfg.num_heads % tp == 0
        if cfg.moe:
            assert cfg.num_experts % ep == 0, (
                f"experts {cfg.num_experts} not divisible by ep={ep}"
            )
            assert cfg.expert_dim % tp == 0
        else:
            assert ep == 1, "ep>1 requires a MoE config"
            assert cfg.intermediate_size % tp == 0
        assert num_slots % dp == 0, f"slots {num_slots} not divisible by dp={dp}"


def single_device_plan() -> Optional[ShardingPlan]:
    """None when there is nothing to shard (1 device)."""
    if len(jax.devices()) == 1:
        return None
    return ShardingPlan(build_mesh())
