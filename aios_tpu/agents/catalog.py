"""The 10 system agents.

Reference parity (agent-core/python/aios_agent/agents/, 5,078 LoC): same set
of 10 agent types with the same duty cycles — system (health loop 30 s),
network (connectivity loop 60 s against 8.8.8.8/1.1.1.1/9.9.9.9), security
(intrusion/rootkit/ports/integrity), package, monitoring (30 s collection +
rolling-baseline anomaly detection over 100 points), learning (5-minute
learning cycle over patterns/decisions), storage, task (general executor),
web, creator. Task handling is keyword dispatch over call_tool/think, as in
the reference agents.
"""

from __future__ import annotations

import collections
import json
import statistics
import time
from typing import Any, Dict, List

from ..proto_gen import common_pb2
from .base import BaseAgent


class SystemAgent(BaseAgent):
    """Service/process health keeper (reference system.py)."""

    periodic_interval = 30.0

    def get_agent_type(self) -> str:
        return "system"

    def get_capabilities(self) -> List[str]:
        return ["fs.read", "fs.write", "process.read", "process.manage",
                "service.read", "service.manage", "monitor.read", "hw.read"]

    def get_tool_namespaces(self) -> List[str]:
        return ["fs", "process", "service", "monitor", "hw"]

    def periodic(self) -> None:
        health = self.call_tool("self.health")
        if not health["success"]:
            return
        down = [s for s, state in health["output"]["services"].items()
                if state == "down"]
        if down:
            self.push_event("system.services_down", {"services": down},
                            critical=True)
        self.update_metric("system.services_down", float(len(down)))

    def handle_task(self, task: Dict[str, Any]) -> Dict[str, Any]:
        desc = task["description"].lower()
        if "restart" in desc:
            name = task["input"].get("service") or _extract_service_name(desc)
            if not name:
                raise ValueError("no service name found in task")
            status = self.call_tool("service.status", {"name": name})
            result = self.call_tool("service.restart", {"name": name},
                                    reason=task["description"])
            if not result["success"]:
                raise RuntimeError(result["error"])
            after = self.call_tool("service.status", {"name": name})
            return {"service": name, "before": status["output"],
                    "after": after["output"]}
        if "process" in desc and ("list" in desc or "top" in desc):
            return self.call_tool("process.list", {"limit": 15})["output"]
        if "hardware" in desc or "hw" in desc:
            return self.call_tool("hw.info")["output"]
        if "status" in desc or "health" in desc or "check" in desc:
            return {
                "cpu": self.call_tool("monitor.cpu")["output"],
                "memory": self.call_tool("monitor.memory")["output"],
                "services": self.call_tool("self.health")["output"],
            }
        return self._generic(task)

    def _generic(self, task):
        out = self.call_tool("monitor.cpu")
        return {"note": "system agent default health snapshot",
                "cpu": out["output"]}


class NetworkAgent(BaseAgent):
    """Connectivity watchdog + firewall hands (reference network.py)."""

    periodic_interval = 60.0
    PROBE_HOSTS = ("8.8.8.8", "1.1.1.1", "9.9.9.9")

    def get_agent_type(self) -> str:
        return "network"

    def get_capabilities(self) -> List[str]:
        return ["net.diagnose", "net.scan", "firewall.read",
                "firewall.manage", "monitor.read"]

    def get_tool_namespaces(self) -> List[str]:
        return ["net", "firewall", "monitor"]

    def periodic(self) -> None:
        reachable = 0
        for host in self.PROBE_HOSTS:
            res = self.call_tool("net.ping", {"host": host, "count": 1})
            if res["success"] and res["output"].get("reachable"):
                reachable += 1
        self.update_metric("network.reachable_probes", float(reachable))
        if reachable == 0:
            self.push_event("network.offline",
                            {"probes": list(self.PROBE_HOSTS)}, critical=True)

    def handle_task(self, task: Dict[str, Any]) -> Dict[str, Any]:
        desc = task["description"].lower()
        if "interface" in desc:
            return self.call_tool("net.interfaces")["output"]
        if "ping" in desc or "connectivity" in desc or "reachab" in desc:
            results = {}
            for host in task["input"].get("hosts", self.PROBE_HOSTS):
                results[host] = self.call_tool("net.ping",
                                               {"host": host})["output"]
            return {"probes": results}
        if "dns" in desc or "resolve" in desc:
            host = task["input"].get("host", "example.com")
            return self.call_tool("net.dns", {"host": host})["output"]
        if "port" in desc or "scan" in desc:
            return self.call_tool("net.port_scan",
                                  task["input"] or {})["output"]
        if "firewall" in desc:
            return self.call_tool("firewall.rules")["output"]
        return {"interfaces": self.call_tool("net.interfaces")["output"]}


class SecurityAgent(BaseAgent):
    """Scans + audit monitoring (reference security.py)."""

    periodic_interval = 300.0

    def get_agent_type(self) -> str:
        return "security"

    def get_capabilities(self) -> List[str]:
        return ["sec.audit", "sec.admin", "fs.read", "process.read",
                "monitor.read", "net.scan"]

    def get_tool_namespaces(self) -> List[str]:
        return ["sec", "monitor", "net"]

    def periodic(self) -> None:
        audit = self.call_tool("sec.audit")
        if audit["success"] and not audit["output"].get("chain_valid", True):
            self.push_event("security.audit_chain_broken", audit["output"],
                            critical=True)

    def handle_task(self, task: Dict[str, Any]) -> Dict[str, Any]:
        desc = task["description"].lower()
        if "rootkit" in desc:
            return self.call_tool("sec.scan_rootkits")["output"]
        if "integrity" in desc:
            path = task["input"].get("path", "/etc")
            return self.call_tool("sec.file_integrity", {"path": path})["output"]
        if "cert" in desc or "tls" in desc:
            return self.call_tool("sec.cert_rotate",
                                  task["input"] or {})["output"]
        if "audit" in desc:
            return {
                "chain": self.call_tool("sec.audit")["output"],
                "recent": self.call_tool("sec.audit_query",
                                         {"limit": 20})["output"],
            }
        if "perm" in desc or "suid" in desc:
            return self.call_tool("sec.check_perms",
                                  task["input"] or {})["output"]
        # full sweep default
        return {
            "ports": self.call_tool("sec.scan")["output"],
            "rootkits": self.call_tool("sec.scan_rootkits")["output"],
            "perms": self.call_tool("sec.check_perms",
                                    {"path": "/tmp"})["output"],
        }


class PackageAgent(BaseAgent):
    """Package management (reference package.py)."""

    periodic_interval = 3600.0

    def get_agent_type(self) -> str:
        return "package"

    def get_capabilities(self) -> List[str]:
        return ["pkg.read", "pkg.manage", "fs.read"]

    def get_tool_namespaces(self) -> List[str]:
        return ["pkg"]

    def handle_task(self, task: Dict[str, Any]) -> Dict[str, Any]:
        desc = task["description"].lower()
        name = task["input"].get("name") or _last_word(desc)
        if "install" in desc:
            found = self.call_tool("pkg.search", {"query": name})
            if not found["success"] or not found["output"].get("results"):
                raise RuntimeError(f"package {name!r} not found")
            result = self.call_tool("pkg.install", {"name": name},
                                    reason=task["description"])
            if not result["success"]:
                raise RuntimeError(result["error"])
            return result["output"]
        if "remove" in desc or "uninstall" in desc:
            return self.call_tool("pkg.remove", {"name": name})["output"]
        if "update" in desc or "upgrade" in desc:
            return self.call_tool("pkg.update")["output"]
        if "search" in desc:
            return self.call_tool("pkg.search", {"query": name})["output"]
        return self.call_tool("pkg.list_installed", {"limit": 100})["output"]


class MonitoringAgent(BaseAgent):
    """Metric collection + rolling-baseline anomaly detection
    (reference monitoring.py:20-23; 100-point baseline)."""

    periodic_interval = 30.0
    BASELINE_POINTS = 100
    ANOMALY_SIGMA = 3.0

    def __init__(self, name=None):
        super().__init__(name)
        self._history: Dict[str, collections.deque] = collections.defaultdict(
            lambda: collections.deque(maxlen=self.BASELINE_POINTS)
        )

    def get_agent_type(self) -> str:
        return "monitoring"

    def get_capabilities(self) -> List[str]:
        return ["monitor.read", "fs.read", "process.read", "hw.read"]

    def get_tool_namespaces(self) -> List[str]:
        return ["monitor", "hw"]

    def observe(self, key: str, value: float) -> bool:
        """Record a point; True if it is anomalous vs the rolling baseline.

        The stdev floor is scale-proportional, not epsilon: counters that
        sit perfectly flat while idle (KV pages free overnight) would
        otherwise flag the first 1-unit move after a zero-variance
        baseline as a 3-sigma event and spam anomalies on every routine
        transition."""
        hist = self._history[key]
        anomalous = False
        if len(hist) >= 10:
            mean = statistics.fmean(hist)
            stdev = max(statistics.pstdev(hist), 0.01 * abs(mean), 1e-9)
            anomalous = abs(value - mean) > self.ANOMALY_SIGMA * stdev
        hist.append(value)
        return anomalous

    def periodic(self) -> None:
        cpu = self.call_tool("monitor.cpu")["output"].get("percent", 0.0)
        mem = self.call_tool("monitor.memory")["output"].get("percent", 0.0)
        self.update_metric("cpu.percent", cpu)
        self.update_metric("memory.percent", mem)
        for key, value in (("cpu.percent", cpu), ("memory.percent", mem)):
            if self.observe(key, value):
                self.push_event(
                    "monitoring.anomaly",
                    {"metric": key, "value": value},
                    critical=value > 95,
                )
        self.collect_serving_metrics()

    def collect_serving_metrics(self) -> None:
        """Scrape the TPU runtime's per-model serving counters
        (HealthCheck `<model>.serving` details: spec acceptance, KV page
        usage, prefix hits — runtime/service.py) into the memory service's
        metric store, with the same rolling-baseline anomaly detection as
        the system metrics. Quietly skips when the runtime is down — its
        own health is the health checker's job."""
        try:
            h = self.runtime.HealthCheck(common_pb2.Empty(), timeout=5)
            items = list(h.details.items())
        except Exception:  # noqa: BLE001 — runtime absent/restarting
            return
        for key, blob in items:
            if not key.endswith(".serving"):
                continue
            model = key[: -len(".serving")]
            for pair in blob.split(","):
                name, _, raw = pair.partition("=")
                try:
                    value = float(raw)
                except ValueError:
                    continue
                metric = f"runtime.{model}.{name}"
                self.update_metric(metric, value)
                if name in ("kv_pages_free", "spec_tokens_per_round"):
                    if self.observe(metric, value):
                        self.push_event(
                            "monitoring.anomaly",
                            {"metric": metric, "value": value},
                            critical=name == "kv_pages_free" and value == 0,
                        )

    def handle_task(self, task: Dict[str, Any]) -> Dict[str, Any]:
        desc = task["description"].lower()
        if "log" in desc:
            return self.call_tool("monitor.logs", task["input"] or {})["output"]
        if "network" in desc:
            return self.call_tool("monitor.network")["output"]
        if "disk" in desc:
            return self.call_tool("monitor.disk")["output"]
        if "memory" in desc:
            return self.call_tool("monitor.memory")["output"]
        return {
            "cpu": self.call_tool("monitor.cpu")["output"],
            "memory": self.call_tool("monitor.memory")["output"],
            "disk": self.call_tool("monitor.disk")["output"],
        }


class LearningAgent(BaseAgent):
    """5-minute learning cycle over events/decisions (reference
    learning.py:24,698-732): pattern extraction + tool-effectiveness stats."""

    periodic_interval = 300.0

    def get_agent_type(self) -> str:
        return "learning"

    def get_capabilities(self) -> List[str]:
        return ["monitor.read", "fs.read"]

    def get_tool_namespaces(self) -> List[str]:
        return ["monitor"]

    def periodic(self) -> None:
        self.learn_cycle()

    def learn_cycle(self) -> Dict[str, Any]:
        events = self.get_recent_events(count=100)
        by_category = collections.Counter(e["category"] for e in events)
        learned = []
        for category, count in by_category.items():
            if count >= 5:  # recurring situation worth a pattern
                self.store_pattern(
                    trigger=category,
                    action=f"investigate recurring {category} events",
                    success_rate=0.6,
                )
                learned.append(category)
        self.update_metric("learning.patterns_stored", float(len(learned)))
        return {"recurring": dict(by_category), "patterns_stored": learned}

    def handle_task(self, task: Dict[str, Any]) -> Dict[str, Any]:
        return self.learn_cycle()


class StorageAgent(BaseAgent):
    """Disk health + backups (reference storage.py)."""

    periodic_interval = 600.0

    def get_agent_type(self) -> str:
        return "storage"

    def get_capabilities(self) -> List[str]:
        return ["fs.read", "fs.write", "hw.read", "monitor.read"]

    def get_tool_namespaces(self) -> List[str]:
        return ["fs", "monitor", "hw"]

    def periodic(self) -> None:
        disk = self.call_tool("fs.disk_usage", {"path": "/"})
        pct = disk["output"].get("percent_used", 0)
        self.update_metric("disk.percent_used", float(pct))
        if pct > 90:
            self.push_event("storage.disk_pressure", disk["output"],
                            critical=True)

    def handle_task(self, task: Dict[str, Any]) -> Dict[str, Any]:
        desc = task["description"].lower()
        if "backup" in desc:
            src = task["input"].get("src", "/etc")
            dst = task["input"].get(
                "dst", f"/tmp/aios/backups/manual-{int(time.time())}"
            )
            result = self.call_tool("fs.copy", {"src": src, "dst": dst},
                                    reason="backup")
            if not result["success"]:
                raise RuntimeError(result["error"])
            return {"backed_up": src, "to": dst}
        if "usage" in desc or "space" in desc or "disk" in desc:
            return self.call_tool("monitor.disk")["output"]
        if "largest" in desc or "clean" in desc:
            found = self.call_tool(
                "fs.search", {"path": task["input"].get("path", "/tmp"),
                              "pattern": "*", "limit": 50},
            )
            return found["output"]
        return self.call_tool("fs.disk_usage", {"path": "/"})["output"]


class TaskAgent(BaseAgent):
    """General executor: NL parsing, multi-step plans, delegation
    (reference task.py)."""

    periodic_interval = 3600.0

    def get_agent_type(self) -> str:
        return "task"

    def get_capabilities(self) -> List[str]:
        return ["fs.read", "fs.write", "process.read", "service.read",
                "monitor.read", "web.access", "code.generate"]

    def get_tool_namespaces(self) -> List[str]:
        return ["fs", "process", "service", "monitor", "web", "code"]

    def handle_task(self, task: Dict[str, Any]) -> Dict[str, Any]:
        context = ""
        try:
            context = self.assemble_context(task["description"])
        except Exception:  # noqa: BLE001
            pass
        plan_text = self.think(
            "Plan tool calls for this task and reply with a JSON array of "
            '{"tool": "ns.name", "args": {...}} items.\n'
            f"Task: {task['description']}\nContext:\n{context}",
            level=task.get("intelligence_level", "operational"),
        )
        from ..orchestrator.task_planner import extract_json_array

        steps = extract_json_array(plan_text) or []
        results = []
        for step in steps[:8]:
            if not isinstance(step, dict) or not step.get("tool"):
                continue
            res = self.call_tool(step["tool"], step.get("args", {}),
                                 reason=task["description"])
            results.append({"tool": step["tool"], "success": res["success"],
                            "output": res["output"]})
            if not res["success"]:
                raise RuntimeError(f"{step['tool']}: {res['error']}")
        if not results:
            return {"answer": plan_text[:2000]}
        return {"steps": results}


class WebAgent(BaseAgent):
    """Browse/scrape/API calls/URL monitoring (reference web.py)."""

    periodic_interval = 300.0

    def __init__(self, name=None):
        super().__init__(name)
        self.watched_urls: List[str] = []

    def get_agent_type(self) -> str:
        return "web"

    def get_capabilities(self) -> List[str]:
        return ["web.access", "net.diagnose", "fs.read", "fs.write"]

    def get_tool_namespaces(self) -> List[str]:
        return ["web", "net"]

    def periodic(self) -> None:
        for url in self.watched_urls:
            res = self.call_tool("web.http_request", {"url": url})
            ok = res["success"] and res["output"].get("status") == 200
            if not ok:
                self.push_event("web.url_down", {"url": url}, critical=False)

    def handle_task(self, task: Dict[str, Any]) -> Dict[str, Any]:
        desc = task["description"].lower()
        url = task["input"].get("url") or _extract_url(task["description"])
        if "scrape" in desc or "browse" in desc or "read page" in desc:
            if not url:
                raise ValueError("no url in task")
            return self.call_tool("web.scrape", {"url": url})["output"]
        if "download" in desc:
            return self.call_tool(
                "web.download", {"url": url, "dest": task["input"].get("dest",
                                 "/tmp/aios/download.bin")})["output"]
        if "webhook" in desc:
            return self.call_tool("web.webhook", task["input"])["output"]
        if "monitor" in desc and url:
            self.watched_urls.append(url)
            return {"watching": self.watched_urls}
        if url:
            return self.call_tool("web.api_call", {"url": url})["output"]
        raise ValueError("web task needs a url")


class CreatorAgent(BaseAgent):
    """Project scaffolding + AI code generation (reference creator.py)."""

    periodic_interval = 3600.0

    def get_agent_type(self) -> str:
        return "creator"

    def get_capabilities(self) -> List[str]:
        return ["code.generate", "fs.read", "fs.write", "git.use"]

    def get_tool_namespaces(self) -> List[str]:
        return ["code", "fs", "git"]

    def handle_task(self, task: Dict[str, Any]) -> Dict[str, Any]:
        desc = task["description"].lower()
        name = task["input"].get("name", "project")
        if "scaffold" in desc or "new project" in desc or "create a" in desc:
            kind = "web" if ("web" in desc or "site" in desc) else "python"
            result = self.call_tool("code.scaffold",
                                    {"name": name, "kind": kind})
            if not result["success"]:
                raise RuntimeError(result["error"])
            dest = result["output"]["files"][0].rsplit("/", 1)[0]
            self.call_tool("git.init", {"path": dest})
            return {**result["output"], "git": "initialized"}
        if "generate" in desc or "write code" in desc:
            code = self.think(
                f"Write the complete file content for: {task['description']}.\n"
                "Reply with ONLY the code, no commentary.",
                level="tactical", max_tokens=1024,
            )
            dest = task["input"].get("dest", f"/tmp/aios/projects/{name}.py")
            result = self.call_tool("code.generate",
                                    {"dest": dest, "content": code})
            return result["output"]
        raise ValueError("creator task needs scaffold/generate intent")


# ---------------------------------------------------------------------------


def _extract_service_name(desc: str) -> str:
    import re

    m = re.search(r"restart(?:\s+the)?\s+([a-z0-9_.@-]+?)(?:\s+service)?(?:\s|$)",
                  desc)
    return m.group(1) if m else ""


def _extract_url(text: str):
    import re

    m = re.search(r"https?://\S+", text)
    return m.group(0).rstrip(".,)") if m else None


def _last_word(desc: str) -> str:
    words = [w for w in desc.replace("?", "").split() if w not in
             ("the", "a", "an", "package", "install", "remove", "search")]
    return words[-1] if words else ""


CLASSES = {
    "system": SystemAgent,
    "network": NetworkAgent,
    "security": SecurityAgent,
    "package": PackageAgent,
    "monitoring": MonitoringAgent,
    "learning": LearningAgent,
    "storage": StorageAgent,
    "task": TaskAgent,
    "web": WebAgent,
    "creator": CreatorAgent,
}
