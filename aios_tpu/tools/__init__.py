"""aios.tools.ToolRegistry — capability-checked system tool execution.

Reference: tools/src/ (SURVEY.md section 2 rows 3, 3a-3i). Pipeline per
execution: validate -> capability check -> rate limit -> backup-if-reversible
-> execute -> audit (executor.rs:503-633).
"""
