"""Numeric parity tests for the Pallas TPU kernels (interpret mode on CPU).

Each kernel is checked against its pure-jnp reference implementation — the
numeric-parity layer SURVEY.md section 4 says the reference lacks and the
TPU build must invent. On CPU the kernels run under the Pallas interpreter;
the driver's real-chip bench exercises the compiled Mosaic path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aios_tpu.ops.decode_attention import (
    decode_attention,
    decode_attention_reference,
)
from aios_tpu.ops.flash_attention import (
    flash_attention,
    flash_attention_reference,
)
from aios_tpu.ops.quantized_matmul import (
    dequantize,
    quantize_int8,
    quantized_matmul,
    quantized_matmul_reference,
    supports_pallas_qmm,
)

# compile-heavy tier: excluded from the fast commit gate (pytest -m fast)
pytestmark = pytest.mark.slow


def _rand(key, shape, dtype=jnp.float32, scale=1.0):
    return (jax.random.normal(key, shape, jnp.float32) * scale).astype(dtype)


# ---------------------------------------------------------------------------
# flash attention
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "B,T,H,KH,D,window",
    [
        (2, 128, 8, 4, 64, None),  # GQA
        (1, 256, 4, 4, 64, None),  # MHA
        (1, 256, 8, 2, 64, 100),  # sliding window (Mistral-style)
        (2, 64, 8, 1, 128, None),  # MQA, wide head
    ],
)
def test_flash_attention_parity(B, T, H, KH, D, window):
    keys = jax.random.split(jax.random.PRNGKey(0), 3)
    q = _rand(keys[0], (B, T, H, D))
    k = _rand(keys[1], (B, T, KH, D))
    v = _rand(keys[2], (B, T, KH, D))
    ref = flash_attention_reference(q, k, v, causal=True, window=window)
    out = flash_attention(
        q, k, v, causal=True, window=window, block_q=64, block_kv=64,
        interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3
    )


def test_flash_attention_never_materializes_scores():
    # T=512 with tiny blocks: run in interpret mode just to confirm the
    # blocked recurrence matches at a size where fp32 scores would be 1 MB+
    B, T, H, KH, D = 1, 512, 2, 2, 64
    keys = jax.random.split(jax.random.PRNGKey(1), 3)
    q = _rand(keys[0], (B, T, H, D))
    k = _rand(keys[1], (B, T, KH, D))
    v = _rand(keys[2], (B, T, KH, D))
    ref = flash_attention_reference(q, k, v)
    out = flash_attention(q, k, v, block_q=128, block_kv=128, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3
    )


# ---------------------------------------------------------------------------
# ragged decode attention
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "B,H,KH,D,C,window,lengths",
    [
        (4, 8, 4, 64, 256, None, [0, 17, 100, 255]),
        (2, 8, 2, 64, 512, None, [511, 3]),
        (2, 8, 8, 64, 256, 64, [200, 30]),  # sliding window
        (1, 4, 1, 128, 128, None, [77]),  # MQA
    ],
)
def test_decode_attention_parity(B, H, KH, D, C, window, lengths):
    keys = jax.random.split(jax.random.PRNGKey(2), 3)
    q = _rand(keys[0], (B, H, D))
    k_cache = _rand(keys[1], (B, C, KH, D))
    v_cache = _rand(keys[2], (B, C, KH, D))
    lens = jnp.asarray(lengths, jnp.int32)
    ref = decode_attention_reference(q, k_cache, v_cache, lens, window=window)
    out = decode_attention(
        q, k_cache, v_cache, lens, window=window, block_kv=64, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3
    )


def test_decode_attention_ignores_rows_beyond_length():
    # poison the cache beyond each slot's length; output must not change
    B, H, KH, D, C = 2, 4, 2, 64, 128
    keys = jax.random.split(jax.random.PRNGKey(3), 3)
    q = _rand(keys[0], (B, H, D))
    k_cache = _rand(keys[1], (B, C, KH, D))
    v_cache = _rand(keys[2], (B, C, KH, D))
    lens = jnp.asarray([10, 60], jnp.int32)

    out1 = decode_attention(q, k_cache, v_cache, lens, block_kv=64, interpret=True)
    poison = jnp.full_like(k_cache, 1e4)
    rows = jnp.arange(C)[None, :, None, None]
    beyond = rows > lens[:, None, None, None]
    k_p = jnp.where(beyond, poison, k_cache)
    v_p = jnp.where(beyond, poison, v_cache)
    out2 = decode_attention(q, k_p, v_p, lens, block_kv=64, interpret=True)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2), rtol=1e-5)


# ---------------------------------------------------------------------------
# quantized matmul
# ---------------------------------------------------------------------------


def test_quantize_int8_roundtrip():
    w = _rand(jax.random.PRNGKey(4), (256, 384), scale=0.5)
    w_q, s = quantize_int8(w)
    assert w_q.dtype == jnp.int8 and s.shape == (1, 384)
    w_back = dequantize(w_q, s, dtype=jnp.float32)
    # per-channel absmax/127 quantization error bound
    bound = np.asarray(jnp.max(jnp.abs(w), axis=0) / 127.0)
    err = np.abs(np.asarray(w_back - w))
    assert (err <= bound[None, :] + 1e-6).all()


@pytest.mark.parametrize("M,K,N", [(8, 256, 384), (3, 512, 256), (16, 128, 128)])
def test_quantized_matmul_parity(M, K, N):
    keys = jax.random.split(jax.random.PRNGKey(5), 2)
    x = _rand(keys[0], (M, K), scale=0.3)
    w = _rand(keys[1], (K, N), scale=0.1)
    w_q, s = quantize_int8(w)
    assert supports_pallas_qmm(K, N)
    ref = quantized_matmul_reference(x, w_q, s)
    out = quantized_matmul(x, w_q, s, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=5e-3, atol=5e-3
    )


def test_quantized_matmul_close_to_float():
    # end-to-end error vs the unquantized matmul stays small
    keys = jax.random.split(jax.random.PRNGKey(6), 2)
    x = _rand(keys[0], (8, 512), scale=0.3)
    w = _rand(keys[1], (512, 256), scale=0.1)
    w_q, s = quantize_int8(w)
    exact = x @ w
    approx = quantized_matmul(x, w_q, s, interpret=True)
    rel = float(
        jnp.linalg.norm(approx - exact) / (jnp.linalg.norm(exact) + 1e-9)
    )
    assert rel < 0.01, rel


def test_qmm_batch_shapes_and_padding():
    # leading dims flattened, M padded to sublane multiple internally
    keys = jax.random.split(jax.random.PRNGKey(7), 2)
    x = _rand(keys[0], (2, 3, 128), scale=0.3)
    w = _rand(keys[1], (128, 256), scale=0.1)
    w_q, s = quantize_int8(w)
    out = quantized_matmul(x, w_q, s, interpret=True)
    assert out.shape == (2, 3, 256)
    ref = quantized_matmul_reference(x.reshape(6, 128), w_q, s).reshape(2, 3, 256)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=5e-3, atol=5e-3)


# ---------------------------------------------------------------------------
# int8 serving path end-to-end (dequant fallback on CPU)
# ---------------------------------------------------------------------------


def test_quantized_engine_decodes_close_to_float():
    from aios_tpu.engine import model as M
    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.engine.engine import TPUEngine

    params = M.init_params(TINY_TEST, jax.random.PRNGKey(8), dtype=jnp.float32)
    eng_f = TPUEngine(TINY_TEST, params, num_slots=2, max_context=64,
                      cache_dtype=jnp.float32)
    eng_q = TPUEngine(TINY_TEST, params, num_slots=2, max_context=64,
                      cache_dtype=jnp.float32, quantize=True)
    assert eng_q.quantized
    prompt = [1, 5, 9, 2]
    out_f = eng_f.generate(prompt, max_new_tokens=8, temperature=0.0)
    out_q = eng_q.generate(prompt, max_new_tokens=8, temperature=0.0)
    # int8 per-channel quantization on a tiny random model: greedy paths can
    # diverge after a few tokens, but the first steps must agree
    assert out_f[:2] == out_q[:2]


def test_quantized_forward_logits_close():
    from aios_tpu.engine import model as M
    from aios_tpu.engine.config import TINY_TEST

    params = M.init_params(TINY_TEST, jax.random.PRNGKey(9), dtype=jnp.float32)
    qparams = M.quantize_params(params)
    tokens = jnp.asarray([[1, 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
    lf = M.forward_full(params, TINY_TEST, tokens)
    lq = M.forward_full(qparams, TINY_TEST, tokens)
    denom = float(jnp.linalg.norm(lf)) + 1e-9
    rel = float(jnp.linalg.norm(lq - lf)) / denom
    assert rel < 0.05, rel


# ---------------------------------------------------------------------------
# int8 KV cache
# ---------------------------------------------------------------------------


def test_kv_quantize_roundtrip():
    from aios_tpu.engine import model as M

    x = _rand(jax.random.PRNGKey(10), (4, 7, 2, 64), scale=2.0)
    q, s = M.quantize_kv(x)
    assert q.dtype == jnp.int8 and s.shape == (4, 7, 2)
    back = M.dequantize_kv(q, s, jnp.float32)
    bound = np.asarray(jnp.max(jnp.abs(x), axis=-1) / 127.0)
    err = np.abs(np.asarray(back - x))
    assert (err <= bound[..., None] + 1e-6).all()


def test_int8_kv_cache_engine_close_to_float():
    from aios_tpu.engine import model as M
    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.engine.engine import TPUEngine

    params = M.init_params(TINY_TEST, jax.random.PRNGKey(11), dtype=jnp.float32)
    eng_f = TPUEngine(TINY_TEST, params, num_slots=2, max_context=64,
                      cache_dtype=jnp.float32)
    eng_q = TPUEngine(TINY_TEST, params, num_slots=2, max_context=64,
                      cache_dtype=jnp.int8)
    assert eng_q.quant_cache and "k_s" in eng_q.state
    prompt = [3, 1, 4, 1, 5, 9, 2, 6]
    out_f = eng_f.generate(prompt, max_new_tokens=10, temperature=0.0)
    out_q = eng_q.generate(prompt, max_new_tokens=10, temperature=0.0)
    # int8 KV on a tiny random model: early greedy tokens must agree
    assert out_f[:3] == out_q[:3]


def test_int8_kv_cache_slot_isolation():
    from aios_tpu.engine import model as M
    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.engine.engine import TPUEngine

    params = M.init_params(TINY_TEST, jax.random.PRNGKey(12), dtype=jnp.float32)
    eng = TPUEngine(TINY_TEST, params, num_slots=4, max_context=64,
                    cache_dtype=jnp.int8)
    # run a decode with another slot active, then check a fresh slot's
    # output matches a single-slot engine (no cross-slot contamination)
    eng.prefill(2, [9, 8, 7], temperature=0.0)
    eng.step(4)
    out = eng.generate([3, 1, 4], max_new_tokens=6, temperature=0.0, slot=0)

    eng2 = TPUEngine(TINY_TEST, params, num_slots=4, max_context=64,
                     cache_dtype=jnp.int8)
    out2 = eng2.generate([3, 1, 4], max_new_tokens=6, temperature=0.0, slot=0)
    assert out == out2


# ---------------------------------------------------------------------------
# int8-KV ragged decode kernel
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("window", [None, 16])
@pytest.mark.parametrize("lengths", [[0, 13, 31, 63], [63, 63, 7, 1]])
def test_decode_attention_int8_parity(window, lengths):
    from aios_tpu.ops import (
        decode_attention_int8,
        decode_attention_int8_reference,
    )

    rng = np.random.default_rng(5)
    B, H, KH, D, C = 4, 8, 2, 16, 64
    q = jnp.asarray(rng.normal(size=(B, H, D)), jnp.float32)
    k = jnp.asarray(rng.integers(-127, 128, (B, C, KH, D)), jnp.int8)
    v = jnp.asarray(rng.integers(-127, 128, (B, C, KH, D)), jnp.int8)
    ks = jnp.asarray(rng.uniform(0.005, 0.02, (B, C, KH)), jnp.float32)
    vs = jnp.asarray(rng.uniform(0.005, 0.02, (B, C, KH)), jnp.float32)
    lens = jnp.asarray(lengths, jnp.int32)
    got = decode_attention_int8(
        q, k, v, ks, vs, lens, window=window, block_kv=16, interpret=True
    )
    ref = decode_attention_int8_reference(q, k, v, ks, vs, lens, window=window)
    np.testing.assert_allclose(got, ref, atol=2e-5, rtol=2e-5)


def test_decode_step_int8_ragged_wiring(monkeypatch):
    """AIOS_TPU_INT8_RAGGED=1 routes the int8-KV decode through the ragged
    kernel (reference body stands in on CPU); outputs match the
    dequantizing XLA path."""
    import aios_tpu.ops as ops_mod
    from aios_tpu.engine import model as M
    from aios_tpu.engine.config import TINY_TEST

    cfg = TINY_TEST
    params = M.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    toks = jnp.asarray([1, 2, 3, 4], jnp.int32)
    lens = jnp.asarray([5, 0, 9, 2], jnp.int32)
    k, v = M.init_kv_cache(cfg, 4, 128, jnp.int8)
    scales = M.init_kv_scales(cfg, 4, 128)

    ref, _, _, _ = M.decode_step(
        params, cfg, toks, lens, k, v, kernels=False,
        cache_scales=scales,
    )

    called = {}

    def fake_kernel(q, k_l, v_l, k_s, v_s, lengths, window=None):
        called["hit"] = True
        return ops_mod.decode_attention_int8_reference(
            q, k_l, v_l, k_s, v_s, lengths, window=window
        )

    monkeypatch.setenv("AIOS_TPU_INT8_RAGGED", "1")
    monkeypatch.setenv("AIOS_TPU_RAGGED_MIN_C", "1")  # force the crossover
    monkeypatch.setattr(ops_mod, "decode_attention_int8", fake_kernel)
    got, _, _, _ = M.decode_step(
        params, cfg, toks, lens, k, v, kernels=True,
        cache_scales=scales,
    )
    assert called.get("hit")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


# ---------------------------------------------------------------------------
# int4 serving weights (ops/int4_matmul.py)
# ---------------------------------------------------------------------------


def test_int4_pack_unpack_roundtrip():
    import importlib
    i4 = importlib.import_module("aios_tpu.ops.int4_matmul")

    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.integers(-8, 8, size=(256, 128), dtype=np.int64),
                    jnp.float32)
    p, s = i4.quantize_int4(q * 1.0, group=128)  # values already int => exact
    w = i4.unpack_int4(p, group=128).astype(jnp.float32)
    scaled = np.asarray(i4.dequantize_int4(p, s, dtype=jnp.float32))
    # unpack must invert pack ordering: dequant(q) == q * group-scale, and
    # since the group absmax is an integer multiple of every value / 7...
    # the robust invariant: quantize(dequantize(p)) is a fixed point
    p2, s2 = i4.quantize_int4(jnp.asarray(scaled), group=128)
    np.testing.assert_array_equal(np.asarray(p), np.asarray(p2))
    assert w.shape == (256, 128)


@pytest.mark.parametrize("M,K,N,group", [
    (8, 256, 128, 128),
    (3, 512, 384, 128),   # M padding
    (16, 256, 256, None), # auto group
])
def test_int4_matmul_parity(M, K, N, group):
    import importlib
    i4 = importlib.import_module("aios_tpu.ops.int4_matmul")

    k1, k2 = jax.random.split(jax.random.PRNGKey(11))
    w = _rand(k1, (K, N), scale=0.05)
    x = _rand(k2, (M, K), dtype=jnp.bfloat16)
    p, s = i4.quantize_int4(w, group=group)
    ref = i4.int4_matmul_reference(x, p, s)
    out = i4.int4_matmul(x, p, s, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=2e-2, atol=2e-2,
    )


def test_int4_matmul_close_to_float():
    import importlib
    i4 = importlib.import_module("aios_tpu.ops.int4_matmul")

    k1, k2 = jax.random.split(jax.random.PRNGKey(12))
    w = _rand(k1, (512, 256), scale=0.05)
    x = _rand(k2, (8, 512), dtype=jnp.bfloat16)
    p, s = i4.quantize_int4(w)
    exact = (x.astype(jnp.float32) @ w.astype(jnp.float32))
    approx = i4.int4_matmul(x, p, s, interpret=True).astype(jnp.float32)
    denom = float(jnp.linalg.norm(exact)) + 1e-9
    rel = float(jnp.linalg.norm(approx - exact)) / denom
    # plain RTN group-wise int4 on gaussian weights: RMS error is
    # step/sqrt(12) with step ~= absmax(128)/7 ~= 0.4 sigma -> ~11-12%
    assert rel < 0.15, rel


def test_int4_group_inference_and_small_groups():
    import importlib
    i4 = importlib.import_module("aios_tpu.ops.int4_matmul")

    # K=64 falls back to group 64; kernel is not eligible, reference works
    assert i4.pick_group(64) == 64
    assert not i4.kernel_supported(64, 128, 64)
    w = _rand(jax.random.PRNGKey(13), (64, 128), scale=0.1)
    p, s = i4.quantize_int4(w)
    assert i4.infer_group(p, s) == 64
    x = _rand(jax.random.PRNGKey(14), (4, 64), dtype=jnp.bfloat16)
    out = i4.int4_matmul_reference(x, p, s)
    exact = x.astype(jnp.float32) @ w.astype(jnp.float32)
    denom = float(jnp.linalg.norm(exact)) + 1e-9
    assert float(jnp.linalg.norm(out.astype(jnp.float32) - exact)) / denom < 0.15


def test_quantize_params_int4_mode():
    from aios_tpu.engine import model as M
    from aios_tpu.engine.config import TINY_TEST

    params = M.init_params(TINY_TEST, jax.random.PRNGKey(15), dtype=jnp.float32)
    qp = M.quantize_params(params, mode="int4")
    # fused w_qkv [E=64, 96]: K=64 -> group 64 storage works
    assert "q4" in qp["layers"]["w_qkv"]
    assert qp["layers"]["w_qkv"]["q4"].dtype == jnp.uint8
    # logits head quantizes too
    assert "q4" in qp["lm_head"] or "q" in qp["lm_head"]
    # forward stays close to float
    tokens = jnp.asarray([[1, 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
    lf = M.forward_full(params, TINY_TEST, tokens)
    lq = M.forward_full(qp, TINY_TEST, tokens)
    denom = float(jnp.linalg.norm(lf)) + 1e-9
    rel = float(jnp.linalg.norm(lq - lf)) / denom
    # group-64 int4 on a 2-layer random model: coarse but bounded
    assert rel < 0.3, rel


def test_int4_engine_decode_matches_dense_on_fixed_point():
    """Greedy decode with int4 serving == dense decode when the weights are
    already exact int4 fixed points (quantize->dequantize round-trip), so
    the comparison isolates the serving path from quantization error."""
    from aios_tpu.engine import model as M
    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.engine.engine import TPUEngine
    import importlib
    i4 = importlib.import_module("aios_tpu.ops.int4_matmul")

    params = M.init_params(TINY_TEST, jax.random.PRNGKey(16), dtype=jnp.float32)

    def roundtrip(w):
        if w.ndim >= 2 and i4.supports_int4(w.shape[-2], w.shape[-1]):
            p, s = i4.quantize_int4(w)
            return i4.dequantize_int4(p, s, dtype=jnp.float32)
        return w

    fixed = dict(params)
    fixed["layers"] = {k: roundtrip(v) for k, v in params["layers"].items()}
    # tied lm_head: materialize + round-trip it so the head matmul is a
    # fixed point for both engines too
    fixed["lm_head"] = roundtrip(params["embed"].T)
    eng_f = TPUEngine(TINY_TEST, fixed, num_slots=2, max_context=64,
                      cache_dtype=jnp.float32)
    eng_q = TPUEngine(TINY_TEST, fixed, num_slots=2, max_context=64,
                      cache_dtype=jnp.float32, quantize="int4")
    assert eng_q.quantized and eng_q.quant_mode == "int4"
    prompt = [1, 5, 9, 2]
    out_f = eng_f.generate(prompt, max_new_tokens=8, temperature=0.0)
    out_q = eng_q.generate(prompt, max_new_tokens=8, temperature=0.0)
    # bf16 rounding differs between the dense-f32 and int4-dequant paths,
    # so late tokens may drift on a random tiny model; the early steps of
    # the greedy path must agree exactly
    assert out_f[:3] == out_q[:3], (out_f, out_q)


def test_int4_composes_with_sharding_plan():
    """int4 serving under a TP plan (the round-4 composition that replaced
    the old downgrade-to-int8 rule): eligibility/scale groups computed on
    shard-local dims, quant_mode stays int4, and the sharded engine
    decodes token-identically to the single-chip int4 engine."""
    from aios_tpu.engine.engine import TPUEngine
    from aios_tpu.engine import model as M
    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.parallel.sharding import ShardingPlan, build_mesh

    params = M.init_params(TINY_TEST, jax.random.PRNGKey(17), dtype=jnp.float32)
    plan = ShardingPlan(build_mesh(tp=2, n_devices=2))
    eng = TPUEngine(TINY_TEST, params, num_slots=2, max_context=64,
                    cache_dtype=jnp.float32, shardings=plan, quantize="int4")
    assert eng.quant_mode == "int4"
    # off-TPU, storage-eligible dims stay int4 (the jnp reference path
    # dequantizes inline either way); on TPU, shard-ineligible leaves fall
    # back per leaf — covered by the kernel-rule tests in test_checkpoint
    assert any(
        isinstance(v, dict) and "q4" in v
        for v in eng.params["layers"].values()
    )
    solo = TPUEngine(TINY_TEST, params, num_slots=2, max_context=64,
                     cache_dtype=jnp.float32, quantize="int4")
    prompt = [1, 5, 9, 2]
    got = eng.generate(prompt, max_new_tokens=8, temperature=0.0)
    want = solo.generate(prompt, max_new_tokens=8, temperature=0.0)
    # the single-chip engine quantizes the FUSED layout, the sharded one
    # the unfused tp-grouped layout — different rounding, so late tokens
    # may drift on a random tiny model (same caveat as the dense-vs-int4
    # test above); the early greedy steps must agree exactly
    assert got[:4] == want[:4], (got, want)


def test_int4_clip_search_beats_plain_rtn():
    """The per-group MSE clip search must never be worse than plain
    absmax RTN, and measurably better on gaussian weights."""
    import importlib
    i4 = importlib.import_module("aios_tpu.ops.int4_matmul")

    w = _rand(jax.random.PRNGKey(18), (1024, 512), scale=0.05)
    errs = {}
    for flag in (False, True):
        p, s = i4.quantize_int4(w, optimize_clip=flag)
        wd = i4.dequantize_int4(p, s, dtype=jnp.float32)
        errs[flag] = float(jnp.linalg.norm(wd - w) / jnp.linalg.norm(w))
    assert errs[True] <= errs[False]
    assert errs[True] < 0.95 * errs[False], errs  # a real improvement


def test_int4_clip_search_exact_values_stay_exact():
    """Values already exactly representable (err 0 at clip 1.0) must be
    reproduced bit-exactly — the search keeps the first zero-error scale."""
    import importlib
    i4 = importlib.import_module("aios_tpu.ops.int4_matmul")

    # ints in [-7, 7] with a guaranteed ±7 per group-column => scale 2^-5
    # exactly, reconstruction exact at clip 1.0
    rng = np.random.default_rng(19)
    q = rng.integers(-7, 8, size=(128, 128)).astype(np.float32)
    q[0, :] = 7.0
    w = jnp.asarray(q * 2.0**-5)
    p, s = i4.quantize_int4(w, group=128)
    wd = i4.dequantize_int4(p, s, dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(wd), np.asarray(w))
