"""Host-side page-table management for the paged KV cache.

The device holds a fixed page pool ([L, N, P, KH, D] per k/v) and reads it
through per-slot page tables; THIS module owns the mapping. Allocation is a
free-list pop, release a push — O(1), no compaction, no device traffic
beyond the [S, MAX_BLOCKS] int32 table that rides along with each dispatch
(a few hundred bytes). The scheduler's admission/retire cycle calls
`ensure`/`free_slot`; a pool that can't back a grow request raises
`PoolExhausted` so the batcher can retire a victim request instead of
corrupting anyone's cache.

Page 0 is reserved as the *sacrificial page*: never allocated, mapped by
every unbacked table entry, and the write target for inactive slots — the
paged twin of the dense engine's sacrificial last cache row.

Reference equivalence: llama.cpp's per-sequence KV cells behind
llama-server (SURVEY.md section 2.3); redesigned as vLLM/JetStream-style
paging because HBM reservation, not compute, is what caps co-resident
slots x context on a TPU chip (SURVEY.md section 7.2, hard part no. 1).
"""

from __future__ import annotations

import logging
import struct
import zlib
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import faults
from ..analysis.locks import make_lock

log = logging.getLogger("aios.paged")

SACRIFICIAL_PAGE = 0

# How the serving router scores prefix rows that are only host-resident:
# a restorable prefix saves the prefill compute but still pays alloc +
# device_put + scatter, so it is worth less than true HBM residency —
# routing prefers the replica with the pages already on chip and falls
# back to the one that can at least restore them.
HOST_OVERLAP_DISCOUNT = 0.5


class PoolExhausted(RuntimeError):
    """No free pages left to back a prefill/decode grow request.
    ``replica`` identifies the starved replica of a dp-partitioned pool
    (0 for the unreplicated pool) so the batcher can evict a request that
    actually frees pages there."""

    def __init__(self, needed: int, free: int, replica: int = 0):
        super().__init__(
            f"KV page pool exhausted: need {needed} page(s), {free} free "
            f"(replica {replica})"
        )
        self.needed = needed
        self.free = free
        self.replica = replica


class PageAllocator:
    """Free-list allocator over ``num_pages`` physical pages of ``page_size``
    rows, mapping ``num_slots`` slots x ``max_blocks`` logical blocks.

    ``replicas`` partitions the pool for a dp-replicated serving plan:
    the physical page axis shards over dp, so each replica owns a
    contiguous range of ``num_pages / replicas`` pages and table entries
    hold REPLICA-LOCAL ids (each device reads only its own slots' tables
    under shard_map, so local ids need no translation on device). Every
    replica's local page 0 is sacrificial. Slots map to replicas in
    contiguous blocks — the same split GSPMD applies to the slot axis."""

    def __init__(self, num_pages: int, page_size: int, num_slots: int,
                 max_blocks: int, replicas: int = 1) -> None:
        if replicas < 1 or num_pages % replicas:
            raise ValueError(
                f"num_pages {num_pages} must divide into {replicas} replicas"
            )
        if num_slots % replicas:
            raise ValueError(
                f"num_slots {num_slots} must divide into {replicas} replicas"
            )
        if num_pages // replicas < 2:
            raise ValueError("need at least 2 pages/replica (one sacrificial)")
        self.num_pages = num_pages
        self.page_size = page_size
        self.num_slots = num_slots
        self.max_blocks = max_blocks
        self.replicas = replicas
        self.local_pages = num_pages // replicas
        # local page 0 of every replica is sacrificial — never on a free list
        self._free: List[List[int]] = [
            list(range(self.local_pages - 1, 0, -1)) for _ in range(replicas)
        ]
        # host copy of the device tables; unbacked entries map page 0
        self.tables = np.full((num_slots, max_blocks), SACRIFICIAL_PAGE,
                              dtype=np.int32)
        self._blocks_used = np.zeros(num_slots, dtype=np.int64)
        # leading blocks already released by sliding-window trimming; their
        # table entries are stale-but-unread until the slot frees
        self._trimmed = np.zeros(num_slots, dtype=np.int64)
        # window+sink KV compression (prune_range): blocks
        # [_pruned_lo, _pruned_hi) of a slot were released mid-sequence —
        # their table entries map the sacrificial page and free_slot must
        # not decref them again. _pruned_lo is the sink boundary (fixed
        # once pruning starts), _pruned_hi only moves forward.
        self._pruned_lo = np.zeros(num_slots, dtype=np.int64)
        self._pruned_hi = np.zeros(num_slots, dtype=np.int64)
        # pages mapped by more than one owner (prefix sharing) carry a
        # refcount; rc 0 means free
        self._rc = np.zeros((replicas, self.local_pages), dtype=np.int64)
        # called with the shortfall when the free list runs dry; returns
        # how many pages it reclaimed (PrefixIndex.reclaim plugs in here)
        self.reclaimer: Optional[Callable[[int], int]] = None

    def replica_of(self, slot: int) -> int:
        return slot * self.replicas // self.num_slots

    @property
    def free_pages(self) -> int:
        return sum(len(f) for f in self._free)

    def free_pages_for(self, slot: int) -> int:
        """Free pages in the replica that backs ``slot`` — the number
        ``ensure`` can actually draw from (``free_pages`` sums across
        replicas and overstates capacity when replicas > 1)."""
        return len(self._free[self.replica_of(slot)])

    def capacity_blocks(self) -> int:
        """Most blocks ONE slot can ever hold: its replica's page count
        minus the sacrificial page (== num_pages - 1 when unreplicated)."""
        return self.local_pages - 1

    def pages_in_use(self) -> int:
        return (self.num_pages - self.replicas) - self.free_pages

    def blocks_for(self, rows: int) -> int:
        return -(-rows // self.page_size)  # ceil

    def _take(self, grow: int, replica: int = 0) -> None:
        act = faults.point("allocator.pressure")
        if act is not None:
            # chaos: synthetic pool pressure — rides the real
            # PoolExhausted recovery (victim eviction at decode grow /
            # prefill, restore fallback at alloc_pages)
            raise PoolExhausted(grow, len(self._free[replica]), replica)
        free = self._free[replica]
        if grow > len(free) and self.reclaimer is not None:
            self.reclaimer(grow - len(free))
        if grow > len(free):
            raise PoolExhausted(grow, len(free), replica)

    def ensure(self, slot: int, rows: int) -> bool:
        """Back slot ``slot`` for ``rows`` logical rows; allocates any
        missing pages (rc 1). Returns True iff the table changed. Raises
        PoolExhausted (leaving existing pages intact) if the free list —
        after asking the reclaimer to drop cold prefix pages — can't cover
        the growth."""
        need = min(self.blocks_for(rows), self.max_blocks)
        have = int(self._blocks_used[slot])
        if need <= have:
            return False
        r = self.replica_of(slot)
        self._take(need - have, r)
        for b in range(have, need):
            page = self._free[r].pop()
            self._rc[r, page] = 1
            self.tables[slot, b] = page
        self._blocks_used[slot] = need
        return True

    def map_shared(self, slot: int, pages: Sequence[int]) -> None:
        """Map already-resident pages (a matched prefix) as slot ``slot``'s
        leading blocks, taking a reference on each. The slot must be empty
        (fresh admission)."""
        assert int(self._blocks_used[slot]) == 0, "slot must be empty"
        r = self.replica_of(slot)
        for b, page in enumerate(pages):
            self._rc[r, page] += 1
            self.tables[slot, b] = page
        self._blocks_used[slot] = len(pages)

    def alloc_pages(self, n: int, replica: int = 0) -> List[int]:
        """Pop ``n`` fresh pages (refcount 1 each) WITHOUT mapping them to
        a slot — the host-tier restore path allocates its landing pages
        here, scatters the stored KV in, then maps them via
        ``append_owned``. Raises PoolExhausted (after asking the
        reclaimer) with nothing allocated."""
        self._take(n, replica)
        out: List[int] = []
        for _ in range(n):
            page = self._free[replica].pop()
            self._rc[replica, page] = 1
            out.append(page)
        return out

    def append_owned(self, slot: int, pages: Sequence[int]) -> None:
        """Map already-allocated pages (references taken by
        ``alloc_pages``) as ``slot``'s next logical blocks — they extend a
        ``map_shared`` prefix, so no extra reference is taken here."""
        start = int(self._blocks_used[slot])
        for b, page in enumerate(pages, start=start):
            self.tables[slot, b] = page
        self._blocks_used[slot] = start + len(pages)

    def refcount(self, page: int, replica: int = 0) -> int:
        """Public read of a page's reference count (0 = on the free
        list) — the supported accessor for policy code like
        ``PrefixIndex.reclaim`` that must know whether a page is held
        only by the index."""
        return int(self._rc[replica, page])

    def refcounts(self, pages, replica: int = 0) -> np.ndarray:
        """Vectorized :meth:`refcount` over an array of page ids — one
        numpy gather instead of a Python loop of scalar reads, for policy
        code that scans many pages under a lock (``PrefixIndex.
        reclaimable``)."""
        return self._rc[replica, np.asarray(pages, dtype=np.int64)]

    def incref(self, page: int, replica: int = 0) -> None:
        self._rc[replica, page] += 1

    def decref(self, page: int, replica: int = 0) -> None:
        self._rc[replica, page] -= 1
        if self._rc[replica, page] == 0:
            self._free[replica].append(page)
        assert self._rc[replica, page] >= 0, \
            f"page {page} (replica {replica}) refcount underflow"

    def free_slot(self, slot: int) -> None:
        """Drop the slot's reference on each of its pages; pages whose
        refcount hits zero return to the free list (shared prefix pages
        survive under their other owners / the prefix index). Blocks
        released earlier by window trimming or window+sink pruning were
        already decref'd and are skipped."""
        used = int(self._blocks_used[slot])
        r = self.replica_of(slot)
        plo, phi = int(self._pruned_lo[slot]), int(self._pruned_hi[slot])
        for b in range(self._trimmed[slot], used):
            if plo <= b < phi:
                continue  # pruned: reference already dropped
            self.decref(int(self.tables[slot, b]), r)
        # trimmed/pruned entries were already decref'd — just restore the
        # "unbacked maps page 0" invariant for the whole row
        self.tables[slot, :used] = SACRIFICIAL_PAGE
        self._blocks_used[slot] = 0
        self._trimmed[slot] = 0
        self._pruned_lo[slot] = 0
        self._pruned_hi[slot] = 0

    def trim_below_window(self, slot: int, length: int, window: int) -> int:
        """Release the slot's leading blocks that sliding-window attention
        can never read again: block b is dead once its last row
        ``(b+1)*P - 1`` falls below ``length - window`` (window starts only
        move forward, so this is monotone-safe — the reader masks/skips
        those blocks already; ops/paged_attention.py start_blk). The table
        entries keep their stale page ids, which is fine: they are never
        read and ``ensure`` never rewinds. Returns blocks freed now."""
        used = int(self._blocks_used[slot])
        r = self.replica_of(slot)
        dead_rows = max(length - window, 0)
        dead = min(dead_rows // self.page_size, used)
        freed = 0
        for b in range(self._trimmed[slot], dead):
            self.decref(int(self.tables[slot, b]), r)
            freed += 1
        if dead > self._trimmed[slot]:
            self._trimmed[slot] = dead
        return freed

    def prune_range(self, slot: int, lo: int, hi: int) -> int:
        """Window+sink KV compression: release the slot's logical blocks
        [lo, hi) — the dead middle between the attention-sink pages
        ([0, lo)) and the sliding window's tail. Each released page drops
        this slot's reference (pages shared with the prefix index or
        other slots survive under their other owners) and its table entry
        is remapped to the sacrificial page, so a stale read is
        deterministic garbage the pruned attention mask never exposes.
        The range only grows forward: repeated calls release
        [max(lo, previous hi), hi). Returns blocks released now.
        Caller (the engine, under its lock) guarantees the mask stops
        attending these rows before the next dispatch."""
        used = int(self._blocks_used[slot])
        hi = min(hi, used)
        prev_hi = int(self._pruned_hi[slot])
        start = max(lo, prev_hi)
        if hi <= start:
            return 0
        r = self.replica_of(slot)
        freed = 0
        for b in range(start, hi):
            self.decref(int(self.tables[slot, b]), r)
            self.tables[slot, b] = SACRIFICIAL_PAGE
            freed += 1
        if prev_hi == 0:
            self._pruned_lo[slot] = lo
        self._pruned_hi[slot] = hi
        return freed

    def pruned_blocks(self, slot: int) -> int:
        """Blocks of ``slot`` released by :meth:`prune_range` so far."""
        return int(self._pruned_hi[slot] - self._pruned_lo[slot]) \
            if self._pruned_hi[slot] else 0

    def slot_pages_resident(self, slot: int) -> int:
        """Pages the slot currently references (mapped blocks minus
        window-trimmed and pruned ones) — what the compressed-slot
        residency gauge reports."""
        return max(
            int(self._blocks_used[slot]) - int(self._trimmed[slot])
            - self.pruned_blocks(slot),
            0,
        )

    def slot_rows_backed(self, slot: int) -> int:
        return int(self._blocks_used[slot]) * self.page_size


def chain_hashes(
    token_ids: Sequence[int], page_size: int, num_blocks: int
) -> List[bytes]:
    """Content hash per full prompt block, chained so a block's hash
    commits to everything before it — matching block b therefore matches
    the entire prefix [0, (b+1)*P), which is exactly the K/V-equivalence
    condition (K/V of a row depends on all rows before it).

    sha256 over the token bytes, NOT Python's ``hash()``: the index key
    decides whose K/V a request attends over, so a collision is silent
    cross-request cache poisoning — and tuple ``hash()`` is analyzable
    enough to craft collisions in a multi-tenant deployment."""
    import hashlib

    hashes: List[bytes] = []
    h = b""
    for b in range(num_blocks):
        block = np.asarray(
            token_ids[b * page_size : (b + 1) * page_size], np.int32
        )
        h = hashlib.sha256(h + block.tobytes()).digest()
        hashes.append(h)
    return hashes


# -- host-tier wire format (fleet KV transfer, aios_tpu/fleet/kvx.py) -------

# One HostPageStore entry <-> self-describing bytes: magic, tensor count,
# then per tensor key / dtype string / shape / raw buffer. The crc32 rides
# the RPC envelope separately (fleet.proto PageEntry.crc32), computed by
# HostPageStore._entry_crc over the ARRAYS — so the receiver re-derives it
# from the unpacked entry and a flipped bit anywhere in transit (or in the
# sender's host RAM) fails verification, never scatters into live KV.
_WIRE_MAGIC = b"KVX1"


def pack_entry(entry: Dict[str, np.ndarray]) -> bytes:
    """Serialize one page-KV entry for the transfer plane (sorted keys,
    so the byte stream — like the crc — is order-independent)."""
    parts = [_WIRE_MAGIC, struct.pack("<B", len(entry))]
    for key in sorted(entry):
        a = np.ascontiguousarray(entry[key])
        kb = key.encode("utf-8")
        db = a.dtype.str.encode("ascii")
        parts.append(struct.pack("<B", len(kb)))
        parts.append(kb)
        parts.append(struct.pack("<B", len(db)))
        parts.append(db)
        parts.append(struct.pack("<B", a.ndim))
        parts.append(struct.pack(f"<{a.ndim}I", *a.shape))
        parts.append(struct.pack("<Q", a.nbytes))
        parts.append(a.tobytes())
    return b"".join(parts)


def unpack_entry(data: bytes) -> Dict[str, np.ndarray]:
    """Inverse of :func:`pack_entry`. Raises ``ValueError`` on any
    malformed framing — the transfer plane counts that as a
    ``decode_error`` and falls back to local prefill, exactly like a
    failed host-tier restore. Arrays are COPIES (writable): store
    entries must be mutable for the ``host_store.corrupt`` fault
    point and immutable-by-convention everywhere else."""
    if data[:4] != _WIRE_MAGIC:
        raise ValueError("bad page-entry magic")
    off = 4
    try:
        (n,) = struct.unpack_from("<B", data, off)
        off += 1
        entry: Dict[str, np.ndarray] = {}
        for _ in range(n):
            (klen,) = struct.unpack_from("<B", data, off)
            off += 1
            key = data[off : off + klen].decode("utf-8")
            off += klen
            (dlen,) = struct.unpack_from("<B", data, off)
            off += 1
            dtype = np.dtype(data[off : off + dlen].decode("ascii"))
            off += dlen
            (ndim,) = struct.unpack_from("<B", data, off)
            off += 1
            shape = struct.unpack_from(f"<{ndim}I", data, off)
            off += 4 * ndim
            (nbytes,) = struct.unpack_from("<Q", data, off)
            off += 8
            if off + nbytes > len(data):
                raise ValueError("page-entry payload truncated")
            a = np.frombuffer(
                data[off : off + nbytes], dtype=dtype
            ).reshape(shape).copy()
            off += nbytes
            entry[key] = a
    except struct.error as exc:
        raise ValueError(f"bad page-entry framing: {exc}") from exc
    if off != len(data):
        raise ValueError("trailing bytes after page-entry payload")
    return entry


class HostPageStore:
    """Host-RAM spill tier behind the prefix cache (hash -> page KV bytes).

    Every HBM eviction from the :class:`PrefixIndex` — LRU past
    ``max_pages`` or the allocator's ``reclaim()`` under pool pressure —
    used to throw the computed KV away; with a store configured
    (``AIOS_TPU_PREFIX_HOST_BYTES`` / ``ModelConfig.prefix_host_bytes``)
    the page's contents are copied device->host here instead, and a later
    prompt whose hash chain misses HBM but hits this tier restores them
    with a ``device_put`` + scatter instead of a prefill forward pass.
    Host RAM is orders of magnitude larger than the HBM slack the index
    can hold, so this multiplies effective prefix capacity (RTP-LLM's
    multi-tier KV cache, PAPERS.md).

    Entries are numpy arrays keyed by the same chain hash the index uses;
    the byte budget is enforced by LRU eviction. The store has its own
    lock: the spill worker writes from its background thread, the engine
    reads under its dispatch lock, and the serving router peeks without
    either.

    Integrity: every entry carries a crc32 computed at spill time and
    verified at restore-probe time — host RAM sits outside the device's
    ECC domain and an entry may be days old, so a flipped byte would
    otherwise scatter silently into live KV and poison every request
    sharing the prefix. A mismatch drops the entry (counted by
    ``corruptions`` / ``aios_tpu_prefix_host_corrupt_total``) and the
    chain truncates there: the caller recomputes instead of restoring
    garbage. The ``host_store.corrupt`` fault point (docs/FAULTS.md)
    flips a byte of a matched entry to drive this path on demand."""

    def __init__(self, max_bytes: int) -> None:
        self.max_bytes = int(max_bytes)
        #: guarded_by _lock
        self._entries: "OrderedDict[bytes, Dict[str, np.ndarray]]" = (
            OrderedDict()
        )
        #: guarded_by _lock
        self._crcs: Dict[bytes, int] = {}
        self.bytes_resident = 0  #: guarded_by _lock
        self.spills = 0  # entries accepted from HBM evictions
        self.restores = 0  # entries promoted back into pool pages
        self.hits = 0  # restore probes that found >= 1 entry
        self.misses = 0
        self.corruptions = 0  # entries dropped on crc32 mismatch
        self._lock = make_lock("host_store")

    @staticmethod
    def _entry_bytes(entry: Dict[str, np.ndarray]) -> int:
        return sum(int(a.nbytes) for a in entry.values())

    @staticmethod
    def _entry_crc(entry: Dict[str, np.ndarray]) -> int:
        crc = 0
        for key in sorted(entry):
            a = entry[key]
            if not a.flags["C_CONTIGUOUS"]:
                a = np.ascontiguousarray(a)
            # checksum the array's buffer directly — tobytes() would
            # copy every page just to feed the crc, doubling memory
            # traffic on each spill and restore probe
            crc = zlib.crc32(a, crc)
        return crc

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def put(self, h: bytes, entry: Dict[str, np.ndarray]) -> None:
        """Insert a spilled page (the newest entry; LRU evicts past the
        byte budget). An entry bigger than the whole budget is dropped.
        The crc32 is computed OUTSIDE the lock (spill-worker thread CPU
        time; the engine's restore probe shares this lock)."""
        nb = self._entry_bytes(entry)
        if nb > self.max_bytes:
            return
        crc = self._entry_crc(entry)
        with self._lock:
            old = self._entries.pop(h, None)
            if old is not None:
                self.bytes_resident -= self._entry_bytes(old)
            self._entries[h] = entry
            self._crcs[h] = crc
            self.bytes_resident += nb
            self.spills += 1
            while self.bytes_resident > self.max_bytes and self._entries:
                dropped_h, dropped = self._entries.popitem(last=False)
                self._crcs.pop(dropped_h, None)
                self.bytes_resident -= self._entry_bytes(dropped)

    def match_chain(
        self, hashes: Sequence[bytes]
    ) -> List[Tuple[bytes, Dict[str, np.ndarray]]]:
        """Longest stored prefix of ``hashes`` (LRU refreshed, hit/miss
        counted once per probe). Entries stay resident until the caller
        confirms the restore with ``discard`` — a failed restore (pool
        exhausted mid-allocation) must not lose the spilled KV.

        Every matched entry's crc32 is verified before it is handed out;
        a mismatch drops the entry and truncates the chain there (the
        caller recomputes the tail — restoring a corrupt page would
        poison every request sharing the prefix). The crc pass runs
        OUTSIDE the lock (put()'s rationale, mirrored: the spill worker
        and concurrent probes must not stall behind checksum CPU time);
        entries are immutable once stored, and the drop re-checks
        identity under the lock in case a concurrent put replaced the
        hash meanwhile."""
        candidates: List[Tuple[bytes, Dict[str, np.ndarray], int]] = []
        with self._lock:
            for h in hashes:
                e = self._entries.get(h)
                if e is None:
                    break
                self._entries.move_to_end(h)
                candidates.append((h, e, self._crcs.get(h)))
        if candidates:
            # chaos (docs/FAULTS.md): fired only when the probe actually
            # matched — flipping nothing on a miss would count an
            # injected fault whose recovery path never ran
            act = faults.point("host_store.corrupt")
            if act is not None:
                a = next(iter(candidates[0][1].values()))
                a.flat[0] = -a.flat[0] if a.flat[0] else 1
        out: List[Tuple[bytes, Dict[str, np.ndarray]]] = []
        bad: Optional[Tuple[bytes, Dict[str, np.ndarray]]] = None
        for h, e, crc in candidates:
            if crc != self._entry_crc(e):
                bad = (h, e)
                break
            out.append((h, e))
        with self._lock:
            if bad is not None and self._entries.get(bad[0]) is bad[1]:
                self._entries.pop(bad[0], None)
                self._crcs.pop(bad[0], None)
                self.bytes_resident -= self._entry_bytes(bad[1])
                self.corruptions += 1
                log.error(
                    "host-tier page failed crc32 verification; "
                    "dropped (chain truncated at %d of %d)",
                    len(out), len(hashes),
                )
            if out:
                self.hits += 1
            else:
                self.misses += 1
        return out

    def note_failed_restore(self) -> None:
        """A probe hit but the restore itself failed (scatter error or an
        injected ``host_store.restore_fail``): count it as a miss too —
        the request paid a full recompute, which is what the hit/miss
        ratio is supposed to predict."""
        with self._lock:
            self.misses += 1

    def peek_chain(self, hashes: Sequence[bytes]) -> int:
        """Length of the longest stored prefix WITHOUT touching LRU order
        or the hit/miss counters — the serving router's read-only overlap
        probe (same contract as ``PrefixIndex.peek``)."""
        n = 0
        with self._lock:
            for h in hashes:
                if h not in self._entries:
                    break
                n += 1
        return n

    def export_chain(
        self, hashes: Sequence[bytes], budget_bytes: int = 0
    ) -> List[Tuple[bytes, int, Dict[str, np.ndarray]]]:
        """Longest stored prefix of ``hashes`` as wire-ready
        ``(hash, crc32, entry)`` triples for the fleet transfer plane —
        no LRU refresh and no hit/miss movement (exporting to a peer is
        not a local restore probe). ``budget_bytes`` > 0 truncates the
        chain once the cumulative entry size would exceed it.

        The sender-side half of the verified-at-both-ends contract:
        every entry's stored crc32 is recomputed here before it ships; a
        mismatch (host-RAM rot since the spill) drops the entry, counts
        a corruption, and truncates the chain — shipping a rotten page
        would just move the receiver's crc failure one hop later."""
        candidates: List[Tuple[bytes, Dict[str, np.ndarray], int]] = []
        total = 0
        with self._lock:
            for h in hashes:
                e = self._entries.get(h)
                if e is None:
                    break
                total += self._entry_bytes(e)
                if budget_bytes and total > budget_bytes and candidates:
                    break
                candidates.append((h, e, self._crcs.get(h)))
        out: List[Tuple[bytes, int, Dict[str, np.ndarray]]] = []
        bad: Optional[Tuple[bytes, Dict[str, np.ndarray]]] = None
        for h, e, crc in candidates:
            if crc != self._entry_crc(e):
                bad = (h, e)
                break
            out.append((h, crc, e))
        if bad is not None:
            with self._lock:
                if self._entries.get(bad[0]) is bad[1]:
                    self._entries.pop(bad[0], None)
                    self._crcs.pop(bad[0], None)
                    self.bytes_resident -= self._entry_bytes(bad[1])
                    self.corruptions += 1
            log.error(
                "host-tier page failed crc32 at export; dropped "
                "(chain truncated at %d of %d)", len(out), len(hashes),
            )
        return out

    def stored_hashes(self, limit: int) -> List[bytes]:
        """Up to ``limit`` most-recently-used entry hashes — the host
        tier's contribution to the gossiped prefix digest. Read-only
        (no LRU refresh, no counters)."""
        with self._lock:
            keys = list(self._entries.keys())
        return keys[-limit:] if limit else []

    def discard(self, hashes: Sequence[bytes], *, restored: bool = False
                ) -> None:
        """Drop entries (restore promotion, or invalidation). With
        ``restored`` the restore counter moves."""
        with self._lock:
            for h in hashes:
                e = self._entries.pop(h, None)
                self._crcs.pop(h, None)
                if e is not None:
                    self.bytes_resident -= self._entry_bytes(e)
                    if restored:
                        self.restores += 1

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._crcs.clear()
            self.bytes_resident = 0


class _PrefixIndexBase:
    """Shared plumbing of the prefix-page indexes: the allocator hookup
    (``reclaimer``), hit/miss counters, the host-tier ``spill`` hook and
    the lock discipline (the index carries its OWN lock — the serving
    router peeks it per incoming request, and a probe that had to wait
    for an in-flight decode dispatch or a multi-second XLA compile would
    stall pool-wide admission behind one replica's graph build).

    Two implementations share the contract: the legacy flat hash-chain
    map (:class:`PrefixIndex`, the ``AIOS_TPU_PREFIX_RADIX=0`` escape
    hatch) and the refcounted radix tree (:class:`RadixPrefixIndex`, the
    default — SGLang-style cross-request sharing with leaf-LRU
    eviction)."""

    def __init__(self, allocator: PageAllocator, max_pages: int) -> None:
        if allocator.replicas != 1:
            # prefix pages are replica-local under a dp-partitioned pool;
            # cross-replica sharing is impossible, so the engine disables
            # the index rather than serve replica-0-only hits
            raise ValueError(
                "prefix indexes require an unreplicated pool (replicas=1)"
            )
        self.alloc = allocator
        self.max_pages = max_pages
        self.hits = 0
        self.misses = 0
        # host-tier demotion hook: called with evicted (hash, page) pairs
        # before their references drop (see PrefixIndex docstring); None
        # keeps the pre-host-tier behavior (evictions just free the pages)
        self.spill: Optional[
            Callable[[List[Tuple[bytes, int]]], None]
        ] = None
        self._lock = make_lock("prefix_index")
        allocator.reclaimer = self.reclaim

    def reclaim(self, n: int) -> int:  # pragma: no cover - abstract
        raise NotImplementedError

    def _drop(self, evicted: List[Tuple[bytes, int]]) -> None:
        """Spill evicted entries (hook set), then release their page
        references. Runs OUTSIDE the index lock — the spill hook enqueues
        device reads and the router's ``peek`` must not wait on them; the
        allocator mutation is safe because both eviction paths are
        reached from engine-lock-holding callers. References drop only
        AFTER the spill captured the contents, so a freed page can't be
        reallocated and overwritten mid-copy."""
        if not evicted:
            return
        try:
            if self.spill is not None:
                try:
                    self.spill(evicted)
                except Exception:  # noqa: BLE001 - degrade to plain evict
                    log.exception(
                        "host-tier spill failed; dropping %d page(s)",
                        len(evicted),
                    )
        finally:
            # the references drop even if the spill dies with a
            # BaseException (KeyboardInterrupt mid-gather): these entries
            # are already out of the index, so skipping the decref would
            # leak their pages for the process lifetime
            for _, page in evicted:
                self.alloc.decref(page)


class PrefixIndex(_PrefixIndexBase):
    """Content-addressed cache of prompt-prefix pages (hash -> page).

    Agent workloads resend the same system/task preamble constantly
    (SURVEY.md section 3.1: every reasoning round rebuilds the prompt from
    the same context); matching a prompt's leading full blocks against this
    index turns their prefill into a table update — zero forward-pass
    compute and zero new pages. The index holds one reference per cached
    page, so pages survive their originating request; LRU eviction (and the
    allocator's reclaimer hook, under pool pressure) drops the coldest
    entries. Shared pages are read-only BY CONSTRUCTION: matches are capped
    at the prompt's last full block minus one row, so every write a slot
    performs (tail prefill, decode) lands at rows past the shared region.

    Hashes are the ``bytes`` sha256 digests of :func:`chain_hashes`,
    end-to-end — the engine's ``_match_prefix``/``prefix_hashes`` and the
    serving router's overlap probes all trade in the same digest chain.

    ``spill`` (set by the engine when a :class:`HostPageStore` is
    configured) is called with the evicted ``[(hash, page), ...]`` pairs
    BEFORE their index references drop, outside the index lock — the
    engine captures the pages' device contents there, so an eviction
    becomes a host-tier demotion instead of a loss. The hook runs under
    the engine dispatch lock (both eviction paths are reached from
    lock-holding callers), which is what keeps the page contents stable
    until the capture is enqueued.
    """

    def __init__(self, allocator: PageAllocator, max_pages: int) -> None:
        super().__init__(allocator, max_pages)
        self._index: "OrderedDict[bytes, int]" = OrderedDict()  # hash -> page

    def snapshot(self) -> Dict[bytes, int]:
        """Point-in-time hash -> page mapping of every cached block
        (tests/diagnostics; both index implementations provide it)."""
        with self._lock:
            return dict(self._index)

    def digest(self, limit: int) -> List[Tuple[bytes, int]]:
        """Up to ``limit`` hottest ``(chain hash, depth-in-blocks)``
        pairs for the gossiped fleet prefix digest. The flat map does
        not track chain depth, so it advertises 0 (membership is what
        remote overlap scoring consumes; depth is advisory). Read-only —
        no LRU refresh, no counters."""
        with self._lock:
            keys = list(self._index.keys())
        return [(h, 0) for h in keys[-limit:]] if limit else []

    def match(self, hashes: Sequence[bytes]) -> List[int]:
        """Longest indexed prefix of ``hashes``; returns its pages (LRU
        positions refreshed). No references are taken — the caller maps
        them via ``PageAllocator.map_shared`` under the engine lock."""
        pages: List[int] = []
        with self._lock:
            for h in hashes:
                page = self._index.get(h)
                if page is None:
                    break
                self._index.move_to_end(h)
                pages.append(page)
            if pages:
                self.hits += 1
            else:
                self.misses += 1
        return pages

    def peek(self, hashes: Sequence[bytes]) -> int:
        """Length of the longest indexed prefix of ``hashes`` WITHOUT
        touching hit/miss counters or LRU order — the serving router's
        read-only overlap probe (scoring N replicas per request must not
        skew the cache statistics or keep cold entries artificially
        warm)."""
        n = 0
        with self._lock:
            for h in hashes:
                if h not in self._index:
                    break
                n += 1
        return n

    def put(self, hashes: Sequence[bytes], pages: Sequence[int]) -> None:
        """Register freshly computed (or host-restored) prefix blocks, one
        index reference each; LRU entries past ``max_pages`` are evicted —
        spilled to the host tier first when a ``spill`` hook is set."""
        evicted: List[Tuple[bytes, int]] = []
        with self._lock:
            for h, page in zip(hashes, pages):
                if h in self._index:
                    self._index.move_to_end(h)
                    continue
                self.alloc.incref(page)
                self._index[h] = page
            while len(self._index) > self.max_pages:
                evicted.append(self._index.popitem(last=False))
        self._drop(evicted)

    def clear(self) -> None:
        """Drop every entry (and its page reference) WITHOUT spilling —
        the warmup/shutdown path, where the cached blocks are synthetic
        junk that must not pollute the host tier."""
        with self._lock:
            while self._index:
                _, page = self._index.popitem(last=False)
                self.alloc.decref(page)

    def reclaimable(self) -> int:
        """How many entries ``reclaim`` could free right now (pages held
        ONLY by the index, refcount 1). The restore path pre-clamps its
        chain to free + reclaimable so a chain the pool can't back
        doesn't evict cold HBM entries just to fail anyway."""
        with self._lock:
            if not self._index:
                return 0
            pages = np.fromiter(
                self._index.values(), dtype=np.int64, count=len(self._index)
            )
            return int(np.count_nonzero(self.alloc.refcounts(pages) == 1))

    def reclaim(self, n: int) -> int:
        """Drop up to ``n`` cold entries whose pages are held ONLY by the
        index (refcount 1) — called by the allocator when the free list
        runs dry. Entries still shared by live slots are left alone.
        Dropped pages spill to the host tier (hook set) before they free,
        so pool pressure demotes the cold prefix KV instead of burning
        it."""
        evicted: List[Tuple[bytes, int]] = []
        with self._lock:
            for h in list(self._index):
                if len(evicted) >= n:
                    break
                page = self._index[h]
                if self.alloc.refcount(page) == 1:
                    del self._index[h]
                    evicted.append((h, page))
        self._drop(evicted)
        return len(evicted)


class _RadixNode:
    """One path-compressed radix-tree node: a run of consecutive prefix
    blocks (``entries`` = aligned (chain hash, page) pairs) plus children
    keyed by the FIRST hash of each child's run. ``stamp`` is the LRU
    clock at the node's last traversal."""

    __slots__ = ("entries", "children", "parent", "stamp")

    def __init__(self, parent: Optional["_RadixNode"]) -> None:
        self.entries: List[Tuple[bytes, int]] = []
        self.children: Dict[bytes, "_RadixNode"] = {}
        self.parent = parent
        self.stamp = 0


class RadixPrefixIndex(_PrefixIndexBase):
    """Refcounted radix tree over prompt-prefix token blocks (SGLang-style,
    arXiv:2312.07104) — the default prefix index.

    Same digest currency as :class:`PrefixIndex` (the ``bytes`` sha256
    chain of :func:`chain_hashes`; a block's hash commits to everything
    before it, so a prompt's hash chain IS its tree path), but the tree
    structure buys what the flat LRU map cannot:

      * **sharing by construction** — eviction is leaf-LRU, bottom-up, so
        a cached chain's prefix is always cached too. The flat map could
        evict block 0 of a chain while deeper blocks survived as
        unreachable garbage, pinning their pages until a pool-pressure
        reclaim; here that state is unrepresentable.
      * **divergence-aware structure** — two prompts sharing K leading
        blocks share one K-entry path and branch below it (path
        compression splits a node at the divergence point), so the shared
        preamble's recency is maintained once, by every user, while each
        cold divergent tail ages out on its own.
      * **partial-node overlap credit** — ``peek`` counts a match that
        ends mid-node (a prompt diverging inside another prompt's cached
        run), so the serving router's overlap score sees the true
        shareable row count, not floor-to-node granularity.

    Eviction (LRU past ``max_pages``) and pool-pressure ``reclaim`` both
    pop entries from leaf TAILS (deepest blocks of the coldest chains
    first) and hand the evicted (hash, page) pairs to the PR 4 ``spill``
    hook before the references drop — the host-tier demotion contract is
    unchanged. ``put`` accepts chains whose leading blocks are already
    cached (the host-tier restore re-inserts a restored segment by
    passing its lead context), traversing the cached part and grafting
    only the new suffix."""

    def __init__(self, allocator: PageAllocator, max_pages: int) -> None:
        super().__init__(allocator, max_pages)
        self._root = _RadixNode(None)
        self._size = 0  # total entries (== pages referenced by the tree)
        self._clock = 0

    # -- internal helpers (caller holds self._lock) -------------------------

    def _split(self, node: _RadixNode, j: int) -> None:
        """Path-compression split: ``entries[:j]`` stay on ``node``; the
        suffix moves to a new child that inherits node's children (and
        node's pre-touch stamp, so the unshared tail ages on its own)."""
        suffix = node.entries[j:]
        child = _RadixNode(node)
        child.entries = suffix
        child.children = node.children
        child.stamp = node.stamp
        for c in child.children.values():
            c.parent = child
        node.entries = node.entries[:j]
        node.children = {suffix[0][0]: child}

    def _leaves(self):
        stack = [self._root]
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            elif n is not self._root:
                yield n

    def _detach(self, node: _RadixNode) -> None:
        parent = node.parent
        if parent is None:
            return
        for key, child in list(parent.children.items()):
            if child is node:
                del parent.children[key]
                break

    def _evict_overflow(self, evicted: List[Tuple[bytes, int]]) -> None:
        """Pop deepest blocks of least-recently-used chains until the
        size fits ``max_pages``. One leaf DFS per VICTIM LEAF, not per
        entry: the coldest leaf stays the minimum-stamp leaf until it
        drains, so its whole tail pops under one scan — a bulk overflow
        (a long prompt registering many blocks at once) holds the index
        lock for O(overflow + leaves), not O(overflow x tree)."""
        while self._size > self.max_pages:
            best = None
            for leaf in self._leaves():
                if leaf.entries and (
                    best is None or leaf.stamp < best.stamp
                ):
                    best = leaf
            if best is None:
                return
            while best.entries and self._size > self.max_pages:
                evicted.append(best.entries.pop())
                self._size -= 1
            if not best.entries:
                self._detach(best)  # parent may become the new leaf

    # -- the PrefixIndex contract -------------------------------------------

    def match(self, hashes: Sequence[bytes]) -> List[int]:
        """Longest cached prefix of ``hashes``; returns its pages (path
        stamps refreshed). A match ending mid-node splits it, so the
        matched run's recency refreshes without dragging the divergent
        tail along. No references are taken — the caller maps the pages
        via ``PageAllocator.map_shared`` under the engine lock."""
        pages: List[int] = []
        with self._lock:
            self._clock += 1
            node, i = self._root, 0
            while i < len(hashes):
                child = node.children.get(hashes[i])
                if child is None:
                    break
                j = 0
                while (
                    j < len(child.entries)
                    and i < len(hashes)
                    and child.entries[j][0] == hashes[i]
                ):
                    pages.append(child.entries[j][1])
                    i += 1
                    j += 1
                if j < len(child.entries):
                    # match ended mid-run (divergence OR a shorter
                    # prompt): split so only the MATCHED prefix's
                    # recency refreshes — stamping the whole node would
                    # keep its cold unmatched tail permanently warm
                    self._split(child, j)
                    child.stamp = self._clock
                    break
                child.stamp = self._clock
                node = child
            if pages:
                self.hits += 1
            else:
                self.misses += 1
        return pages

    def peek(self, hashes: Sequence[bytes]) -> int:
        """Length of the longest cached prefix WITHOUT touching hit/miss
        counters, stamps, or structure — the serving router's read-only
        overlap probe. Partial-node overlap IS credited: a prompt
        diverging inside a cached run scores the blocks it shares."""
        n = 0
        with self._lock:
            node, i = self._root, 0
            while i < len(hashes):
                child = node.children.get(hashes[i])
                if child is None:
                    break
                j = 0
                while (
                    j < len(child.entries)
                    and i < len(hashes)
                    and child.entries[j][0] == hashes[i]
                ):
                    n += 1
                    i += 1
                    j += 1
                if j < len(child.entries):
                    break
                node = child
        return n

    def put(self, hashes: Sequence[bytes], pages: Sequence[int]) -> None:
        """Register freshly computed (or host-restored) prefix blocks, one
        index reference per NEW entry; blocks already cached are traversed
        (recency refreshed), so callers may pass a chain whose lead is
        resident — the restore path passes lead + restored segment so the
        graft lands at the right tree position. Entries past ``max_pages``
        evict leaf-LRU — spilled to the host tier first when a ``spill``
        hook is set."""
        hashes = list(hashes)
        pages = list(pages)
        evicted: List[Tuple[bytes, int]] = []
        with self._lock:
            self._clock += 1
            node, i = self._root, 0
            while i < len(hashes):
                child = node.children.get(hashes[i])
                if child is None:
                    break
                j = 0
                while (
                    j < len(child.entries)
                    and i < len(hashes)
                    and child.entries[j][0] == hashes[i]
                ):
                    i += 1
                    j += 1
                if j < len(child.entries):
                    # split BEFORE stamping (divergence OR a shorter
                    # chain): the unshared suffix keeps the node's old
                    # stamp and ages on its own
                    self._split(child, j)
                    node = child
                    child.stamp = self._clock
                    break
                child.stamp = self._clock
                node = child
            if i < len(hashes) and i < len(pages):
                new = _RadixNode(node)
                new.stamp = self._clock
                for h, page in zip(hashes[i:], pages[i:]):
                    self.alloc.incref(page)
                    new.entries.append((h, page))
                node.children[hashes[i]] = new
                self._size += len(new.entries)
            self._evict_overflow(evicted)
        self._drop(evicted)

    def clear(self) -> None:
        """Drop every entry (and its page reference) WITHOUT spilling —
        the warmup/shutdown path (synthetic blocks must not pollute the
        host tier)."""
        with self._lock:
            stack = [self._root]
            while stack:
                n = stack.pop()
                for _, page in n.entries:
                    self.alloc.decref(page)
                stack.extend(n.children.values())
            self._root = _RadixNode(None)
            self._size = 0

    def reclaimable(self) -> int:
        """How many entries ``reclaim`` could free right now: an entry is
        reclaimable iff its page is held ONLY by the tree (refcount 1)
        AND everything below it in its subtree is reclaimable too —
        removal is suffix-of-tree only, or a cached chain would lose a
        middle block and strand its tail."""
        with self._lock:
            total = 0
            fully: Dict[int, bool] = {}
            stack: List[Tuple[_RadixNode, bool]] = [(self._root, False)]
            while stack:
                node, seen = stack.pop()
                if not seen:
                    stack.append((node, True))
                    for c in node.children.values():
                        stack.append((c, False))
                    continue
                f = all(
                    fully.pop(id(c)) for c in node.children.values()
                )
                if f:
                    run = 0
                    for _, page in reversed(node.entries):
                        if self.alloc.refcount(page) == 1:
                            run += 1
                        else:
                            break
                    total += run
                    f = run == len(node.entries)
                fully[id(node)] = f
            return total

    def reclaim(self, n: int) -> int:
        """Drop up to ``n`` cold entries whose pages are held ONLY by the
        tree — called by the allocator when the free list runs dry.
        Bottom-up and LRU-first: tail entries of the coldest leaves pop
        until a live-shared page blocks that chain; a leaf that empties
        detaches, exposing its parent's tail next. Dropped pages spill to
        the host tier (hook set) before they free."""
        evicted: List[Tuple[bytes, int]] = []
        with self._lock:
            while len(evicted) < n:
                cands = [
                    l for l in self._leaves()
                    if l.entries
                    and self.alloc.refcount(l.entries[-1][1]) == 1
                ]
                if not cands:
                    break
                leaf = min(cands, key=lambda l: l.stamp)
                while (
                    leaf.entries
                    and len(evicted) < n
                    and self.alloc.refcount(leaf.entries[-1][1]) == 1
                ):
                    evicted.append(leaf.entries.pop())
                    self._size -= 1
                if not leaf.entries:
                    self._detach(leaf)
        self._drop(evicted)
        return len(evicted)

    def snapshot(self) -> Dict[bytes, int]:
        """Point-in-time hash -> page mapping of every cached block
        (tests/diagnostics; same contract as ``PrefixIndex.snapshot``)."""
        with self._lock:
            out: Dict[bytes, int] = {}
            stack = [self._root]
            while stack:
                n = stack.pop()
                out.update(n.entries)
                stack.extend(n.children.values())
            return out

    def digest(self, limit: int) -> List[Tuple[bytes, int]]:
        """Up to ``limit`` ``(chain hash, depth-in-blocks)`` pairs for
        the gossiped fleet prefix digest — breadth-first, so when the
        cap bites, SHALLOW blocks survive: a remote prompt shorter than
        a cached chain still finds its prefix hash in the digest, while
        an over-deep match merely degrades to the advertised depth.
        Read-only (same contract as ``peek``)."""
        if not limit:
            return []
        out: List[Tuple[bytes, int]] = []
        with self._lock:
            queue: List[Tuple[_RadixNode, int]] = [(self._root, 0)]
            while queue and len(out) < limit:
                node, depth = queue.pop(0)
                d = depth
                for h, _ in node.entries:
                    d += 1
                    out.append((h, d))
                    if len(out) >= limit:
                        break
                for child in node.children.values():
                    queue.append((child, d))
        return out
