"""Mesh construction and parameter/cache sharding plans.

Megatron-style tensor parallelism expressed as GSPMD annotations: we place
NamedShardings on params and KV caches, and XLA inserts the ICI collectives
(all-reduce after row-parallel matmuls, all-gather for the vocab-sharded
embedding) — no hand-written collective calls on the decode path, per the
scaling-book recipe: pick a mesh, annotate, let XLA do the rest.

Axes:
  dp — data/replica axis: batch slots in decode, batch in training
  sp — sequence axis: ring-attention sequence parallelism (long context)
  ep — expert axis: MoE experts sharded across chips (engine/moe.py); the
       dense-MoE einsum contracts the expert axis, so GSPMD inserts one
       psum over ep per MoE layer — expert parallelism with no explicit
       dispatch collectives
  tp — model axis: attention heads + FFN hidden sharded across chips
       (innermost: the per-matmul allreduce rides the fastest ICI links)

Equivalent role in the reference: none (single-process llama.cpp); this is
the "Mistral-7B tensor-parallel decode across 4 chips (ICI all-reduce)"
benchmark config of BASELINE.md.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..engine.config import ModelConfig


def build_mesh(
    n_devices: Optional[int] = None,
    *,
    dp: int = 1,
    sp: int = 1,
    ep: int = 1,
    tp: Optional[int] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a (dp, sp, ep, tp) mesh. Unspecified tp absorbs the rest."""
    devices = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    if tp is None:
        assert n % (dp * sp * ep) == 0, (n, dp, sp, ep)
        tp = n // (dp * sp * ep)
    assert dp * sp * ep * tp == n, f"mesh {dp}x{sp}x{ep}x{tp} != {n} devices"
    arr = np.asarray(devices).reshape(dp, sp, ep, tp)
    return Mesh(arr, axis_names=("dp", "sp", "ep", "tp"))


# Partition rules for the engine params pytree (path suffix -> spec).
# Column-parallel projections shard the output dim on tp; row-parallel ones
# shard the input dim, and GSPMD inserts the psum on their outputs.
PARAM_RULES: Dict[str, P] = {
    "embed": P("tp", None),  # vocab-sharded
    "layers/attn_norm": P(None, None),
    "layers/ffn_norm": P(None, None),
    "layers/q_norm": P(None, None),
    "layers/k_norm": P(None, None),
    "layers/wq": P(None, None, "tp"),
    "layers/wk": P(None, None, "tp"),
    "layers/wv": P(None, None, "tp"),
    "layers/wo": P(None, "tp", None),
    "layers/w_gate": P(None, None, "tp"),
    "layers/w_up": P(None, None, "tp"),
    "layers/w_down": P(None, "tp", None),
    # MoE leaves [L, X, in, out]: experts over ep, expert-FFN hidden over tp
    # (the router is tiny and stays replicated)
    "layers/w_router": P(None, None, None),
    "layers/we_gate": P(None, "ep", None, "tp"),
    "layers/we_up": P(None, "ep", None, "tp"),
    "layers/we_gateup": P(None, "ep", None, "tp"),
    "layers/we_down": P(None, "ep", "tp", None),
    "final_norm": P(None),
    "lm_head": P(None, "tp"),
}

# KV cache [L, slots, C, KH, D]: slots over dp, kv heads over tp.
CACHE_SPEC = P(None, "dp", None, "tp", None)
# int8 KV-cache scales [L, slots, C, KH] ride the same placement.
CACHE_SCALE_SPEC = P(None, "dp", None, "tp")
# Context-sharded variant: the C axis additionally splits over sp, so one
# slot's KV can exceed a single chip's HBM (long-context serving). XLA
# partitions the decode attention over the sharded contraction itself —
# per-shard partial max/denominator/accumulator with psums over sp, the
# flash-decoding-across-chips pattern — while row writes stay local to the
# owning shard (verified: no cache-sized all-gathers in the lowered HLO).
CACHE_SPEC_SEQ = P(None, "dp", "sp", "tp", None)
CACHE_SCALE_SPEC_SEQ = P(None, "dp", "sp", "tp")


@dataclass
class ShardingPlan:
    """Placement helper handed to TPUEngine / the trainer."""

    mesh: Mesh

    def spec_for(self, path: str) -> P:
        if path in PARAM_RULES:
            return PARAM_RULES[path]
        # int8 serving leaves {"q", "s"} (model.quantize_params fuse=False):
        # the int8 tensor shards exactly like the dense weight it replaces;
        # the per-output-channel scale is size 1 on the contraction dim
        # (axis -2), so its spec is the weight's with that axis unsharded.
        if path.endswith(("/q", "/s")):
            base = PARAM_RULES.get(path[:-2])
            if base is not None:
                if path.endswith("/q"):
                    return base
                return P(*base[:-2], None, base[-1])
        # int4 serving leaves {"q4", "s4"} (packed nibbles + group scales):
        # q4 [..., K/2, N] shards exactly like the dense weight (nibble
        # pairs never straddle a shard: K/tp stays even for every real
        # geometry); s4 [..., G, 1, N] is the weight's spec with the
        # contraction axis carrying the group axis and a fresh unsharded
        # axis in front of N.
        if path.endswith(("/q4", "/s4")):
            base = PARAM_RULES.get(path[:-3])
            if base is not None:
                if path.endswith("/q4"):
                    return base
                return P(*base[:-1], None, base[-1])
        raise KeyError(f"no partition rule for param {path!r}")

    def params_shardings(self, params) -> Dict:
        def walk(tree, prefix=""):
            out = {}
            for k, v in tree.items():
                path = f"{prefix}{k}"
                if isinstance(v, dict):
                    out[k] = walk(v, path + "/")
                else:
                    out[k] = NamedSharding(self.mesh, self.spec_for(path))
            return out

        return walk(params)

    def put_params(self, params):
        shardings = self.params_shardings(params)
        return jax.tree.map(
            lambda x, s: jax.device_put(jax.numpy.asarray(x), s), params, shardings
        )

    def put_cache(self, cache, seq_shard: bool = False):
        spec = CACHE_SPEC_SEQ if seq_shard else CACHE_SPEC
        return jax.device_put(cache, NamedSharding(self.mesh, spec))

    def put_cache_scales(self, scales, seq_shard: bool = False):
        spec = CACHE_SCALE_SPEC_SEQ if seq_shard else CACHE_SCALE_SPEC
        return jax.device_put(scales, NamedSharding(self.mesh, spec))

    def ragged_attention(self, window: Optional[int], use_kernel: bool):
        """Per-device ragged decode attention under shard_map.

        Attention is head- and slot-local, so with q sharded (dp, tp) and
        the per-layer cache (dp, none, tp) every device attends its own
        [B/dp, C, KH/tp, D] shard with ZERO collectives — the Pallas ragged
        kernel (ops/decode_attention.py) runs per device exactly as on one
        chip. ``use_kernel=False`` swaps in the jnp reference body (CPU
        virtual meshes; numerics identical), which is how the dryrun and the
        test suite exercise this path without TPU hardware.

        Returns attn(q [B,H,D], k_l [B,C,KH,D], v_l [B,C,KH,D], lengths [B])
        -> [B, H, D], for model.decode_step's ``attn_impl`` hook.
        """
        from jax.experimental.shard_map import shard_map

        from .. import ops

        def local(q, k_l, v_l, lengths):
            if use_kernel:
                return ops.decode_attention(q, k_l, v_l, lengths, window=window)
            return ops.decode_attention_reference(
                q, k_l, v_l, lengths, window=window
            )

        return shard_map(
            local,
            mesh=self.mesh,
            in_specs=(
                P("dp", "tp", None),
                P("dp", None, "tp", None),
                P("dp", None, "tp", None),
                P("dp"),
            ),
            out_specs=P("dp", "tp", None),
            check_rep=False,
        )

    def int4_matmul_impl(self, use_kernel: bool):
        """Per-device packed-nibble int4 matmuls under shard_map.

        The int4 kernel (ops/int4_matmul.py) is a per-device Pallas
        program, so under a sharding plan it cannot ride GSPMD like the
        int8 dot_generals do. Same answer as ragged decode attention: run
        the kernel on each device's weight shard under shard_map —
        Megatron TP done by hand for exactly these matmuls.

          col  — column-parallel (wq/wk/wv/w_gate/w_up): the output dim is
                 tp-sharded, activations replicated; zero collectives.
          row  — row-parallel (wo/w_down): the contraction dim (and its
                 scale groups) is tp-sharded; a psum over tp completes the
                 partial products — the same all-reduce GSPMD inserts for
                 the dense/int8 layouts.
          head — the lm_head [E, V] with vocab tp-sharded (col pattern on
                 rank-2 activations [B, E]).

        Each device picks kernel vs jnp reference from its LOCAL shard
        dims (a shard can be kernel-ineligible even when the global shape
        is not); ``use_kernel=False`` forces the reference body — how CPU
        virtual meshes (dryrun, tests) exercise this path bit-for-bit.

        Returns f(x, leaf, kind) -> y for model.matmul's ``qmm`` hook.
        """
        from jax.experimental.shard_map import shard_map

        from ..ops.int4_matmul import (
            infer_group,
            int4_matmul,
            int4_matmul_reference,
            kernel_supported,
        )

        def local_mm(x_l, q4_l, s4_l):
            g = infer_group(q4_l, s4_l)
            if use_kernel and kernel_supported(
                q4_l.shape[-2] * 2, q4_l.shape[-1], g
            ):
                return int4_matmul(x_l, q4_l, s4_l)
            return int4_matmul_reference(x_l, q4_l, s4_l)

        mesh = self.mesh
        specs = {
            # (x, q4, s4) in_specs, out_spec, psum over tp?
            "col": (
                (P("dp", None, None), P(None, "tp"), P(None, None, "tp")),
                P("dp", None, "tp"),
                False,
            ),
            "row": (
                (P("dp", None, "tp"), P("tp", None), P("tp", None, None)),
                P("dp", None, None),
                True,
            ),
            "head": (
                (P("dp", None), P(None, "tp"), P(None, None, "tp")),
                P("dp", "tp"),
                False,
            ),
        }
        fns = {}
        for kind, (in_specs, out_spec, reduce_tp) in specs.items():
            def local(x_l, q4_l, s4_l, _reduce=reduce_tp):
                y = local_mm(x_l, q4_l, s4_l)
                return jax.lax.psum(y, "tp") if _reduce else y

            fns[kind] = shard_map(
                local,
                mesh=mesh,
                in_specs=in_specs,
                out_specs=out_spec,
                check_rep=False,
            )

        def qmm(x, leaf, kind):
            return fns[kind](x, leaf["q4"], leaf["s4"])

        return qmm

    def paged_pool_impl(self, window: Optional[int], use_kernel: bool,
                        quantized: bool):
        """Per-device paged-pool write + attend under shard_map (dp > 1).

        Under a dp-replicated plan the page pool's physical page axis
        shards over dp and table entries are REPLICA-LOCAL ids
        (engine/paged.py PageAllocator replicas=...). A GSPMD gather
        through the tables could not prove locality and would all-gather
        the pool; under shard_map each device scatters/gathers its own
        slots' rows in its own pool shard — zero collectives, exactly the
        single-chip paged path per device. kv heads additionally shard
        over tp, like the dense cache.

        Returns, for the bf16 pool,
          f(q [B,H,D], k_new [B,KH,D], v_new, k_l [N,P,KH,D], v_l,
            tables [B,MB], lengths [B], pages [B], offs [B])
            -> (attn [B,H,D], k_l', v_l')
        and for the int8 pool the same with (k_s [N,P,KH], v_s) appended
        to inputs and outputs. Plugged into model.decode_step_paged's
        ``pool_impl`` hook.
        """
        from jax.experimental.shard_map import shard_map

        from .. import ops
        from ..engine import model as model_mod

        def local_bf16(q, k_new, v_new, k_l, v_l, tables, lengths, pages,
                       offs):
            k_l = k_l.at[pages, offs].set(k_new.astype(k_l.dtype))
            v_l = v_l.at[pages, offs].set(v_new.astype(v_l.dtype))
            if use_kernel:
                attn = ops.paged_decode_attention(
                    q, k_l, v_l, tables, lengths, window=window
                )
            else:
                attn = ops.paged_decode_attention_reference(
                    q, k_l, v_l, tables, lengths, window=window
                )
            return attn, k_l, v_l

        def local_int8(q, k_new, v_new, k_l, v_l, k_s, v_s, tables,
                       lengths, pages, offs):
            k_l, k_s = model_mod.scatter_quant(k_l, k_s, pages, offs, k_new)
            v_l, v_s = model_mod.scatter_quant(v_l, v_s, pages, offs, v_new)
            attn = model_mod.paged_int8_attend(
                q, k_l, v_l, k_s, v_s, tables, lengths, window=window,
                use_int8_kernel=(
                    use_kernel and model_mod._int8_ragged_enabled()
                ),
            )
            return attn, k_l, v_l, k_s, v_s

        pool = P("dp", None, "tp", None)
        scale = P("dp", None, "tp")
        vec = P("dp", "tp", None)
        if quantized:
            in_specs = (vec, vec, vec, pool, pool, scale, scale,
                        P("dp", None), P("dp"), P("dp"), P("dp"))
            out_specs = (vec, pool, pool, scale, scale)
            fn = local_int8
        else:
            in_specs = (vec, vec, vec, pool, pool,
                        P("dp", None), P("dp"), P("dp"), P("dp"))
            out_specs = (vec, pool, pool)
            fn = local_bf16
        return shard_map(
            fn, mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )

    def paged_prefill_scatter(self, quantized: bool):
        """Per-device scatter of a whole prefilled prompt's K/V rows into
        the dp-sharded page pool (replica-local page ids, like
        ``paged_pool_impl``). The prompt's forward pass itself is
        replicated over dp (B=1 — dp has nothing to split), so every
        device computes the same rows; only the OWNING replica's scatter
        targets real pages — the rest write their local sacrificial
        page 0, which is never read.

        bf16: f(k_pool [L,N,P,KH,D], v_pool, kq [L,T,KH,D], vq, pages [T],
               offs [T], owner scalar) -> (k_pool', v_pool')
        int8: scales [L,N,P,KH] and per-row scale values [L,T,KH] ride
              along (inputs and outputs).
        """
        from jax.experimental.shard_map import shard_map

        def local_bf16(k_l, v_l, kq, vq, pages, offs, owner):
            mine = jax.lax.axis_index("dp") == owner
            pg = jnp.where(mine, pages, 0)
            k_l = k_l.at[:, pg, offs].set(kq.astype(k_l.dtype))
            v_l = v_l.at[:, pg, offs].set(vq.astype(v_l.dtype))
            return k_l, v_l

        def local_int8(k_l, v_l, k_s, v_s, kq, vq, ks, vs, pages, offs,
                       owner):
            mine = jax.lax.axis_index("dp") == owner
            pg = jnp.where(mine, pages, 0)
            k_l = k_l.at[:, pg, offs].set(kq)
            v_l = v_l.at[:, pg, offs].set(vq)
            k_s = k_s.at[:, pg, offs].set(ks)
            v_s = v_s.at[:, pg, offs].set(vs)
            return k_l, v_l, k_s, v_s

        pool = P(None, "dp", None, "tp", None)
        scale = P(None, "dp", None, "tp")
        rows = P(None, None, "tp", None)
        rows_s = P(None, None, "tp")
        if quantized:
            in_specs = (pool, pool, scale, scale, rows, rows, rows_s,
                        rows_s, P(None), P(None), P())
            out_specs = (pool, pool, scale, scale)
            fn = local_int8
        else:
            in_specs = (pool, pool, rows, rows, P(None), P(None), P())
            out_specs = (pool, pool)
            fn = local_bf16
        return shard_map(
            fn, mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )

    @property
    def tp(self) -> int:
        return self.mesh.shape["tp"]

    @property
    def dp(self) -> int:
        return self.mesh.shape["dp"]

    @property
    def sp(self) -> int:
        return self.mesh.shape["sp"]

    @property
    def ep(self) -> int:
        return self.mesh.shape.get("ep", 1)

    def validate(self, cfg: ModelConfig, num_slots: int) -> None:
        tp, dp, ep = self.tp, self.dp, self.ep
        assert cfg.num_kv_heads % tp == 0, (
            f"kv heads {cfg.num_kv_heads} not divisible by tp={tp}"
        )
        assert cfg.num_heads % tp == 0
        if cfg.moe:
            assert cfg.num_experts % ep == 0, (
                f"experts {cfg.num_experts} not divisible by ep={ep}"
            )
            assert cfg.expert_dim % tp == 0
        else:
            assert ep == 1, "ep>1 requires a MoE config"
            assert cfg.intermediate_size % tp == 0
        assert num_slots % dp == 0, f"slots {num_slots} not divisible by dp={dp}"


def single_device_plan() -> Optional[ShardingPlan]:
    """None when there is nothing to shard (1 device)."""
    if len(jax.devices()) == 1:
        return None
    return ShardingPlan(build_mesh())
