"""Version-compat shims shared across the package.

``tomllib``: stdlib from Python 3.11; on 3.10 the API-identical ``tomli``
backport (baked into the image) stands in. Import it from here so the
fallback policy lives in ONE place:

    from aios_tpu._compat import tomllib
"""

from __future__ import annotations

try:
    import tomllib
except ImportError:  # Python 3.10
    import tomli as tomllib  # type: ignore[no-redef]

__all__ = ["tomllib"]
