"""Request routing: provider selection, fallback chains, response cache.

Reference parity (api-gateway/src/router.rs):
  * selection: preferred provider first, else claude > openai > qwen3 >
    local by availability AND budget (router.rs:179-204);
  * per-provider fallback chains on error when allow_fallback
    (router.rs:55-93);
  * response cache keyed by prompt hash, TTL 1 h, ~1000-entry LRU
    (router.rs:206-248).
"""

from __future__ import annotations

import collections
import hashlib
import threading
import time
from typing import Dict, List, Optional, Tuple

from .budget import BudgetManager
from .providers import (
    ClaudeClient,
    InferResult,
    LocalRuntimeClient,
    ProviderError,
    openai_client,
    qwen3_client,
)

PRIORITY = ["claude", "openai", "qwen3", "local"]
FALLBACK_CHAINS: Dict[str, List[str]] = {
    "claude": ["openai", "qwen3", "local"],
    "openai": ["claude", "qwen3", "local"],
    "qwen3": ["local"],
    "local": [],
}

CACHE_TTL = 3600.0
CACHE_MAX = 1000


class ResponseCache:
    def __init__(self, ttl: float = CACHE_TTL, max_entries: int = CACHE_MAX):
        self.ttl = ttl
        self.max_entries = max_entries
        self._store: "collections.OrderedDict[str, Tuple[float, InferResult]]" = (
            collections.OrderedDict()
        )
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    @staticmethod
    def key(prompt: str, system: str, max_tokens: int, temperature: float) -> str:
        blob = f"{prompt}\x00{system}\x00{max_tokens}\x00{temperature:.3f}"
        return hashlib.sha256(blob.encode()).hexdigest()

    def get(self, key: str) -> Optional[InferResult]:
        with self._lock:
            entry = self._store.get(key)
            if entry is None:
                self.misses += 1
                return None
            ts, result = entry
            if time.monotonic() - ts > self.ttl:
                del self._store[key]
                self.misses += 1
                return None
            self._store.move_to_end(key)
            self.hits += 1
            return result

    def put(self, key: str, result: InferResult) -> None:
        with self._lock:
            self._store[key] = (time.monotonic(), result)
            self._store.move_to_end(key)
            while len(self._store) > self.max_entries:
                self._store.popitem(last=False)


class RequestRouter:
    def __init__(
        self,
        budget: Optional[BudgetManager] = None,
        runtime_address: Optional[str] = None,
    ):
        self.providers = {
            "claude": ClaudeClient(),
            "openai": openai_client(),
            "qwen3": qwen3_client(),
            "local": LocalRuntimeClient(runtime_address),
        }
        self.budget = budget or BudgetManager()
        self.cache = ResponseCache()
        self.last_errors: Dict[str, str] = {}

    def _usable(self, name: str) -> bool:
        provider = self.providers[name]
        return provider.available() and self.budget.can_afford(name)

    def _selection_order(self, preferred: str, allow_fallback: bool) -> List[str]:
        if preferred and preferred in self.providers:
            order = [preferred]
            if allow_fallback:
                order += [p for p in FALLBACK_CHAINS[preferred] if p not in order]
            return order
        # no/unknown preference: global priority by availability & budget
        order = [p for p in PRIORITY if self._usable(p)]
        return order or ["local"]

    def _candidates(self, preferred: str, allow_fallback: bool, errors: List[str]):
        """Yield (name, provider) for each usable candidate in policy order —
        the ONE selection policy shared by route() and route_stream()."""
        for name in self._selection_order(preferred, allow_fallback):
            if not self._usable(name):
                errors.append(f"{name}: unavailable or over budget")
                continue
            yield name, self.providers[name]

    def _record_and_cache(
        self, name, result: InferResult, agent, task_id, use_cache, cache_key
    ) -> None:
        self.budget.record(
            name,
            result.model,
            result.input_tokens,
            result.output_tokens,
            agent=agent,
            task_id=task_id,
        )
        if use_cache:
            self.cache.put(cache_key, result)

    @staticmethod
    def _honors_schema(provider, json_schema: str) -> bool:
        """Cache eligibility: a schema-keyed entry may only hold a response
        from a provider that actually HONORS the schema."""
        return not json_schema or getattr(
            provider, "supports_json_schema", False
        )

    def route(
        self,
        prompt: str,
        system: str = "",
        max_tokens: int = 1024,
        temperature: float = 0.7,
        preferred: str = "",
        allow_fallback: bool = True,
        agent: str = "",
        task_id: str = "",
        use_cache: bool = True,
        json_schema: str = "",
    ) -> InferResult:
        # a schema-constrained response is NOT interchangeable with the
        # unconstrained response for the same prompt — key the cache on it
        cache_key = self.cache.key(
            prompt, system + "\x00" + json_schema, max_tokens, temperature
        )
        if use_cache:
            hit = self.cache.get(cache_key)
            if hit is not None:
                return hit

        errors: List[str] = []
        for name, provider in self._candidates(preferred, allow_fallback, errors):
            try:
                result = provider.infer(
                    prompt, system, max_tokens, temperature,
                    json_schema=json_schema,
                )
            except ProviderError as exc:
                self.last_errors[name] = str(exc)
                errors.append(f"{name}: {exc}")
                if not allow_fallback:
                    break
                continue
            # a provider that IGNORES the schema returns unconstrained
            # text; caching it under the schema-keyed entry would serve
            # non-conforming responses to later schema requests
            honors = self._honors_schema(provider, json_schema)
            self._record_and_cache(
                name, result, agent, task_id, use_cache and honors, cache_key
            )
            return result
        raise ProviderError("all providers failed: " + "; ".join(errors))

    def route_stream(
        self,
        prompt: str,
        system: str = "",
        max_tokens: int = 1024,
        temperature: float = 0.7,
        preferred: str = "",
        allow_fallback: bool = True,
        agent: str = "",
        task_id: str = "",
        use_cache: bool = True,
        json_schema: str = "",
        register_call=None,
        client_alive=None,
    ):
        """Route with live streaming: yields (text_delta, provider_name).

        Providers exposing ``stream_infer`` (the local TPU runtime) pipe
        their token stream straight through — the first delta arrives while
        generation is still running. Cloud providers without a streaming
        client fall back to infer-then-rechunk (64-char pieces, matching
        the reference's StreamInfer behavior). Fallback to the next
        provider happens only before the first delta is emitted; after
        that, a mid-stream failure surfaces to the caller.

        ``register_call`` (optional) receives each in-flight downstream
        gRPC call so the gateway servicer can cancel it from its RPC-
        termination callback — the only abort path when this generator is
        parked in next() with no delta flowing (a disconnect then never
        raises GeneratorExit here). ``client_alive`` (optional callable)
        reports whether the consumer still exists: a provider failure with
        a dead consumer aborts routing instead of falling back (no cloud
        spend for nobody); it also distinguishes a deliberate
        disconnect-cancel from a genuine runtime CANCELLED failure, which
        DOES fall back.
        """
        # same composite key as route() so the two paths share hits
        cache_key = self.cache.key(
            prompt, system + "\x00" + json_schema, max_tokens, temperature
        )
        if use_cache:
            hit = self.cache.get(cache_key)
            if hit is not None:
                for i in range(0, len(hit.text), 64):
                    yield hit.text[i : i + 64], hit.provider
                return

        errors: List[str] = []
        for name, provider in self._candidates(preferred, allow_fallback, errors):
            if hasattr(provider, "stream_infer"):
                # the runtime's incremental detokenizer emits ~one delta per
                # generated token, so len(pieces) IS the completion token
                # count; the chunk wire format carries no usage fields
                # (runtime.proto InferChunk, reference parity). Recording
                # happens in the finally so a client that disconnects
                # mid-stream (GeneratorExit) still pays for what streamed;
                # only COMPLETE responses enter the cache.
                pieces: List[str] = []
                completed = False
                try:
                    try:
                        for delta in provider.stream_infer(
                            prompt, system, max_tokens, temperature,
                            json_schema=json_schema,
                            register_call=register_call,
                        ):
                            pieces.append(delta)
                            yield delta, name
                        completed = True
                        if not pieces:
                            # empty completion (immediate EOS): still hand
                            # the consumer the serving provider's name so
                            # the terminal done-chunk isn't unattributed
                            yield "", name
                    except ProviderError as exc:
                        self.last_errors[name] = str(exc)
                        if pieces:  # mid-stream failure: don't restart
                            raise
                        if client_alive is not None and not client_alive():
                            # OUR consumer is gone (the disconnect cancel
                            # tore the downstream call): falling back would
                            # spend another provider — possibly cloud
                            # budget — for a dead client
                            raise
                        errors.append(f"{name}: {exc}")
                        if not allow_fallback:
                            break
                        continue
                finally:
                    if pieces:
                        honors = self._honors_schema(
                            provider, json_schema
                        )
                        self._record_and_cache(
                            name,
                            InferResult(
                                text="".join(pieces),
                                input_tokens=0,
                                output_tokens=len(pieces),
                                model=f"{name}-stream",
                                provider=name,
                            ),
                            agent,
                            task_id,
                            use_cache and completed and honors,
                            cache_key,
                        )
                return
            try:
                result = provider.infer(
                    prompt, system, max_tokens, temperature,
                    json_schema=json_schema,
                )
            except ProviderError as exc:
                self.last_errors[name] = str(exc)
                errors.append(f"{name}: {exc}")
                if not allow_fallback:
                    break
                continue
            # record BEFORE yielding: the provider call is already paid for
            # even if the client disconnects during the rechunk relay
            honors = self._honors_schema(provider, json_schema)
            self._record_and_cache(
                name, result, agent, task_id, use_cache and honors, cache_key
            )
            if not result.text:
                yield "", name  # attribute the terminal chunk (see above)
            for i in range(0, len(result.text), 64):
                yield result.text[i : i + 64], name
            return
        raise ProviderError("all providers failed: " + "; ".join(errors))
