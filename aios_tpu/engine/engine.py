"""The TPU decode engine: slot KV cache, bucketed prefill, batched decode.

This is the component that replaces llama.cpp end-to-end (SURVEY.md
section 2.3, "TPU equivalence requirement"): weights live in HBM, prefill and
the decode loop are jitted graphs with static shapes, sampling happens on
device, and ALL decode state (KV caches, slot lengths, last tokens, per-slot
sampling params, RNG key) is device-resident and donated — a decode dispatch
moves no state across the host boundary except the sampled tokens coming out.

Shape discipline (the TPU contract):
  * decode is ONE graph for the lifetime of the engine: `step_n` runs K
    decode steps under `lax.scan` per dispatch ([S] -> [K, S] tokens), so
    host/relay round-trip latency amortizes over K tokens. Continuous
    batching inserts/retires requests by mutating slot state, never by
    changing shapes.
  * prefill is compiled per power-of-two length bucket, so an arbitrary
    prompt costs at most 2x its length and never recompiles after warmup.

A slot lifecycle: prefill(slot, prompt) writes K/V rows [0, len) and samples
the first token -> step_n() extends every active slot -> release(slot).
Inactive slots keep decoding garbage (their outputs are ignored); that is the
price of a fixed-shape graph and it is what keeps XLA fast.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import model, paged, sampling, spec
from .config import ModelConfig
from .. import faults
from ..analysis.locks import make_lock
from ..obs import instruments as obs
from ..obs import devprof, flightrec

log = logging.getLogger("aios.engine")

DEFAULT_BUCKETS = (16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 8192)

# Run-length buckets for the grammar jump-ahead graphs (jump_step): a
# forced run of K tokens dispatches through the smallest bucket >= K, so
# warmup compiles len(JUMP_BUCKETS) graphs and serving never compiles.
# Two buckets on purpose: padding a short run up to the bucket is nearly
# free (the verify dispatch is weight-bandwidth-bound), while every extra
# bucket is another graph in every constrained deployment's warmup gate.
# Bounded by spec.HISTORY_PAD - 2 (the post-dispatch history scatter must
# stay inside the pad margin, same bound as speculative draft_len).
JUMP_BUCKETS = (4, 16)
assert JUMP_BUCKETS[-1] <= spec.HISTORY_PAD - 2

# Device-side stop-id slots per batch slot in the mega decode loop
# (_mega_impl): each slot's first MEGA_STOP_SLOTS stop ids ride the
# dispatch as a fixed-shape [S, MEGA_STOP_SLOTS] operand (pad -1) so
# EOS/stop detection runs on device. BEST-EFFORT by design: the host
# emit loop stays authoritative for stream truncation (it checks the
# FULL stop set), so an overflowing stop set only costs an early-exit
# opportunity, never correctness.
MEGA_STOP_SLOTS = 4

# Width buckets for the standalone draft-KV bulk-ingest graphs: a freshly
# admitted (or failed-over) slot's draft cache trails the serving state by
# the whole prompt, and spec_step_draft catches it up in these power-of-
# two teacher-forced chunks before the fused rounds take over (whose
# per-round catch-up width is only draft_len+1 — the steady-state gap is
# 0 or 1). Capped at the shared prefill-chunk granularity; the draft tier
# is small, so each graph is a cheap compile.
DRAFT_INGEST_BUCKETS = (32, 64, 128, 256, 512)

# Live HostPageStores per model name: replica engines share the (model,)
# label on the aios_tpu_prefix_host_* gauges, so the scrape callbacks sum
# over this set instead of reporting whichever replica registered last.
_HOST_STORES_BY_MODEL: Dict[str, object] = {}

# Live engines per model name, for the same last-writer-wins reason: the
# aios_tpu_engine_jump_ahead_* and aios_tpu_spec_* gauges sum over every
# replica engine instead of reporting whichever registered last.
_ENGINES_BY_MODEL: Dict[str, object] = {}


def _cpu_device():
    from .checkpoint import cpu_device

    return cpu_device()


def _to_default_device(a):
    """jnp.asarray that also MOVES committed host arrays to the default
    backend's device. Both jnp.asarray AND bare jax.device_put(x) are
    identities on an array already committed to any device (jax 0.9
    semantics), so the target device must be explicit. An operator-pinned
    jax_default_device wins over devices()[0]."""
    target = getattr(jax.config, "jax_default_device", None)
    if isinstance(target, str):
        # the config validator accepts platform-name strings ('cpu'/'tpu');
        # device_put does not — resolve to that backend's first device
        target = jax.devices(target)[0]
    elif target is None:
        target = jax.devices()[0]
    return jax.device_put(jnp.asarray(a), target)


def _is_prequantized(params) -> bool:
    """True when the params tree already holds serving-quantized leaves
    ({"q","s"} int8 or {"q4","s4"} int4 dicts from quantize_params)."""
    layers = params.get("layers", {}) if isinstance(params, dict) else {}
    return any(
        isinstance(v, dict) and ("q" in v or "q4" in v)
        for v in layers.values()
    )


def _prequantized_mode(params) -> str:
    """The dominant stored serving mode of a prequantized tree ("int4" when
    any packed-nibble leaf exists — mixed trees are int4-with-int8-fallback
    by construction)."""
    for v in params.get("layers", {}).values():
        if isinstance(v, dict) and "q4" in v:
            return "int4"
    return "int8"


def _resolve_stored_mode(params, requested, *, quiet_default: bool = False):
    """The STORED serving mode of a prequantized tree wins over the
    engine-level request; flag a mismatch rather than silently reporting
    the wrong precision. ``quiet_default`` logs the no-request case at
    info (benches/prepared checkpoints pass quantized trees without a
    mode on purpose)."""
    stored = _prequantized_mode(params)
    if requested and requested != stored:
        log.warning(
            "checkpoint stores %s serving weights; requested quantize=%s "
            "is ignored (re-run prepare_model to change the stored mode)",
            stored, requested,
        )
    elif not requested and quiet_default:
        log.info(
            "serving prequantized %s weights (bf16 serving is unavailable "
            "for prepared-quantized trees)", stored,
        )
    return stored


def _is_fused_prequantized(params) -> bool:
    """True for the FUSED single-chip serving layout (w_qkv/w_gateup
    concats from quantize_params fuse=True) — it has no TP sharding rule
    (a fused concat would interleave q/k/v columns across shards)."""
    layers = params.get("layers", {}) if isinstance(params, dict) else {}
    return any(k in layers for k in ("w_qkv", "w_gateup", "we_gateup"))


# keys whose CONTRACTION dim (K) shards under tp (row-parallel); every
# other quantized projection — and the lm_head's vocab — shards its
# output dim N (column-parallel). Mirrors quantize_params's tp rule.
_ROW_PARALLEL_KEYS = ("wo", "w_down")


def _validate_prequantized_tp(params, tp: int) -> None:
    """A prepared (unfused) quantized tree must have been quantized for
    THIS tp degree: int4 scale groups are picked from shard-local dims, so
    a mismatched plan would hand the per-device kernel groups it cannot
    serve — and int8 {'q','s'} leaves need their SHARDED dim divisible by
    tp (N for column-parallel projections, K for the row-parallel
    _ROW_PARALLEL_KEYS) or the mismatch only surfaces as an opaque GSPMD
    shape error inside the first dispatch. Raise with the re-prepare
    recipe instead."""
    if tp <= 1:
        return
    from ..ops.int4_matmul import kernel_supported

    leaves = dict(params.get("layers", {}))
    if isinstance(params.get("lm_head"), dict):
        leaves["lm_head"] = params["lm_head"]
    bad = []
    mode = "int8"
    for key, v in leaves.items():
        if not isinstance(v, dict):
            continue
        if "q4" in v:
            mode = "int4"
            K, N = v["q4"].shape[-2] * 2, v["q4"].shape[-1]
            groups = v["s4"].shape[-3]
            group = K // groups
            if key in _ROW_PARALLEL_KEYS:
                ok = (K % tp == 0 and groups % tp == 0
                      and kernel_supported(K // tp, N, group))
            else:
                ok = N % tp == 0 and kernel_supported(K, N // tp, group)
        elif "q" in v:
            # int8: the contraction dim K shards for row-parallel
            # projections, the output dim N (and its per-channel scales)
            # everywhere else — quantize_params's tp rule
            K, N = v["q"].shape[-2], v["q"].shape[-1]
            ok = (K % tp == 0) if key in _ROW_PARALLEL_KEYS else (N % tp == 0)
        else:
            continue
        if not ok:
            bad.append(key)
    if bad:
        raise ValueError(
            f"prepared {mode} checkpoint is not servable under tp={tp} "
            f"(leaves {', '.join(bad)}): re-run scripts/prepare_model.py "
            f"--quantize {mode} --tp {tp} so shard-local eligibility and "
            "scale groups are baked for this plan"
        )


def _on_accelerator(params) -> bool:
    """True if ANY param leaf already lives on a non-CPU jax device (a
    mixed tree must not round-trip device weights through the host)."""
    for leaf in jax.tree.leaves(params):
        if isinstance(leaf, jax.Array):
            try:
                if leaf.devices().pop().platform != "cpu":
                    return True
            # aios: waive(silent-except): placement probe over possibly-deleted arrays — an unreadable leaf just doesn't vote
            except Exception:  # noqa: BLE001
                continue
    return False


def _env_flag(name: str) -> Optional[bool]:
    """Tri-state env boolean: None when unset/blank (caller falls back to
    its config default), else the lenient truthiness the other AIOS_TPU_*
    knobs use."""
    raw = os.environ.get(name, "").strip().lower()
    if not raw:
        return None
    return raw in ("1", "true", "on", "yes")


def _env_int(name: str) -> Optional[int]:
    """Lenient env integer: None when unset/blank/malformed (a bad knob
    logs and falls back instead of failing a model load — the
    serving-config convention)."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return None
    try:
        v = int(float(raw))
        if v < 0:
            raise ValueError("must be >= 0")
        return v
    except ValueError:
        log.warning("%s=%r ignored (expected a non-negative integer)",
                    name, raw)
        return None


# Device-resident decode state, threaded through the jitted cores as one
# donated pytree: {k, v, lengths, last_tokens, temps, top_ps, key}
DecodeState = Dict[str, jnp.ndarray]


class PendingDecode:
    """Handle for a decode dispatch running on the engine's dispatch
    worker (engine.step_async).

    The worker thread performs the whole dispatch — lock, graph call,
    device->host token readback — so the CALLER's thread overlaps its own
    host work (emit/detokenize/retire) with the device execution; on the
    CPU backend, where XLA executes "parallel" computations inline in the
    dispatching call, the worker is the ONLY way to get that overlap (the
    GIL is released inside the XLA call).

    ``wait()`` blocks until the tokens materialize and returns the host
    ``[n_steps, S]`` array. ``lengths`` (valid after ``wait()``)
    snapshots the host slot lengths AFTER this dispatch's advance — the
    batcher's out-of-cache retirement check must read the lengths as of
    THIS dispatch, not whatever later dispatches have since added
    (pipeline-on output would otherwise retire early and diverge from
    pipeline-off). ``wait_started()`` blocks until the dispatch holds the
    engine lock: ordering fence for callers about to issue further
    engine calls that must land AFTER this dispatch. ``device_s``
    (valid after ``wait()``) carries the dispatch's sampled device-time
    measurement when devprof took one (obs/devprof.py), None otherwise —
    the batcher joins it onto the flight-recorder event it recorded at
    submit time."""

    __slots__ = ("_fut", "_started", "n_steps", "tokens", "lengths",
                 "ticks", "device_s")

    def __init__(self, fut, n_steps: int, started: threading.Event) -> None:
        self._fut = fut
        self._started = started
        self.n_steps = int(n_steps)
        self.tokens: Optional[np.ndarray] = None
        self.lengths: Optional[np.ndarray] = None
        # REAL ticks the dispatch ran: n_steps for the scan graphs, the
        # device loop's k <= n_steps for a megagraph dispatch that
        # early-exited (mega_step_async); set at wait()
        self.ticks = int(n_steps)
        self.device_s: Optional[float] = None

    def wait_started(self) -> None:
        if self.tokens is not None or self._fut.done():
            return  # finished implies started; skip the event syscall
        self._started.wait()

    def wait(self) -> np.ndarray:
        if self.tokens is None:
            res = self._fut.result()
            if len(res) == 4:  # mega: (tokens, lengths, k, device_s)
                self.tokens, self.lengths, self.ticks, self.device_s = res
            else:
                self.tokens, self.lengths, self.device_s = res
        return self.tokens


class TPUEngine:
    """Single-model decode engine over a fixed set of batch slots."""

    def __init__(
        self,
        cfg: ModelConfig,
        params,
        *,
        num_slots: int = 8,
        max_context: Optional[int] = None,
        cache_dtype=jnp.bfloat16,
        seed: int = 0,
        shardings=None,  # optional ShardingPlan (aios_tpu.parallel.sharding)
        quantize=False,  # serving weights: False/True/"int8"/"int4"
        sharded_attention: Optional[bool] = None,  # shard_map ragged decode
        paged_pool_rows: Optional[int] = None,  # physical KV rows -> paged
        page_size: int = 128,
        prefix_cache: Optional[bool] = None,  # None -> on when paged
        prefix_host_bytes: Optional[int] = None,  # host spill tier budget
        host_restore_min_pages: Optional[int] = None,  # restore floor
        seq_sharded_cache: bool = False,  # shard KV context axis over sp
        track_history: bool = True,  # device-side token history (spec.py)
        unified_step: Optional[bool] = None,  # one dynamic-n decode graph
        prefix_radix: Optional[bool] = None,  # radix-tree prefix index
        draft: Optional["spec.DraftModel"] = None,  # draft-model proposer
        kv_compress_after: Optional[int] = None,  # window+sink threshold rows
        kv_sink_pages: Optional[int] = None,  # live leading (sink) pages
        kv_window_pages: Optional[int] = None,  # live trailing window pages
        seq_prefill_min: Optional[int] = None,  # sp-sharded prefill floor rows
        mega_ticks: Optional[int] = None,  # multi-tick decode megagraph cap
    ) -> None:
        self.cfg = cfg
        self.num_slots = num_slots
        # Per-step history scatter exists ONLY for the n-gram speculative
        # proposer (spec.py reads history[s, :length+1]); deployments with
        # speculative decode off skip the write and its serial dependency
        # in the decode scan (ModelManager passes track_history=spec).
        self.track_history = bool(track_history)
        self.max_context = int(max_context or cfg.max_context)
        self.buckets = tuple(
            b for b in DEFAULT_BUCKETS if b <= self.max_context
        ) or (self.max_context,)
        self._lock = make_lock("engine")
        self.plan = shardings
        # normalize the quantize knob to a mode: True -> int8 (the measured
        # single-chip default), "int4" -> packed-nibble group-wise int4
        # (ops/int4_matmul.py; half the int8 weight bytes). Under a
        # sharding plan int4 runs the kernel per device under shard_map
        # (ShardingPlan.int4_matmul_impl) — column-parallel shards with no
        # collective, row-parallel with the same tp psum GSPMD inserts for
        # the int8 dots — so BASELINE config 4 (Mistral TP) serves the
        # best weight format too.
        if quantize is True:
            quantize = "int8"
        elif not quantize:
            quantize = None
        elif quantize not in ("int8", "int4"):
            raise ValueError(f"unknown quantize mode {quantize!r}")
        self.quant_mode = quantize
        self.quantized = quantize is not None
        # int8 KV cache: half the cache footprint/traffic; scales ride along
        # in the decode state and rows quantize on write inside the graph
        self.quant_cache = cache_dtype == jnp.int8
        # Pallas kernels are per-device programs; under a sharding plan the
        # global-array paths must stay pure XLA (GSPMD partitions those) —
        # EXCEPT decode attention, which is head/slot-local and runs the
        # ragged kernel per device under shard_map (see _attn_impl below).
        self._kernels: Optional[bool] = False if shardings is not None else None
        # MoE decode: the gathered path streams only the routed experts'
        # weights (moe.moe_ffn_gather) when every slot's picks together
        # touch fewer experts than exist. Measured on v5e (2.3B geometry,
        # 32 experts top-4, single request): gather 126.5 tok/s vs dense
        # 216.4 — the expert-weight gather costs more than the skipped
        # streaming saves at small expert sizes, so DENSE is the default
        # and AIOS_TPU_MOE_GATHER=1 opts in (bigger experts / higher
        # X/(slots*k) ratios may still favor it). Single-device only:
        # under EP the expert axis is sharded and the dense path's psum is
        # the right collective. Decode/verify dispatches only — prefill
        # token counts saturate the experts.
        self._moe_impl: Optional[str] = None
        if (
            cfg.moe
            and shardings is None
            and num_slots * cfg.num_experts_per_tok < cfg.num_experts
            and os.environ.get("AIOS_TPU_MOE_GATHER", "").lower()
            in ("1", "true", "on")
        ):
            self._moe_impl = "gather"

        if shardings is not None:
            if _is_prequantized(params):
                if _is_fused_prequantized(params):
                    # the fused concat layout has no TP sharding rule — a
                    # fused w_qkv would interleave q/k/v columns across
                    # shards. Unfused prepared artifacts load fine below.
                    # The recipe names the checkpoint's STORED mode so the
                    # re-prepare doesn't silently change precision.
                    raise ValueError(
                        "this prepared checkpoint stores the FUSED "
                        "single-chip layout; sharded plans need an unfused "
                        "artifact (scripts/prepare_model.py --quantize "
                        f"{_prequantized_mode(params)} --tp {shardings.tp}) "
                        "or the dense source with quantize at load time"
                    )
                # unfused prepared artifact (prepare_model --tp N): leaves
                # already match quantize_params(fuse=False, tp=...) — shard
                # straight to the mesh, no load-time quantization pass (the
                # BASELINE config-4 boot path: no dense-weight transient,
                # no per-boot quantization)
                self.quant_mode = quantize = _resolve_stored_mode(
                    params, quantize
                )
                self.quantized = True
                _validate_prequantized_tp(params, shardings.tp)
                self.params = shardings.put_params(params)
            elif quantize:
                # unfused layout: each projection's output dim shards on tp,
                # scales follow (sharding.py quantized-leaf rules); the
                # int8 x bf16 dot_generals partition like their dense
                # counterparts, with GSPMD inserting the same psums. int4
                # leaves quantize with SHARD-local eligibility/groups
                # (tp=...) — dims whose shards the kernel can't serve fall
                # back to int8 leaves.
                self.params = shardings.put_params(
                    model.quantize_params(
                        params, fuse=False, mode=quantize,
                        tp=shardings.tp,
                    )
                )
            else:
                self.params = shardings.put_params(params)
        else:
            if _is_prequantized(params):
                # prepared serving checkpoint (scripts/prepare_model.py
                # --quantize): the leaves are already {"q","s"}/{"q4","s4"}
                # — restore straight to device, nothing to quantize.
                self.quant_mode = quantize = _resolve_stored_mode(
                    params, quantize, quiet_default=True
                )
                self.quantized = True
                self.params = jax.tree.map(_to_default_device, params)
            elif quantize and not _on_accelerator(params):
                # Host-resident params (GGUF load, checkpoints staged on
                # CPU): quantize on the host CPU backend FIRST, then ship
                # only the quantized leaves. Transferring dense bf16 and
                # quantizing on-device would stage dense + quantized HBM
                # at once — an OOM for the 7B tier on a 16 GB chip.
                cpu = _cpu_device()
                if cpu is not None:
                    with jax.default_device(cpu):
                        qp = model.quantize_params(
                            jax.tree.map(jnp.asarray, params), mode=quantize
                        )
                    # explicit device_put: jnp.asarray on a CPU-committed
                    # jax.Array is an identity and would leave the weights
                    # host-resident (PCIe-speed decode)
                    self.params = jax.tree.map(_to_default_device, qp)
                else:
                    self.params = model.quantize_params(
                        jax.tree.map(jnp.asarray, params), mode=quantize
                    )
            else:
                # _to_default_device, not jnp.asarray: checkpoint restores
                # may hand CPU-COMMITTED jax.Arrays, which asarray would
                # leave on the host
                self.params = jax.tree.map(_to_default_device, params)
                if quantize:
                    self.params = model.quantize_params(
                        self.params, mode=quantize
                    )

        # Context-sharded KV: the cache's C axis splits over the mesh's sp
        # axis, so one slot's KV can exceed a single chip's HBM — XLA
        # partitions the decode attention over the sharded contraction
        # (partial softmax stats + psum over sp; sharding.CACHE_SPEC_SEQ).
        self.seq_sharded = bool(seq_sharded_cache)
        if self.seq_sharded:
            if shardings is None:
                raise ValueError("seq_sharded_cache needs a sharding plan")
            if paged_pool_rows is not None:
                raise ValueError(
                    "seq_sharded_cache and the paged pool are exclusive"
                )
            if self.max_context % shardings.sp:
                raise ValueError(
                    f"max_context {self.max_context} must divide by "
                    f"sp={shardings.sp} for a context-sharded cache"
                )

        # Ragged decode attention under shard_map: auto on TPU meshes with a
        # bf16 cache long enough for the kernel to win (same crossover as
        # the single-chip ladder); force with sharded_attention=True to
        # exercise the path on CPU virtual meshes (jnp reference body).
        self._attn_impl = None
        if sharded_attention and (shardings is None or self.quant_cache):
            raise ValueError(
                "sharded_attention=True needs a sharding plan and a bf16 KV "
                "cache (the ragged kernel reads bf16 caches only)"
            )
        if sharded_attention and self.seq_sharded:
            raise ValueError(
                "sharded_attention=True is incompatible with "
                "seq_sharded_cache: the shard_map ragged kernel assumes "
                "each device holds whole slots' context"
            )
        on_tpu = False
        try:
            on_tpu = jax.default_backend() == "tpu"
        # aios: waive(silent-except): backend probe at construction — no backend registered means "not TPU", the default already set
        except Exception:
            pass
        if shardings is not None and not self.quant_cache and not self.seq_sharded:
            enable = (
                sharded_attention
                if sharded_attention is not None
                else on_tpu and self.max_context >= 2048
            )
            if enable:
                self._attn_impl = shardings.ragged_attention(
                    cfg.sliding_window, use_kernel=on_tpu
                )

        # int4 matmuls under a plan: matmul()'s default ladder would run
        # the per-device Pallas kernel on GSPMD-sharded GLOBAL arrays, so
        # every sharded consumer of q4 leaves must get an explicit impl —
        #   * decode steps: shard_map per-device kernel (bandwidth-bound,
        #     the path the int4 format exists for)
        #   * prefill / chunked prefill / speculative verify: the jnp
        #     reference body on global arrays, which GSPMD partitions like
        #     any dot (compute-bound passes; the inline dequant is noise
        #     there, and their [1, T, E] / [B, T, E] shapes don't fit the
        #     decode-shaped shard_map specs)
        self._qmm_impl = None
        self._qmm_gspmd = None
        if shardings is not None and quantize == "int4":
            from ..ops.int4_matmul import int4_matmul_reference

            self._qmm_impl = shardings.int4_matmul_impl(use_kernel=on_tpu)
            self._qmm_gspmd = (
                lambda x, leaf, kind: int4_matmul_reference(
                    x, leaf["q4"], leaf["s4"]
                )
            )

        # Paged KV cache: HBM is reserved per page IN USE, not per
        # num_slots x max_context — many long-context slots oversubscribe a
        # fixed pool (SURVEY.md section 7.2). Logical layout and outputs are
        # identical to the dense cache; the page indirection lives in
        # engine/paged.py (tables) + ops/paged_attention.py (reads).
        self.paged = paged_pool_rows is not None
        self.allocator: Optional[paged.PageAllocator] = None
        self.prefix_index: Optional[paged.PrefixIndex] = None
        self._prefix_chunk: Optional[int] = None
        self._pool_impl = None
        self._paged_scatter = None
        self.pool_replicas = 1
        if self.paged:
            # sp in the mesh: the pool (like any non-seq-sharded cache)
            # REPLICATES over the sp axis — its shard_map specs name only
            # dp/tp, so each sp slice runs the identical pool program. A
            # context that must SHARD over sp (exceeding per-chip HBM)
            # uses seq_sharded_cache instead — pages hold contiguous rows
            # of one slot and cannot split across sp shards; the model
            # manager's HBM-budget check picks between the two per model.
            if page_size < 1 or page_size & (page_size - 1):
                # chunked admission relies on power-of-two chunk/page sizes
                # never straddling (model.prefill_chunk_paged)
                raise ValueError(f"page_size {page_size} must be a power of 2")
            if self.max_context % page_size:
                raise ValueError(
                    f"max_context {self.max_context} must be a multiple of "
                    f"page_size {page_size}"
                )
            R = shardings.dp if shardings is not None else 1
            self.pool_replicas = R
            max_blocks = self.max_context // page_size
            # per replica: one sacrificial page + its share of the pool
            local_pages = 1 + max(
                1, -(-int(paged_pool_rows) // (page_size * R))
            )
            num_pages = R * local_pages
            self.allocator = paged.PageAllocator(
                num_pages, page_size, num_slots, max_blocks, replicas=R
            )
            shape = (
                cfg.num_layers, num_pages, page_size,
                cfg.num_kv_heads, cfg.head_dim,
            )
            k, v = jnp.zeros(shape, cache_dtype), jnp.zeros(shape, cache_dtype)
            if R > 1:
                # dp-replicated pool: page ops must run per device under
                # shard_map (table ids are replica-local; a GSPMD gather
                # could not prove locality and would all-gather the pool).
                # Chunked admission and the prefix index stay off — both
                # read the pool during per-slot admission, which the
                # whole-prompt scatter path avoids.
                self._pool_impl = shardings.paged_pool_impl(
                    cfg.sliding_window, use_kernel=on_tpu,
                    quantized=self.quant_cache,
                )
                self._paged_scatter = shardings.paged_prefill_scatter(
                    quantized=self.quant_cache
                )
                self.prefill_chunk_default = 0  # instance override
                if prefix_cache:
                    log.info(
                        "prefix cache disabled: pages are replica-local "
                        "under a dp-partitioned pool"
                    )
                prefix_cache = False
            # Prefix caching rides on the page pool: prompts whose leading
            # full blocks hash-match an earlier prompt map those pages
            # instead of recomputing them (paged.PrefixIndex). The tail
            # (always >= 1 token) admits through the chunked path, which
            # attends over the mapped prefix for free. Matching needs a
            # chunk size the bucket grid can honour.
            self._prefix_chunk = max(
                (b for b in self.buckets
                 if b <= self.prefill_chunk_default
                 and self.max_context % b == 0),
                default=None,
            )
            if prefix_cache is None:
                prefix_cache = True
            if prefix_cache and self._prefix_chunk is not None:
                # radix tree by default (cross-request sharing by
                # construction, leaf-LRU eviction, partial-node overlap
                # credit for the router); AIOS_TPU_PREFIX_RADIX=0 /
                # ModelConfig.prefix_radix=False is the escape hatch back
                # to the flat hash-chain map
                if prefix_radix is None:
                    prefix_radix = _env_flag("AIOS_TPU_PREFIX_RADIX")
                if prefix_radix is None:
                    prefix_radix = bool(getattr(cfg, "prefix_radix", True))
                index_cls = (
                    paged.RadixPrefixIndex if prefix_radix
                    else paged.PrefixIndex
                )
                self.prefix_index = index_cls(
                    self.allocator, max_pages=num_pages
                )
        else:
            prefix_host_bytes = 0
            k, v = model.init_kv_cache(
                cfg, num_slots, self.max_context, cache_dtype
            )
        # speculative verify does global pool scatters; under a
        # dp-partitioned pool those need a shard_map twin that does not
        # exist yet — refuse rather than corrupt replica-local pages
        self.spec_supported = not (self.paged and self.pool_replicas > 1)

        # -- Long-context tier (docs/ENGINE_PERF.md "Long-context tier") --
        # (1) Window+sink KV compression: past kv_compress_after rows a
        # slot's paged KV prunes to kv_sink_pages leading pages plus a
        # kv_window_pages trailing window (SnapStream/StreamingLLM-style,
        # PAPERS.md) — freed pages return to the pool (or survive under
        # their prefix-index references and spill through the PR 4 host
        # tier), and every attention graph masks the pruned middle via a
        # per-slot window-start operand that rides beside the page
        # tables. win_start = 0 keeps the mask a no-op, so below the
        # threshold streams are token-exact.
        def knob(explicit, env, default):
            # explicit constructor arg > env > ModelConfig default — the
            # unified_step/prefix_radix resolution convention
            if explicit is not None:
                return int(explicit)
            v = _env_int(env)
            return int(default) if v is None else v

        self.kv_compress_after = knob(
            kv_compress_after, "AIOS_TPU_KV_COMPRESS_AFTER",
            getattr(cfg, "kv_compress_after", 0),
        )
        self.kv_sink_pages = max(knob(
            kv_sink_pages, "AIOS_TPU_KV_SINK_PAGES",
            getattr(cfg, "kv_sink_pages", 1),
        ), 1)
        self.kv_window_pages = max(knob(
            kv_window_pages, "AIOS_TPU_KV_WINDOW_PAGES",
            getattr(cfg, "kv_window_pages", 8),
        ), 1)
        self.kv_compress_armed = False
        self._sink_rows = 0
        if self.kv_compress_after > 0:
            if not self.paged or self.pool_replicas > 1:
                log.warning(
                    "%s: kv_compress_after needs a paged, unreplicated "
                    "KV pool; compression disabled", cfg.name,
                )
            elif cfg.sliding_window is not None:
                log.warning(
                    "%s: kv_compress_after is redundant under a model "
                    "sliding window (residency is already bounded); "
                    "compression disabled", cfg.name,
                )
            else:
                P = self.allocator.page_size
                # the pruned mask needs sink + window to fit under the
                # threshold, or an armed slot could prune rows it is
                # still token-exactly below the threshold for
                floor = (self.kv_sink_pages + self.kv_window_pages) * P
                if self.kv_compress_after < floor:
                    log.info(
                        "%s: kv_compress_after %d raised to sink+window "
                        "floor %d", cfg.name, self.kv_compress_after,
                        floor,
                    )
                    self.kv_compress_after = floor
                self.kv_compress_armed = True
                self._sink_rows = self.kv_sink_pages * P
        # per-slot live-window start in ROWS (0 = uncompressed); rides
        # beside the page tables as a dispatch operand, never in the
        # donated state
        self._win_starts = np.zeros(num_slots, dtype=np.int32)
        self.kv_compress_slots = 0  # slots that crossed the threshold
        self.kv_pages_pruned = 0  # pages released by pruning

        # (2) Sequence-sharded prefill: prompts >= seq_prefill_min rows
        # prefill in ONE dispatch with the sequence sharded over the
        # mesh's sp axis (parallel/ring_attention.py make_ring_attn_fn /
        # ulysses.py make_ulysses_attn_fn) instead of serially through
        # chunked admission; the resulting KV scatters back into the
        # normal paged layout so decode, prefix-cache insertion,
        # spill/restore and failover see nothing new.
        self.seq_prefill_min = knob(
            seq_prefill_min, "AIOS_TPU_SEQ_PREFILL_MIN",
            getattr(cfg, "seq_prefill_min", 0),
        )
        # Device-resident multi-tick decode megagraph (_mega_impl): up to
        # mega_ticks decode ticks per dispatch in one lax.while_loop with
        # sampling, stop detection and budget/cap checks on device, early
        # exit the moment no slot needs another tick. 0 = off (default).
        # The loop's key fanout is split(key, K+1) — identical to the
        # per-size scan graph of the same K, so a full-window mega
        # dispatch is key-for-key the _step_impl(K) dispatch.
        self.mega_ticks = max(knob(
            mega_ticks, "AIOS_TPU_MEGA_TICKS",
            getattr(cfg, "mega_ticks", 0),
        ), 0)
        self._seq_attn = None
        self._seq_prefill_fns: Dict[int, object] = {}
        self.prefill_seq_sharded = 0
        if self.seq_prefill_min > 0:
            sp = shardings.sp if shardings is not None else 1
            if not self.paged or self.pool_replicas > 1 or sp <= 1:
                log.warning(
                    "%s: seq_prefill_min needs a paged, unreplicated "
                    "pool and a sharding plan with sp > 1; "
                    "sequence-sharded prefill disabled", cfg.name,
                )
                self.seq_prefill_min = 0
            else:
                impl = os.environ.get(
                    "AIOS_TPU_SEQ_PREFILL_IMPL", "ring"
                ).strip().lower() or "ring"
                if impl == "ulysses" and (
                    cfg.num_heads % sp or cfg.num_kv_heads % sp
                ):
                    log.warning(
                        "%s: ulysses seq prefill needs heads (%d/%d) "
                        "divisible by sp=%d; using ring", cfg.name,
                        cfg.num_heads, cfg.num_kv_heads, sp,
                    )
                    impl = "ring"
                if impl == "ulysses":
                    from ..parallel.ulysses import make_ulysses_attn_fn

                    self._seq_attn = make_ulysses_attn_fn(
                        shardings.mesh, "sp", window=cfg.sliding_window
                    )
                else:
                    from ..parallel.ring_attention import make_ring_attn_fn

                    self._seq_attn = make_ring_attn_fn(
                        shardings.mesh, "sp", window=cfg.sliding_window
                    )
                # routed buckets are powers of two >= sp (sp is a
                # power-of-two mesh axis), so the shard split is exact
                self.seq_prefill_min = max(self.seq_prefill_min, sp)
        if shardings is not None:
            k = shardings.put_cache(k, seq_shard=self.seq_sharded)
            v = shardings.put_cache(v, seq_shard=self.seq_sharded)
        self.state: DecodeState = {
            "k": k,
            "v": v,
            "lengths": jnp.zeros((num_slots,), jnp.int32),
            "last_tokens": jnp.zeros((num_slots,), jnp.int32),
            "temps": jnp.zeros((num_slots,), jnp.float32),
            "top_ps": jnp.ones((num_slots,), jnp.float32),
            # device-side mirror of the host `active` array: inactive slots
            # cost no cache bandwidth in decode and write only to the
            # sacrificial last row (model.decode_step)
            "active": jnp.zeros((num_slots,), jnp.bool_),
            # per-slot token history (prompt + generated) for device-side
            # n-gram draft proposal (spec.py); history[s, :lengths[s]+1]
            # mirrors cache rows + the pending last token
            "history": spec.init_history(num_slots, self.max_context),
            "key": jax.random.PRNGKey(seed),
        }
        if self.quant_cache:
            if self.paged:
                # per-(page, row, kv-head) scales alongside the int8 pool
                s_shape = (
                    cfg.num_layers, k.shape[1], page_size, cfg.num_kv_heads,
                )
                k_s = jnp.ones(s_shape, jnp.float32)
                v_s = jnp.ones(s_shape, jnp.float32)
                if shardings is not None:
                    # pool scales [L, N, P, KH]: same spec as dense scales
                    # ([L, S, C, KH]) — axis 1 rides the size-1 dp axis,
                    # kv heads shard over tp
                    k_s = shardings.put_cache_scales(k_s)
                    v_s = shardings.put_cache_scales(v_s)
            else:
                k_s, v_s = model.init_kv_scales(
                    cfg, num_slots, self.max_context
                )
                if shardings is not None:
                    k_s = shardings.put_cache_scales(
                        k_s, seq_shard=self.seq_sharded
                    )
                    v_s = shardings.put_cache_scales(
                        v_s, seq_shard=self.seq_sharded
                    )
            self.state["k_s"] = k_s
            self.state["v_s"] = v_s

        # Draft-model speculation (spec.DraftModel): the small tier
        # proposes, the serving model verifies — single-device only (the
        # draft cache and its graphs have no shard_map twins), on top of
        # the same verify machinery/track-history requirements as n-gram
        # speculation. A config that can't carry it FALLS BACK to n-gram
        # (the batcher's proposer ladder) rather than failing the load;
        # a vocab mismatch is a hard error — draft tokens feed the
        # serving verify directly, so it could never produce sense.
        self.draft: Optional[spec.DraftModel] = None
        self.draft_state = None
        self._draft_host_lengths = np.zeros(num_slots, dtype=np.int64)
        # host mirror of "slot decodes greedily" (set at admission):
        # only greedy slots ever propose, so the bulk-ingest gap math
        # skips sampling slots instead of building draft KV their ok
        # gate guarantees is never read
        self._host_greedy = np.zeros(num_slots, dtype=bool)
        self._draft_fns: Dict[object, object] = {}
        if draft is not None:
            if draft.cfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"draft model vocab ({draft.cfg.vocab_size}) must "
                    f"match the serving model's ({cfg.vocab_size}) — they "
                    "must share one tokenizer"
                )
            if shardings is not None:
                log.warning(
                    "%s: draft-model speculation is single-device only; "
                    "falling back to the n-gram proposer under a sharding "
                    "plan", cfg.name,
                )
            elif not self.spec_supported:
                log.warning(
                    "%s: draft-model speculation unsupported on a "
                    "dp-replicated page pool; falling back to the n-gram "
                    "proposer", cfg.name,
                )
            elif not self.track_history:
                log.warning(
                    "%s: draft-model speculation needs the token history "
                    "(track_history=True); draft model ignored", cfg.name,
                )
            else:
                self.draft = draft
                # draft cache rows mirror history columns 1:1, so it is
                # sized to the SERVING context; bf16 stands in when the
                # serving cache is int8 (the draft path has no scales)
                self.draft_state = draft.init_state(
                    num_slots, self.max_context,
                    cache_dtype=(
                        cache_dtype if cache_dtype != jnp.int8
                        else jnp.bfloat16
                    ),
                )

        # host-side mirror for the scheduler
        self.active = np.zeros(num_slots, dtype=bool)
        self._host_lengths = np.zeros(num_slots, dtype=np.int64)

        self._step_fns: Dict[int, object] = {}
        self._prefill_fns: Dict[int, object] = {}
        self._chunk_fns: Dict[Tuple[int, bool], object] = {}
        self._spec_fns: Dict[Tuple[int, int, int], object] = {}
        self._restore_fns: Dict[int, object] = {}
        self._jump_fns: Dict[int, object] = {}  # run-length-bucketed
        self._mega_fns: Dict[int, object] = {}  # pow2 K-bucketed megagraphs
        # Unified decode graph: ONE compiled fori_loop over a static
        # max-steps bound with the actual step count as a DYNAMIC operand,
        # so every chunk size the batcher dispatches shares a single XLA
        # graph instead of compiling per size (warmup compiles 1 graph,
        # not len(step_sizes)). Greedy output is identical to the per-size
        # scan graphs; sampling draws from a different (fixed-fanout) key
        # split, so the knob is opt-in (AIOS_TPU_UNIFIED_STEP /
        # ModelConfig.unified_step) rather than the default.
        if unified_step is None:
            unified_step = _env_flag("AIOS_TPU_UNIFIED_STEP")
        if unified_step is None:
            unified_step = bool(getattr(cfg, "unified_step", False))
        self.unified_step = bool(unified_step)
        self._unified_max = 0
        # single-thread dispatch worker behind step_async (built lazily:
        # only pipelined batchers use it); FIFO order is the dispatch
        # ordering contract
        self._dispatch_pool = None
        self.decode_steps = 0
        self.prefix_rows_reused = 0
        self.prefix_rows_restored = 0

        # Host-RAM spill tier behind the prefix cache: HBM evictions copy
        # their page KV device->host (paged.HostPageStore) instead of
        # dropping it; a later hash-chain hit restores the pages with a
        # device_put + scatter instead of a prefill forward pass. The
        # copy-out runs on a background thread (the engine lock only pays
        # for enqueuing the device-side gather); restores shorter than
        # host_restore_min_pages fall through to normal prefill (a short
        # device_put can lose to recompute).
        if prefix_host_bytes is None:
            prefix_host_bytes = getattr(cfg, "prefix_host_bytes", 0)
        self.host_store: Optional[paged.HostPageStore] = None
        self.host_restore_min_pages = max(int(host_restore_min_pages or 1), 1)
        self.host_restore_seconds = 0.0
        self._obs_restore_hist = None
        self._spill_q: Optional[object] = None
        self._spill_thread: Optional[threading.Thread] = None
        if self.prefix_index is not None and int(prefix_host_bytes) > 0:
            import queue as _queue

            self.host_store = paged.HostPageStore(int(prefix_host_bytes))
            # BOUNDED in PAGES: each queued batch pins its materialized
            # device-side gather copies until the worker lands them in
            # host RAM, so unbounded spilling would let an eviction burst
            # transiently hold many pools' worth of extra HBM on a chip
            # already sized near capacity. Pending pages are capped at
            # one pool's worth; past that, spills drop (plain eviction).
            self._spill_q = _queue.Queue()
            # pending-page counter shared by the engine thread (raise) and
            # the worker (lower) — int += is a read-modify-write, NOT
            # GIL-atomic, so it gets its own tiny lock
            self._spill_pending = 0  #: guarded_by _spill_lock
            self._spill_lock = make_lock("engine_spill")
            self._spill_max_pending = max(
                16, self.allocator.capacity_blocks()
            )
            import weakref

            # the worker must NOT root the engine (a bound-method target
            # would pin params + pool state forever if the engine were
            # dropped without close()) — it takes the queue/store/lock
            # directly and the pending counter through a weakref, the
            # same collectibility pattern as the _register_gauges
            # closures
            self._spill_thread = threading.Thread(
                target=TPUEngine._spill_worker,
                args=(self._spill_q, self.host_store, self._spill_lock,
                      weakref.ref(self)),
                name=f"prefix-host-spill-{cfg.name}",
                daemon=True,
            )
            self._spill_thread.start()
            self.prefix_index.spill = self._spill_pages
        self.spec_rounds = 0
        self.spec_tokens = 0
        self.spec_slot_rounds = 0
        # per-proposer splits of the speculative counters (the
        # aios_tpu_spec_*{proposer=...} label): rounds dispatched and
        # draft tokens accepted, keyed by spec.SPEC_PROPOSERS
        self.spec_proposer_rounds = {p: 0 for p in spec.SPEC_PROPOSERS}
        self.spec_proposer_accepted = {p: 0 for p in spec.SPEC_PROPOSERS}
        # draft-side dispatch accounting: bulk ingest dispatches (the
        # catch-up KV writes outside the fused round) and tokens proposed
        self.draft_ingest_dispatches = 0
        self.draft_proposed_tokens = 0
        # grammar jump-ahead accounting (jump_step): dispatches and the
        # forced tokens they appended — each dispatch replaced
        # jump_tokens/jump_dispatches masked single-token dispatches
        self.jump_dispatches = 0
        self.jump_tokens = 0
        # multi-tick megagraph accounting (mega_step): dispatches and the
        # REAL ticks they ran (k <= K when the device loop early-exited);
        # dispatches * K - mega_tick_total = ticks the early-exit contract
        # saved. Distinct attribute names from the mega_ticks knob above.
        self.mega_dispatches = 0
        self.mega_tick_total = 0
        # XLA compile-event accounting: every new jit graph counts once
        # and its FIRST dispatch's wall time — jax compiles synchronously
        # inside that call — is recorded as the compile stall. stats(),
        # bench.py, and the aios_tpu_engine_xla_* instruments all read
        # these, so a mid-serving compile (the TTFT-stall class warmup
        # exists to prevent) is visible instead of a mystery latency spike.
        self.compile_events = 0
        self.compile_seconds = 0.0
        # Device-time attribution (obs/devprof.py): per-graph cost
        # ledger + sampled dispatch timing, OFF by default — the hot
        # paths pay one attribute None-check, the faults/ pattern. Read
        # at construction like the pipeline knob: a live engine never
        # grows instrumentation mid-serving.
        self._devprof: Optional[devprof.DevprofLedger] = None
        if devprof.enabled():
            self._devprof = devprof.DevprofLedger(cfg.name)
        self._obs_decode_steps = obs.ENGINE_DECODE_STEPS.labels(model=cfg.name)
        self._register_gauges()

    def _register_gauges(self) -> None:
        """Scrape-time gauges over live engine state. weakref-bound so a
        closed engine (close() frees HBM deterministically) can still be
        garbage-collected; a model reload under the same name re-registers
        and the stale callback is replaced."""
        import weakref

        name = self.cfg.name
        ref = weakref.ref(self)

        def slots() -> float:
            e = ref()
            return float(e.active.sum()) if e is not None else 0.0

        def occupancy() -> float:
            e = ref()
            if e is None or not e.num_slots:
                return 0.0
            return float(e.active.sum()) / e.num_slots

        obs.ENGINE_SLOTS_IN_USE.labels(model=name).set_function(slots)
        obs.ENGINE_OCCUPANCY.labels(model=name).set_function(occupancy)
        # jump-ahead + speculative counters: replica engines share the
        # (model,) label and set_function is last-writer-wins, so these
        # read a per-model WeakSet of live engines and report the SUM
        # (the aios_tpu_prefix_host_* aggregation pattern). Dead engines
        # drop out when collected.
        engines = _ENGINES_BY_MODEL.setdefault(name, weakref.WeakSet())
        engines.add(self)

        def engines_sum(attr):
            def read() -> float:
                return float(sum(getattr(e, attr) for e in engines))

            return read

        obs.ENGINE_JUMP_DISPATCHES.labels(model=name).set_function(
            engines_sum("jump_dispatches")
        )
        obs.ENGINE_JUMP_TOKENS.labels(model=name).set_function(
            engines_sum("jump_tokens")
        )
        obs.ENGINE_MEGA_DISPATCHES.labels(model=name).set_function(
            engines_sum("mega_dispatches")
        )
        obs.ENGINE_MEGA_TICKS.labels(model=name).set_function(
            engines_sum("mega_tick_total")
        )
        # long-context tier: compression + sequence-sharded prefill
        # counters (same WeakSet-summed monotonic-engine-counter pattern)
        obs.KV_COMPRESS_SLOTS.labels(model=name).set_function(
            engines_sum("kv_compress_slots")
        )
        obs.KV_COMPRESS_PAGES_PRUNED.labels(model=name).set_function(
            engines_sum("kv_pages_pruned")
        )
        obs.PREFILL_SEQ_SHARDED.labels(model=name).set_function(
            engines_sum("prefill_seq_sharded")
        )

        def compressed_resident() -> float:
            return float(sum(
                e.compressed_resident_pages() for e in engines
            ))

        obs.KV_COMPRESS_RESIDENT.labels(model=name).set_function(
            compressed_resident
        )
        # spec counters carry the (model, proposer) label pair — one
        # series per proposer in the closed spec.SPEC_PROPOSERS enum,
        # each summing its per-proposer engine counter over the WeakSet
        def proposer_sum(attr, proposer):
            def read() -> float:
                return float(sum(
                    getattr(e, attr).get(proposer, 0) for e in engines
                ))

            return read

        for p in spec.SPEC_PROPOSERS:
            obs.SPEC_ROUNDS.labels(model=name, proposer=p).set_function(
                proposer_sum("spec_proposer_rounds", p)
            )
            obs.SPEC_ACCEPTED.labels(model=name, proposer=p).set_function(
                proposer_sum("spec_proposer_accepted", p)
            )
        if self._devprof is not None:
            # devprof family: per-graph children iterate the CLOSED
            # devprof.GRAPH_KINDS enum (the SLO-objectives pattern) and
            # SUM over the per-model WeakSet of replica ledgers. The
            # MFU / HBM-utilization gauges register only when the
            # device_kind's roofline is known (docs/HARDWARE.md) —
            # unknown kinds keep raw seconds and omit the ratios.
            ledgers = devprof.ledgers_for(name)

            def ledger_sum(kind, idx):
                def read() -> float:
                    return float(sum(
                        led.totals(kind)[idx] for led in ledgers
                    ))

                return read

            def ledger_device_s(kind):
                def read() -> float:
                    return float(sum(
                        led.device_seconds(kind) for led in ledgers
                    ))

                return read

            def ledger_util(kind, idx, peak_idx):
                # weighted across replicas: sum sampled flops/bytes over
                # sum sampled seconds (a per-replica mean-of-ratios
                # would over-weight idle replicas)
                def read() -> float:
                    num = sum(led.totals(kind)[idx] for led in ledgers)
                    den = sum(led.totals(kind)[4] for led in ledgers)
                    peaks = next(
                        (led.peaks for led in ledgers
                         if led.peaks is not None), None,
                    )
                    if not den or peaks is None:
                        return 0.0
                    return float(num / den / peaks[peak_idx])

                return read

            roofline = self._devprof.peaks is not None
            for g in devprof.GRAPH_KINDS:
                obs.DEVPROF_DISPATCHES.labels(
                    model=name, graph=g
                ).set_function(ledger_sum(g, 0))
                obs.DEVPROF_DEVICE_SECONDS.labels(
                    model=name, graph=g
                ).set_function(ledger_device_s(g))
                if roofline:
                    obs.DEVPROF_MFU.labels(
                        model=name, graph=g
                    ).set_function(ledger_util(g, 5, 0))
                    obs.DEVPROF_HBM_UTIL.labels(
                        model=name, graph=g
                    ).set_function(ledger_util(g, 6, 1))
        if self.allocator is not None:
            def pages_in_use() -> float:
                e = ref()
                return float(e.allocator.pages_in_use()) if e is not None else 0.0

            def page_util() -> float:
                e = ref()
                if e is None:
                    return 0.0
                total = e.allocator.pages_in_use() + e.allocator.free_pages
                return e.allocator.pages_in_use() / total if total else 0.0

            obs.ENGINE_KV_PAGES_IN_USE.labels(model=name).set_function(
                pages_in_use
            )
            obs.ENGINE_KV_PAGE_UTILIZATION.labels(model=name).set_function(
                page_util
            )
        if self.prefix_index is not None:
            def hits() -> float:
                e = ref()
                ix = e.prefix_index if e is not None else None
                return float(ix.hits) if ix is not None else 0.0

            def misses() -> float:
                e = ref()
                ix = e.prefix_index if e is not None else None
                return float(ix.misses) if ix is not None else 0.0

            obs.ENGINE_PREFIX_HITS.labels(model=name).set_function(hits)
            obs.ENGINE_PREFIX_MISSES.labels(model=name).set_function(misses)
        if self.host_store is not None:
            # Replica engines share the (model,) label, and set_function
            # is last-writer-wins — so every replica's callback reads a
            # shared per-model WeakSet of live stores and reports the SUM,
            # matching the pool.stats() aggregate. Dead pools drop out of
            # the set when their engines are collected.
            stores = _HOST_STORES_BY_MODEL.setdefault(name, weakref.WeakSet())
            stores.add(self.host_store)

            def store_stat(attr):
                def read() -> float:
                    return float(sum(getattr(s, attr) for s in stores))

                return read

            obs.PREFIX_HOST_BYTES.labels(model=name).set_function(
                store_stat("bytes_resident")
            )
            obs.PREFIX_HOST_SPILLS.labels(model=name).set_function(
                store_stat("spills")
            )
            obs.PREFIX_HOST_RESTORES.labels(model=name).set_function(
                store_stat("restores")
            )
            obs.PREFIX_HOST_HITS.labels(model=name).set_function(
                store_stat("hits")
            )
            obs.PREFIX_HOST_MISSES.labels(model=name).set_function(
                store_stat("misses")
            )
            obs.PREFIX_HOST_MISSES_CORRUPT.labels(model=name).set_function(
                store_stat("corruptions")
            )
            self._obs_restore_hist = obs.PREFIX_HOST_RESTORE_SECONDS.labels(
                model=name
            )

    # -- jitted cores -------------------------------------------------------

    def _tables_operand(self):
        """The per-dispatch paged operand: the page tables, paired with
        the per-slot live-window starts when window+sink KV compression
        is armed (the mask operand rides BESIDE the tables rather than in
        the donated state — it changes only at prune events, exactly like
        the tables change only at alloc events). Caller holds the engine
        lock."""
        t = jnp.asarray(self.allocator.tables)
        if self.kv_compress_armed:
            return (t, jnp.asarray(self._win_starts))
        return t

    @staticmethod
    def _split_tables(tables):
        """Unpack a ``_tables_operand`` value into (tables, win_starts);
        win_starts is None on engines without compression armed (their
        graphs are byte-identical to the pre-compression tree)."""
        if isinstance(tables, (tuple, list)):
            return tables[0], tables[1]
        return tables, None

    def _decode_body(self, params, st: DecodeState, sub, tables=None,
                     mask=None):
        """ONE decode step against whichever cache layout this engine runs
        — the shared body of the per-size scan graphs (``_step_impl``) and
        the unified dynamic-n loop graph (``_unified_impl``). Only the
        model call differs between the dense, int8-KV and paged layouts;
        sampling, history gating and the state rebuild are shared.
        ``mask`` [S, V] fp32 adds to the logits before sampling — the
        grammar-constraint hook (engine/jsonmode.py), step_masked only."""
        if self.paged:
            tables, win_starts = self._split_tables(tables)
            scales = (
                (st["k_s"], st["v_s"]) if self.quant_cache else None
            )
            out = model.decode_step_paged(
                params,
                self.cfg,
                st["last_tokens"],
                st["lengths"],
                st["k"],
                st["v"],
                tables,
                kernels=self._kernels,
                cache_scales=scales,
                active=st["active"],
                moe_impl=self._moe_impl,
                qmm=self._qmm_impl,
                pool_impl=self._pool_impl,
                win_starts=win_starts,
                sink_rows=self._sink_rows,
            )
            if self.quant_cache:
                logits, k, v, (k_s, v_s) = out
            else:
                logits, k, v = out
        elif self.quant_cache:
            logits, k, v, (k_s, v_s) = model.decode_step(
                params,
                self.cfg,
                st["last_tokens"],
                st["lengths"],
                st["k"],
                st["v"],
                kernels=self._kernels,
                cache_scales=(st["k_s"], st["v_s"]),
                active=st["active"],
                moe_impl=self._moe_impl,
                qmm=self._qmm_impl,
            )
        else:
            logits, k, v = model.decode_step(
                params,
                self.cfg,
                st["last_tokens"],
                st["lengths"],
                st["k"],
                st["v"],
                kernels=self._kernels,
                active=st["active"],
                attn_impl=self._attn_impl,
                moe_impl=self._moe_impl,
                qmm=self._qmm_impl,
            )
        if mask is not None:
            logits = logits + mask
        next_tokens = sampling.sample(
            logits, sub, st["temps"], st["top_ps"],
            exact=mask is not None,
        )
        slots = jnp.arange(self.num_slots)
        # new token's history col is lengths+1 (<= C, inside the pad);
        # inactive slots — retired or MID-CHUNKED-PREFILL — write to the
        # sacrificial last pad col instead, or interleaved dispatches
        # would scribble over prompt tokens the chunk admission already
        # wrote (K/V has the same gate via the sacrificial cache row)
        hcol = jnp.where(
            st["active"],
            st["lengths"] + 1,
            st["history"].shape[1] - 1,
        )
        st = {
            "k": k,
            "v": v,
            "lengths": jnp.minimum(st["lengths"] + 1, self.max_context - 1),
            "last_tokens": next_tokens,
            "temps": st["temps"],
            "top_ps": st["top_ps"],
            "active": st["active"],
            "history": (
                st["history"].at[slots, hcol].set(next_tokens)
                if self.track_history else st["history"]
            ),
            "key": st["key"],
        }
        if self.quant_cache:
            st["k_s"] = k_s
            st["v_s"] = v_s
        return st, next_tokens

    def _step_impl(self, params, state: DecodeState, n_steps: int, tables=None,
                   mask=None):
        """The decode scan: ``n_steps`` applications of ``_decode_body``
        in one dispatch (one traced body, XLA while-loop — never an
        unrolled graph)."""

        def one(carry, sub):
            return self._decode_body(params, carry, sub, tables, mask)

        # one batched split for the whole dispatch instead of a split per
        # step: keeps the threefry chain out of the scan's serial carry
        # dependency (measurable at TinyLlama step times) — keys[0] becomes
        # the next dispatch's base key, keys[1:] feed the steps
        keys = jax.random.split(state["key"], n_steps + 1)
        state = dict(state, key=keys[0])
        state, tokens = jax.lax.scan(one, state, keys[1:])
        return state, tokens  # tokens [n_steps, S]

    def _unified_impl(self, params, state: DecodeState, n, max_steps: int,
                      tables=None):
        """Dynamic-step decode loop: run ``n`` (a traced operand, n <=
        max_steps) steps of ``_decode_body`` under one fori_loop, emitting
        into a fixed [max_steps, S] token buffer — ONE compiled graph
        serves every chunk size the batcher dispatches. Rows past n stay
        zero and are sliced off on the host (PendingDecode.wait). The key
        fanout is max_steps+1 regardless of n, so sampled sequences differ
        from the per-size scan graphs (greedy output is identical)."""
        keys = jax.random.split(state["key"], max_steps + 1)
        state = dict(state, key=keys[0])

        def body(i, carry):
            st, out = carry
            st, tok = self._decode_body(params, st, keys[i + 1], tables)
            return st, out.at[i].set(tok)

        out0 = jnp.zeros((max_steps, self.num_slots), jnp.int32)
        state, tokens = jax.lax.fori_loop(
            0, jnp.minimum(n, max_steps), body, (state, out0)
        )
        return state, tokens  # tokens [max_steps, S]; rows [n:] are zeros

    def _mega_impl(self, params, state: DecodeState, n, stops, budgets,
                   abort_after, max_ticks: int, tables=None):
        """Device-resident multi-tick decode megagraph: up to ``n`` (a
        traced operand, n <= max_ticks) applications of ``_decode_body``
        under one ``lax.while_loop``, emitting into a fixed
        [max_ticks, S] token buffer — sampling, EOS/stop-sequence
        detection, per-slot token-budget and context-cap checks all run
        ON DEVICE, and the loop EXITS EARLY the moment no slot needs
        another tick, returning the real tick count ``k`` in the
        readback (the early-exit contract; the batcher's flush causes
        become loop-exit conditions instead of pipeline flushes).

        Per-slot live flags: a slot stays live while it is active, has
        not sampled one of its ``stops`` ids ([S, MEGA_STOP_SLOTS]
        int32, pad -1 — best-effort, the host emit loop stays
        authoritative), still has token budget (``budgets`` [S] int32,
        remaining = max_tokens - produced) and is below the context cap.
        ``abort_after`` (int32, normally n) is the injectable
        host-attention override: ``pool.megatick_abort`` caps the loop
        mid-window through it, exercising the early-exit path
        deterministically.

        The key fanout is ``split(key, max_ticks + 1)`` — the SAME
        fanout as ``_step_impl(max_ticks)`` — so a full-window mega
        dispatch is key-for-key identical to the per-size scan graph of
        the same size; early exits only ever skip ticks whose tokens the
        host would have discarded (every live slot done). Composes with
        the shard_map ragged-attention twin (``self._attn_impl``) and
        the paged pool exactly like the scan graphs: ``_decode_body`` is
        the shared body, so dp/tp-sharded plans serve the megagraph
        natively instead of silently falling back."""
        keys = jax.random.split(state["key"], max_ticks + 1)
        state = dict(state, key=keys[0])
        cap = jnp.minimum(jnp.minimum(n, abort_after), max_ticks)
        ctx_cap = self.max_context - 1

        def live(st, done, rem):
            return st["active"] & ~done & (rem > 0) & (st["lengths"] < ctx_cap)

        def cond(carry):
            i, st, _, done, rem = carry
            return (i < cap) & jnp.any(live(st, done, rem))

        def body(carry):
            i, st, out, done, rem = carry
            st, tok = self._decode_body(params, st, keys[i + 1], tables)
            out = out.at[i].set(tok)
            done = done | jnp.any(tok[:, None] == stops, axis=1)
            return i + 1, st, out, done, rem - 1

        out0 = jnp.zeros((max_ticks, self.num_slots), jnp.int32)
        done0 = jnp.zeros((self.num_slots,), jnp.bool_)
        k, state, tokens, _, _ = jax.lax.while_loop(
            cond, body,
            (jnp.int32(0), state, out0, done0,
             jnp.asarray(budgets, jnp.int32)),
        )
        # tokens [max_ticks, S]; rows [k:] are zeros and never read back
        return state, tokens, k

    def _verify_moe_impl(self, feed_width: int):
        """The gathered-MoE crossover gate shared by every verify-shaped
        dispatch (spec rounds, jump-ahead, draft verify): feeding W
        tokens per slot shifts the gather-vs-dense traffic crossover by
        that factor — gathering S*W*k expert blocks (with duplicates
        re-streamed) must still undercut the dense path's X blocks, or
        the verify falls back to dense."""
        if (
            self._moe_impl == "gather"
            and self.num_slots * feed_width * self.cfg.num_experts_per_tok
            >= self.cfg.num_experts
        ):
            return None
        return self._moe_impl

    def _verify_feed(self, params, st: DecodeState, feed, tables=None):
        """One multi-token verify forward against whichever cache layout
        this engine runs — the shared dispatch body of ``_spec_impl``,
        ``_jump_impl`` and ``_draft_spec_impl``. ``feed`` is [S, W]
        ([last_token, draft/forced tokens...]); returns
        (logits [S, W, V], k, v, scales-or-None)."""
        scales = (st["k_s"], st["v_s"]) if self.quant_cache else None
        moe_impl = self._verify_moe_impl(feed.shape[1])
        if self.paged:
            tables, win_starts = self._split_tables(tables)
            out = model.verify_step_paged(
                params, self.cfg, feed, st["lengths"], st["k"], st["v"],
                tables, cache_scales=scales, active=st["active"],
                moe_impl=moe_impl, qmm=self._qmm_gspmd,
                win_starts=win_starts, sink_rows=self._sink_rows,
            )
        else:
            out = model.verify_step(
                params, self.cfg, feed, st["lengths"], st["k"], st["v"],
                kernels=self._kernels, cache_scales=scales,
                active=st["active"], moe_impl=moe_impl,
                qmm=self._qmm_gspmd,
            )
        if self.quant_cache:
            logits, k, v, (k_s, v_s) = out
            return logits, k, v, (k_s, v_s)
        logits, k, v = out
        return logits, k, v, None

    def _spec_impl(
        self, params, state: DecodeState, n_rounds: int, draft_len: int,
        ngram: int, tables=None,
    ):
        """R speculative rounds in one dispatch: propose n-gram drafts from
        the device-resident history, verify them in a single multi-token
        forward, accept the longest matching prefix (spec.py). Every slot
        emits 1..draft_len+1 tokens per round; sampling (temp > 0) and
        inactive slots degrade to exactly one plain decode step per round,
        so this is a strict generalization of ``_step_impl``."""
        S, C, K = self.num_slots, self.max_context, draft_len
        slots = jnp.arange(S)
        # window+sink KV compression guard: a pruned slot proposes only
        # from matches inside its LIVE trailing window (never from the
        # pruned middle the verify attention can no longer see)
        _, win_starts = self._split_tables(tables)

        def one(st, _):
            drafts, _num = spec.propose_ngram(
                st["history"], st["lengths"], K, ngram, C,
                min_pos=win_starts,
            )
            # only greedy, active slots speculate; everyone else verifies
            # a row of -1 drafts (accept count 0 => plain decode step)
            ok = (st["temps"] < sampling.GREEDY_EPS) & st["active"]
            drafts = jnp.where(ok[:, None], drafts, -1)
            feed = jnp.concatenate(
                [st["last_tokens"][:, None], drafts], axis=1
            )  # [S, K+1]
            logits, k, v, new_scales = self._verify_feed(
                params, st, feed, tables
            )
            if self.quant_cache:
                k_s, v_s = new_scales
            g = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [S, K+1]
            a = spec.accept_counts(drafts, g)  # [S] in [0, K]
            key, sub = jax.random.split(st["key"])
            # row 0 == a plain decode step's logits; sample() takes argmax
            # for greedy rows, so this covers both kinds of slot
            first = sampling.sample(
                logits[:, 0], sub, st["temps"], st["top_ps"]
            )
            out_tokens = g.at[:, 0].set(first)  # [S, K+1]
            counts = a + 1  # tokens emitted this round per slot
            new_last = jnp.take_along_axis(out_tokens, a[:, None], axis=1)[:, 0]
            # accepted tokens land at history cols lengths+1 .. lengths+1+K
            # (within the HISTORY_PAD margin — no clamp, no write collisions
            # for active slots); inactive slots write the sacrificial last
            # pad col so interleaved dispatches can't corrupt a
            # mid-chunked-prefill slot's prompt history
            hidx = jnp.where(
                st["active"][:, None],
                st["lengths"][:, None] + 1 + jnp.arange(K + 1)[None, :],
                st["history"].shape[1] - 1,
            )
            st = {
                "k": k,
                "v": v,
                "lengths": jnp.minimum(st["lengths"] + counts, C - 1),
                "last_tokens": new_last,
                "temps": st["temps"],
                "top_ps": st["top_ps"],
                "active": st["active"],
                "history": st["history"].at[slots[:, None], hidx].set(out_tokens),
                "key": key,
            }
            if self.quant_cache:
                st["k_s"] = k_s
                st["v_s"] = v_s
            return st, (out_tokens, counts)

        state, (tokens, counts) = jax.lax.scan(one, state, None, length=n_rounds)
        return state, (tokens, counts)  # [R, S, K+1], [R, S]

    # -- draft-model speculation (spec.DraftModel) --------------------------
    # The draft keeps its own dense KV cache whose rows [0, d_len) mirror
    # history[:, 0:d_len) — the same contract the serving cache keeps with
    # its lengths — so keeping it consistent across accept/reject/retire
    # is a matter of moving d_len, never of rewriting rows: accepted draft
    # rows were written by the draft itself, rejected rows fall beyond the
    # clamped d_len and are overwritten before they can be read.

    def _draft_ingest_body(self, dparams, dstate, history, t_lengths,
                           active, width: int):
        """Teacher-forced draft catch-up: ingest up to ``width`` history
        tokens per slot into the draft KV (rows [d_len, d_len+width)),
        advancing draft lengths toward the serving model's. Write-only —
        the draft's logits are discarded; this is a verify forward used
        as a bulk KV writer. Slots already caught up (or inactive) gate
        out via ``active``, so their writes land on the sacrificial row."""
        dcfg = self.draft.cfg
        d_len = dstate["lengths"]
        gap = jnp.maximum(t_lengths - d_len, 0)
        ing = active & (gap > 0)
        # [S, width] gather from the history buffer; small next to the
        # draft forward it feeds (not the [S, W] full-width gather class
        # propose_ngram avoids — width here is bounded by the ingest
        # bucket, not the context)
        idx = jnp.clip(
            d_len[:, None] + jnp.arange(width)[None, :],
            0, history.shape[1] - 1,
        )
        feed = jnp.take_along_axis(history, idx, axis=1)
        _logits, k, v = model.verify_step(
            dparams, dcfg, feed, d_len, dstate["k"], dstate["v"],
            kernels=self._kernels, active=ing,
        )
        new_len = d_len + jnp.where(ing, jnp.minimum(gap, width), 0)
        return {"k": k, "v": v, "lengths": new_len}

    def _draft_ingest_impl(self, dparams, dstate, history, t_lengths,
                           active, temps, width: int):
        """The standalone bulk-ingest graph (power-of-two ``width``
        buckets): freshly admitted slots' draft KV trails by the whole
        prompt, and burning fused-round catch-up budget on it would cost
        one round per CATCHUP-width chunk. Sampling slots never propose,
        so only greedy slots ingest. Serving state is read-only here;
        only the draft state is donated."""
        return self._draft_ingest_body(
            dparams, dstate, history, t_lengths,
            active & (temps < sampling.GREEDY_EPS), width,
        )

    def _draft_propose_body(self, dparams, dstate, t_last, ok, draft_len):
        """K autoregressive greedy draft steps: step 1 consumes the
        serving model's pending token (writing its draft-KV row at
        d_len), later steps consume the draft's own argmax. Non-proposing
        slots still run (fixed-shape graph) but write the sacrificial row
        and never advance. Returns (drafts [S, K] with -1 rows for
        non-proposing slots, new draft state)."""
        dcfg = self.draft.cfg
        C = dstate["k"].shape[2]

        def one(carry, _):
            k, v, cur_len, cur_tok = carry
            logits, k, v = model.decode_step(
                dparams, dcfg, cur_tok, cur_len, k, v,
                kernels=self._kernels, active=ok,
            )
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            new_len = jnp.where(ok, jnp.minimum(cur_len + 1, C - 1), cur_len)
            return (k, v, new_len, nxt), nxt

        (k, v, d_len, _), drafts = jax.lax.scan(
            one,
            (dstate["k"], dstate["v"], dstate["lengths"], t_last),
            None, length=draft_len,
        )
        drafts = jnp.where(ok[:, None], drafts.T, -1)  # [S, K]
        return drafts, {"k": k, "v": v, "lengths": d_len}

    def _draft_spec_impl(
        self, params, dparams, state: DecodeState, dstate, n_rounds: int,
        draft_len: int, catchup: int, tables=None,
    ):
        """R draft-model speculative rounds in ONE dispatch: each round
        catches the draft KV up to the serving state (teacher-forced,
        width ``catchup`` — steady-state gap is 0 or 1), runs K
        autoregressive draft steps, verifies the whole draft through the
        serving model's verify forward, accepts the longest matching
        prefix (exact for greedy slots — token streams identical to plain
        decode), and clamps the draft lengths back to the verified
        length so rejected draft rows become unreadable. Sampling and
        inactive slots degrade to one plain decode step per round,
        exactly like ``_spec_impl``; slots whose draft is still catching
        up (gap > catchup) also take the plain step this round and
        propose next round. Returns (state', dstate',
        (tokens [R, S, K+1], counts [R, S], proposed [R, S]))."""
        S, C, K = self.num_slots, self.max_context, draft_len
        slots = jnp.arange(S)
        # window+sink KV compression guard: the draft's dense KV mirrors
        # the FULL history, but a pruned slot's serving attention no
        # longer sees the middle — the draft would propose from context
        # the verify can't read, so pruned slots fall back to the plain
        # step inside the round (ok gate below)
        _, win_starts = self._split_tables(tables)

        def one(carry, _):
            st, dst = carry
            # sampling slots never propose (the ok gate below), so
            # building their draft KV would be pure ingest cost — gate
            # the catch-up on greedy too
            greedy_active = st["active"] & (
                st["temps"] < sampling.GREEDY_EPS
            )
            dst = self._draft_ingest_body(
                dparams, dst, st["history"], st["lengths"], greedy_active,
                catchup,
            )
            # propose only where the draft mirrors the serving cache
            # exactly AND the verify-write contract has room for a full
            # K-draft acceptance (accepted rows stay <= C-2)
            ok = (
                (st["temps"] < sampling.GREEDY_EPS)
                & st["active"]
                & (dst["lengths"] == st["lengths"])
                & (st["lengths"] + K <= C - 2)
            )
            if win_starts is not None:
                ok = ok & (win_starts == 0)
            drafts, dst = self._draft_propose_body(
                dparams, dst, st["last_tokens"], ok, K
            )
            proposed = jnp.where(ok, K, 0)
            feed = jnp.concatenate(
                [st["last_tokens"][:, None], drafts], axis=1
            )  # [S, K+1]
            logits, k, v, new_scales = self._verify_feed(
                params, st, feed, tables
            )
            g = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [S, K+1]
            a = spec.accept_counts(drafts, g)  # [S] in [0, K]
            key, sub = jax.random.split(st["key"])
            first = sampling.sample(
                logits[:, 0], sub, st["temps"], st["top_ps"]
            )
            out_tokens = g.at[:, 0].set(first)  # [S, K+1]
            counts = a + 1
            new_last = jnp.take_along_axis(
                out_tokens, a[:, None], axis=1
            )[:, 0]
            hidx = jnp.where(
                st["active"][:, None],
                st["lengths"][:, None] + 1 + jnp.arange(K + 1)[None, :],
                st["history"].shape[1] - 1,
            )
            new_lengths = jnp.minimum(st["lengths"] + counts, C - 1)
            st = {
                "k": k,
                "v": v,
                "lengths": new_lengths,
                "last_tokens": new_last,
                "temps": st["temps"],
                "top_ps": st["top_ps"],
                "active": st["active"],
                "history": st["history"].at[
                    slots[:, None], hidx
                ].set(out_tokens),
                "key": key,
            }
            if self.quant_cache:
                st["k_s"], st["v_s"] = new_scales
            # draft sync: rows for accepted tokens are already correct
            # (the draft wrote them while proposing); everything past the
            # verified length — rejected drafts, or the bonus token's
            # still-unwritten row after a full accept — is clamped out
            dst = dict(dst, lengths=jnp.minimum(dst["lengths"], new_lengths))
            return (st, dst), (out_tokens, counts, proposed)

        (state, dstate), (tokens, counts, proposed) = jax.lax.scan(
            one, (state, dstate), None, length=n_rounds
        )
        return state, dstate, (tokens, counts, proposed)

    def _jump_impl(self, params, state: DecodeState, forced, counts,
                   tables=None):
        """Grammar jump-ahead: append a host-computed FORCED token run to
        each jumping slot in ONE multi-token dispatch. ``forced`` [S, K]
        holds the run tokens (rows padded past ``counts[s]``); a slot with
        ``counts[s] == c > 0`` scores [last_token, f_1..f_{c-1}] through
        the speculative-verify forward — acceptance pinned to all-accept:
        the tokens are grammar-forced, the model's opinion is moot — so
        its K/V rows land exactly as c masked single-token dispatches
        would have left them, ``last_tokens`` becomes f_c (the new pending
        token, K/V written by the next dispatch as usual) and ``lengths``
        advances by c. Slots with ``counts[s] == 0`` are NO-OPS: lengths
        and last_tokens unchanged (their row-0 K/V write is the value the
        next real dispatch rewrites identically; rows past the count land
        beyond ``lengths`` and are overwritten before ever being read).
        The RNG key is untouched — nothing samples here, so greedy AND
        the forced tokens of sampled streams are identical to the
        per-step path. Logits are computed by the verify forward but
        discarded; on TPU the dispatch is weight-bandwidth-bound like any
        decode step, so K forced tokens cost ~one step instead of K."""
        S, C, K = self.num_slots, self.max_context, forced.shape[1]
        slots = jnp.arange(S)
        st = state
        feed = jnp.concatenate([st["last_tokens"][:, None], forced], axis=1)
        _logits, k, v, new_scales = self._verify_feed(params, st, feed,
                                                      tables)
        if self.quant_cache:
            k_s, v_s = new_scales
        jumped = counts > 0
        new_last = jnp.where(
            jumped,
            jnp.take_along_axis(feed, counts[:, None], axis=1)[:, 0],
            st["last_tokens"],
        )
        hist = st["history"]
        if self.track_history:
            # run tokens land at history cols lengths+1 .. lengths+K
            # (inside the HISTORY_PAD margin, K <= HISTORY_PAD - 2); cols
            # past the count are garbage beyond the new length, exactly
            # like the spec scatter. Non-jumping/inactive slots write the
            # sacrificial last pad column.
            hidx = jnp.where(
                (st["active"] & jumped)[:, None],
                st["lengths"][:, None] + 1 + jnp.arange(K)[None, :],
                hist.shape[1] - 1,
            )
            hist = hist.at[slots[:, None], hidx].set(forced)
        new = {
            "k": k,
            "v": v,
            "lengths": jnp.minimum(st["lengths"] + counts, C - 1),
            "last_tokens": new_last,
            "temps": st["temps"],
            "top_ps": st["top_ps"],
            "active": st["active"],
            "history": hist,
            "key": st["key"],
        }
        if self.quant_cache:
            new["k_s"] = k_s
            new["v_s"] = v_s
        return new

    def _prefill_impl_paged(
        self, params, state: DecodeState, tokens, slot, true_len, temp, top_p,
        table_row, attn_fn=None,
    ):
        """Paged twin of ``_prefill_impl``: the prompt's K/V rows scatter
        into the page pool through ``table_row`` (the slot's block->page
        map; rows in unbacked blocks land on the sacrificial page 0 and are
        never read). ``attn_fn`` (a closure, not an operand) swaps the
        forward's attention — the sequence-sharded prefill graphs pass the
        ring/Ulysses adapter so a huge prompt's forward spreads over the
        mesh's sp axis while the scatter/sample/activate tail stays
        byte-for-byte the normal admission path."""
        logits, ks, vs = model.prefill(
            params, self.cfg, tokens, kernels=self._kernels,
            qmm=self._qmm_gspmd, attn_fn=attn_fn,
        )
        T = tokens.shape[1]
        P = state["k"].shape[2]
        nb = -(-T // P)  # blocks this bucket spans (static)
        # static repeat, not table_row[rows // P]: an index-array gather
        # serializes on TPU (same lesson as spec.propose_ngram)
        pages = jnp.repeat(table_row[:nb], P)[:T]  # [T]
        offs = jnp.arange(T) % P
        # ks/vs [L, 1, T, KH, D] -> pool [L, N, P, KH, D]
        if self._paged_scatter is not None:
            # dp-replicated pool: table ids are replica-local, so the
            # scatter must run per device (only the owning replica's
            # writes target real pages — ShardingPlan.paged_prefill_scatter)
            owner = self.allocator.replica_of(slot)
            if self.quant_cache:
                kq, ks_scale = model.quantize_kv(ks[:, 0])
                vq, vs_scale = model.quantize_kv(vs[:, 0])
                k, v, k_s, v_s = self._paged_scatter(
                    state["k"], state["v"], state["k_s"], state["v_s"],
                    kq, vq, ks_scale, vs_scale, pages, offs, owner,
                )
            else:
                k, v = self._paged_scatter(
                    state["k"], state["v"],
                    ks[:, 0].astype(state["k"].dtype),
                    vs[:, 0].astype(state["v"].dtype),
                    pages, offs, owner,
                )
        elif self.quant_cache:
            kq, ks_scale = model.quantize_kv(ks[:, 0])  # [L, T, KH, D/·]
            vq, vs_scale = model.quantize_kv(vs[:, 0])
            k = state["k"].at[:, pages, offs].set(kq)
            v = state["v"].at[:, pages, offs].set(vq)
            k_s = state["k_s"].at[:, pages, offs].set(ks_scale)
            v_s = state["v_s"].at[:, pages, offs].set(vs_scale)
        else:
            k = state["k"].at[:, pages, offs].set(
                ks[:, 0].astype(state["k"].dtype)
            )
            v = state["v"].at[:, pages, offs].set(
                vs[:, 0].astype(state["v"].dtype)
            )
        key, sub = jax.random.split(state["key"])
        last = logits[0, true_len - 1][None, :]  # [1, V]
        first = sampling.sample(last, sub, temp[None], top_p[None])[0]
        history = jax.lax.dynamic_update_slice(
            state["history"], tokens, (slot, jnp.int32(0))
        )
        out = {
            "k": k,
            "v": v,
            "lengths": state["lengths"].at[slot].set(true_len),
            "last_tokens": state["last_tokens"].at[slot].set(first),
            "temps": state["temps"].at[slot].set(temp),
            "top_ps": state["top_ps"].at[slot].set(top_p),
            "active": state["active"].at[slot].set(True),
            "history": history.at[slot, true_len].set(first),
            "key": key,
        }
        if self.quant_cache:
            out["k_s"] = k_s
            out["v_s"] = v_s
        return out, first

    def _prefill_impl(
        self, params, state: DecodeState, tokens, slot, true_len, temp, top_p
    ):
        logits, ks, vs = model.prefill(
            params, self.cfg, tokens, kernels=self._kernels,
            qmm=self._qmm_gspmd,
        )
        # ks/vs [L, B=1, T, KH, D] -> cache layout [L, slot, T, KH, D]
        start = (0, slot, 0, 0, 0)
        if self.quant_cache:
            kq, ks_scale = model.quantize_kv(ks)
            vq, vs_scale = model.quantize_kv(vs)
            k = jax.lax.dynamic_update_slice(state["k"], kq, start)
            v = jax.lax.dynamic_update_slice(state["v"], vq, start)
            k_s = jax.lax.dynamic_update_slice(
                state["k_s"], ks_scale, start[:-1]
            )
            v_s = jax.lax.dynamic_update_slice(
                state["v_s"], vs_scale, start[:-1]
            )
        else:
            k = jax.lax.dynamic_update_slice(
                state["k"], ks.astype(state["k"].dtype), start
            )
            v = jax.lax.dynamic_update_slice(
                state["v"], vs.astype(state["v"].dtype), start
            )
        key, sub = jax.random.split(state["key"])
        last = logits[0, true_len - 1][None, :]  # [1, V]
        first = sampling.sample(last, sub, temp[None], top_p[None])[0]
        history = jax.lax.dynamic_update_slice(
            state["history"], tokens, (slot, jnp.int32(0))
        )
        out = {
            "k": k,
            "v": v,
            "lengths": state["lengths"].at[slot].set(true_len),
            "last_tokens": state["last_tokens"].at[slot].set(first),
            "temps": state["temps"].at[slot].set(temp),
            "top_ps": state["top_ps"].at[slot].set(top_p),
            "active": state["active"].at[slot].set(True),
            "history": history.at[slot, true_len].set(first),
            "key": key,
        }
        if self.quant_cache:
            out["k_s"] = k_s
            out["v_s"] = v_s
        return out, first

    def _chunk_forward(self, params, state: DecodeState, tokens, slot, start,
                       table_row, win_start=None):
        """One prefill chunk against whichever cache layout this engine
        runs (paged / int8 KV / dense); returns (logits, kv-state updates).
        The single place the layout dispatch lives — both chunk impls
        build on it. ``win_start`` (armed engines only) masks the pruned
        middle of a mid-admission compressed slot."""
        upd: Dict[str, jnp.ndarray] = {}
        if self.paged:
            scales = (state["k_s"], state["v_s"]) if self.quant_cache else None
            out = model.prefill_chunk_paged(
                params, self.cfg, tokens, start, state["k"], state["v"],
                table_row, cache_scales=scales, qmm=self._qmm_gspmd,
                win_start=win_start, sink_rows=self._sink_rows,
            )
            if self.quant_cache:
                logits, upd["k"], upd["v"], (upd["k_s"], upd["v_s"]) = out
            else:
                logits, upd["k"], upd["v"] = out
        else:
            scales = (state["k_s"], state["v_s"]) if self.quant_cache else None
            out = model.prefill_chunk(
                params, self.cfg, tokens, slot, start, state["k"], state["v"],
                cache_scales=scales, qmm=self._qmm_gspmd,
            )
            if self.quant_cache:
                logits, upd["k"], upd["v"], (upd["k_s"], upd["v_s"]) = out
            else:
                logits, upd["k"], upd["v"] = out
        return logits, upd

    def _prefill_chunk_impl(
        self, params, state: DecodeState, tokens, slot, start, table_row=None,
        win_start=None,
    ):
        """Mid-prompt chunk: write K/V rows [start, start+Tc), no sampling.
        Paged engines route the writes through ``table_row`` (the slot's
        block->page map) instead of the slot index."""
        _, upd = self._chunk_forward(params, state, tokens, slot, start,
                                     table_row, win_start)
        new = dict(state)
        new.update(upd)
        new["history"] = self._chunk_history(state, tokens, slot, start)
        return new

    @staticmethod
    def _chunk_history(state, tokens, slot, start):
        """Write a chunk's tokens at history cols [start, start+bucket),
        clamping overflow cols onto the sacrificial last pad column — a
        prefix match de-aligns chunk starts, so a final bucket's padding
        may overrun the buffer (dynamic_update_slice would clamp the START
        and silently shift real tokens)."""
        W = state["history"].shape[1]
        hcol = jnp.clip(start + jnp.arange(tokens.shape[1]), 0, W - 1)
        return state["history"].at[slot, hcol].set(tokens[0])

    def _final_chunk_impl(
        self, params, state: DecodeState, tokens, slot, start, n_valid,
        true_len, temp, top_p, table_row=None, win_start=None,
    ):
        """Last chunk: write K/V, then sample the first token from the
        logits row of the prompt's true last token and activate the slot."""
        logits, upd = self._chunk_forward(params, state, tokens, slot, start,
                                          table_row, win_start)
        new = dict(state)
        new.update(upd)
        key, sub = jax.random.split(state["key"])
        last = logits[0, n_valid - 1][None, :]  # [1, V]
        first = sampling.sample(last, sub, temp[None], top_p[None])[0]
        history = self._chunk_history(state, tokens, slot, start)
        new["lengths"] = state["lengths"].at[slot].set(true_len)
        new["last_tokens"] = state["last_tokens"].at[slot].set(first)
        new["temps"] = state["temps"].at[slot].set(temp)
        new["top_ps"] = state["top_ps"].at[slot].set(top_p)
        new["active"] = state["active"].at[slot].set(True)
        new["history"] = history.at[slot, true_len].set(first)
        new["key"] = key
        return new, first

    def _instrument_compile(self, fn, kind: str):
        """Count the new jit graph and time its FIRST dispatch (jax traces
        and XLA-compiles synchronously inside that call; execution itself
        is async, so the first-call elapsed isolates the compile stall).
        Subsequent calls go straight through."""
        obs.ENGINE_XLA_COMPILES.labels(model=self.cfg.name, kind=kind).inc()
        self.compile_events += 1
        hist = obs.ENGINE_XLA_COMPILE_SECONDS.labels(
            model=self.cfg.name, kind=kind
        )
        state = {"first": True}

        def wrapper(*args, **kwargs):
            if not state["first"]:
                return fn(*args, **kwargs)
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            dt = time.perf_counter() - t0
            state["first"] = False
            self.compile_seconds += dt
            hist.observe(dt)
            return out

        return wrapper

    # -- device-time attribution hooks (obs/devprof.py) ---------------------
    # Hot-path contract: one attribute None-check when devprof is off.
    # ``_devprof_note`` counts the dispatch ALWAYS (the per-graph
    # ledger) and returns a timing token only when this dispatch is due
    # a sample; its kind argument must be a devprof.GRAPH_KINDS literal
    # (tests/test_obs_lint.py enumerates the call sites on the AST).
    # ``_devprof_sample`` lands the host-measured completion delta —
    # call it after the result is already known ready (past an
    # np.asarray readback, or submit-side for deliberately-async
    # dispatches like the restore scatter); ``_devprof_sample_sync``
    # blocks on ``arrays`` first, so it must NEVER run under a declared
    # lock (the lock-readback rule the analyzer enforces).

    def _devprof_note(self, kind: str, key=None, need_slack: bool = False):
        dp = self._devprof
        if dp is None:
            return None
        due = dp.note(kind, key)
        if due and need_slack and dp.queue_depth() > 1:
            # the depth-2 double buffer has a dispatch queued behind this
            # one: skip the sample rather than ever delaying it
            due = False
        return (kind, key, time.perf_counter()) if due else None

    def _devprof_sample(self, tok) -> Optional[float]:
        if tok is None:
            return None
        kind, key, t0 = tok
        dt = time.perf_counter() - t0
        self._devprof.sample(kind, key, dt)
        return dt

    def _devprof_sample_sync(self, tok, arrays) -> Optional[float]:
        if tok is None:
            return None
        jax.block_until_ready(arrays)
        return self._devprof_sample(tok)

    def devprof_est_s(self, kind: str) -> Optional[float]:
        """Mean sampled device-seconds per ``kind`` dispatch (None when
        devprof is off or unsampled) — the batcher's per-request
        attribution rate."""
        dp = self._devprof
        return dp.mean_s(kind) if dp is not None else None

    def devprof_take_sample(self):
        """Pop the ledger's most recent (kind, seconds) sample — the
        batcher joins it onto the flight-recorder event of the dispatch
        it just issued."""
        dp = self._devprof
        return dp.take_last_sample() if dp is not None else None

    def devprof_snapshot(self) -> Optional[dict]:
        """The per-graph ledger as a JSON-shaped dict (bench_devprof)."""
        dp = self._devprof
        return dp.snapshot() if dp is not None else None

    # -- jit builders -------------------------------------------------------
    # One builder per graph kind, shared by the LAZY getters (compile on
    # first dispatch, timed by _instrument_compile) and the AOT warmup
    # (jit.lower(...).compile() against the live state avals — traces and
    # compiles WITHOUT dispatching, so warmup needs no synthetic prompts,
    # no page allocations, and no prefix-index/host-store rollbacks).

    def _make_step_jit(self, n_steps: int):
        if self.paged:
            return jax.jit(
                lambda p, s, t: self._step_impl(p, s, n_steps, t),
                donate_argnums=(1,),
            )
        return jax.jit(
            lambda p, s: self._step_impl(p, s, n_steps),
            donate_argnums=(1,),
        )

    def _make_unified_jit(self, max_steps: int):
        if self.paged:
            return jax.jit(
                lambda p, s, t, n: self._unified_impl(p, s, n, max_steps, t),
                donate_argnums=(1,),
            )
        return jax.jit(
            lambda p, s, n: self._unified_impl(p, s, n, max_steps),
            donate_argnums=(1,),
        )

    def _make_masked_jit(self):
        if self.paged:
            return jax.jit(
                lambda p, s, t, m: self._step_impl(p, s, 1, t, m),
                donate_argnums=(1,),
            )
        return jax.jit(
            lambda p, s, m: self._step_impl(p, s, 1, None, m),
            donate_argnums=(1,),
        )

    def _make_jump_jit(self):
        if self.paged:
            return jax.jit(
                lambda p, s, t, f, c: self._jump_impl(p, s, f, c, t),
                donate_argnums=(1,),
            )
        return jax.jit(
            lambda p, s, f, c: self._jump_impl(p, s, f, c),
            donate_argnums=(1,),
        )

    def _make_mega_jit(self, max_ticks: int):
        if self.paged:
            return jax.jit(
                lambda p, s, t, n, st_, b, a: self._mega_impl(
                    p, s, n, st_, b, a, max_ticks, t
                ),
                donate_argnums=(1,),
            )
        return jax.jit(
            lambda p, s, n, st_, b, a: self._mega_impl(
                p, s, n, st_, b, a, max_ticks
            ),
            donate_argnums=(1,),
        )

    def _make_spec_jit(self, key: Tuple[int, int, int]):
        if self.paged:
            return jax.jit(
                lambda p, s, t: self._spec_impl(p, s, *key, tables=t),
                donate_argnums=(1,),
            )
        return jax.jit(
            lambda p, s: self._spec_impl(p, s, *key),
            donate_argnums=(1,),
        )

    def _make_draft_spec_jit(self, key: Tuple[int, int, int]):
        if self.paged:
            return jax.jit(
                lambda p, dp, s, ds, t: self._draft_spec_impl(
                    p, dp, s, ds, *key, tables=t
                ),
                donate_argnums=(2, 3),
            )
        return jax.jit(
            lambda p, dp, s, ds: self._draft_spec_impl(p, dp, s, ds, *key),
            donate_argnums=(2, 3),
        )

    def _make_draft_ingest_jit(self, width: int):
        return jax.jit(
            lambda dp, ds, h, tl, act, tm: self._draft_ingest_impl(
                dp, ds, h, tl, act, tm, width
            ),
            donate_argnums=(1,),
        )

    def _make_prefill_jit(self):
        impl = self._prefill_impl_paged if self.paged else self._prefill_impl
        return jax.jit(impl, donate_argnums=(1,))

    def _make_seq_prefill_jit(self):
        """Sequence-sharded whole-prompt prefill: ``_prefill_impl_paged``
        with the ring/Ulysses attention closed over — the forward's
        sequence axis shards over the mesh's sp axis, everything else
        (pool scatter, sample, activate) is the normal paged prefill."""
        attn = self._seq_attn
        return jax.jit(
            lambda p, s, t, sl, tl, tm, tp_, row: self._prefill_impl_paged(
                p, s, t, sl, tl, tm, tp_, row, attn_fn=attn
            ),
            donate_argnums=(1,),
        )

    def _make_chunk_jit(self, final: bool):
        impl = self._final_chunk_impl if final else self._prefill_chunk_impl
        return jax.jit(impl, donate_argnums=(1,))

    @staticmethod
    def _make_hist_jit():
        def impl(state, tokens, slot, start):
            new = dict(state)
            new["history"] = jax.lax.dynamic_update_slice(
                state["history"], tokens, (slot, start)
            )
            return new

        return jax.jit(impl, donate_argnums=(0,))

    # -- AOT compilation (warmup / readiness gate) --------------------------

    def _compile_aot(self, kind: str, store: Dict, key, jitfn,
                     example_args) -> None:
        """AOT-compile one graph against the live avals of
        ``example_args`` and store the compiled executable where the
        dispatch path looks it up. lower()+compile() traces but never
        executes — no device state moves, nothing donates — so the whole
        serving surface can warm behind the readiness gate in compile
        time alone. Counts the same compile-event accounting a lazy
        first dispatch would; if this backend combination cannot AOT-
        lower the graph, fall back to the lazy instrumented wrapper (the
        first real dispatch then compiles, visibly)."""
        if key in store:
            return
        t0 = time.perf_counter()
        try:
            fn = jitfn.lower(*example_args).compile()
        except Exception:  # noqa: BLE001 - lazy compile still serves
            log.exception(
                "AOT lowering failed for %s graph %r; deferring to "
                "first-dispatch compile", kind, key,
            )
            store[key] = self._instrument_compile(jitfn, kind)
            return
        dt = time.perf_counter() - t0
        obs.ENGINE_XLA_COMPILES.labels(model=self.cfg.name, kind=kind).inc()
        self.compile_events += 1
        self.compile_seconds += dt
        obs.ENGINE_XLA_COMPILE_SECONDS.labels(
            model=self.cfg.name, kind=kind
        ).observe(dt)
        if self._devprof is not None:
            # ledger registration: the compiled executable's static
            # cost_analysis (FLOPs + bytes per dispatch) + compile time,
            # under the same (kind, key) the dispatch path notes —
            # metadata only, no device state moves
            self._devprof.register(kind, key, fn, dt)
        store[key] = fn

    def _step_example(self) -> tuple:
        if self.paged:
            return (self.params, self.state, self._tables_operand())
        return (self.params, self.state)

    def compile_step_fn(self, n_steps: int) -> None:
        """Ensure the ``n_steps`` decode graph exists WITHOUT dispatching
        (the batcher calls this for its chunk sizes when it attaches to a
        warmed engine; warmup calls it for every serving step size)."""
        if self.unified_step:
            self._unified_fn(n_steps, aot=True)
        elif n_steps not in self._step_fns:
            self._compile_aot(
                "step", self._step_fns, n_steps,
                self._make_step_jit(n_steps), self._step_example(),
            )

    def compile_masked_fn(self) -> None:
        if "masked" in self._step_fns:
            return
        mask = jnp.zeros((self.num_slots, self.cfg.vocab_size), jnp.float32)
        self._compile_aot(
            "masked", self._step_fns, "masked", self._make_masked_jit(),
            self._step_example() + (mask,),
        )

    def compile_spec_fn(self, n_rounds: int, draft_len: int,
                        ngram: int) -> None:
        key = (n_rounds, draft_len, ngram)
        if key in self._spec_fns or not self.spec_supported \
                or not self.track_history:
            return
        self._compile_aot(
            "spec", self._spec_fns, key, self._make_spec_jit(key),
            self._step_example(),
        )

    def compile_draft_spec_fn(self, n_rounds: int, draft_len: int) -> None:
        """Ensure the fused draft-propose + verify graph for
        ``n_rounds`` rounds exists WITHOUT dispatching (warmup and the
        batcher attach call this for the batcher's actual dispatch
        sizes, keeping the flat-compile-counters invariant). No-op when
        no draft model is attached."""
        if self.draft is None:
            return
        key = (n_rounds, draft_len, draft_len + 1)
        if key in self._draft_fns:
            return
        self._compile_aot(
            "draft_spec", self._draft_fns, key,
            self._make_draft_spec_jit(key),
            (self.params, self.draft.params, self.state, self.draft_state)
            + ((self._tables_operand(),) if self.paged else ()),
        )

    def compile_draft_ingest_fns(self) -> None:
        """Ensure every bulk draft-ingest bucket graph exists WITHOUT
        dispatching; no-op without a draft model."""
        if self.draft is None:
            return
        for w in self._draft_ingest_buckets():
            key = ("ingest", w)
            if key in self._draft_fns:
                continue
            self._compile_aot(
                "draft_ingest", self._draft_fns, key,
                self._make_draft_ingest_jit(w),
                (self.draft.params, self.draft_state,
                 self.state["history"], self.state["lengths"],
                 self.state["active"], self.state["temps"]),
            )

    def _draft_ingest_buckets(self) -> Tuple[int, ...]:
        bs = tuple(b for b in DRAFT_INGEST_BUCKETS if b <= self.max_context)
        return bs or DRAFT_INGEST_BUCKETS[:1]

    def compile_jump_fn(self, k_bucket: int) -> None:
        """Ensure the ``k_bucket``-run jump-ahead graph exists WITHOUT
        dispatching (warmup and the batcher attach call this for every
        JUMP_BUCKETS size so a constrained tick never compiles
        mid-serving). No-op where jump dispatches are unsupported (the
        dp-replicated pool, like speculative verify)."""
        if k_bucket in self._jump_fns or not self.spec_supported:
            return
        args = [self.params, self.state]
        if self.paged:
            args.append(self._tables_operand())
        args += [
            jnp.zeros((self.num_slots, k_bucket), jnp.int32),
            jnp.zeros((self.num_slots,), jnp.int32),
        ]
        self._compile_aot(
            "jump", self._jump_fns, k_bucket, self._make_jump_jit(),
            tuple(args),
        )

    def mega_bucket(self, n: int) -> int:
        """The power-of-two megagraph bucket serving an ``n``-tick
        window (the smallest compiled K >= n; the dispatch passes the
        true n as a dynamic operand)."""
        m = 1
        while m < n:
            m *= 2
        return m

    def compile_mega_fn(self, k_bucket: int) -> None:
        """Ensure the ``k_bucket``-tick megagraph exists WITHOUT
        dispatching (warmup compiles every power-of-two bucket up to
        ``mega_ticks``; the batcher attach calls this for its own
        window sizes — the flat-compile-counters invariant). No-op when
        the megagraph is disarmed (``mega_ticks`` = 0)."""
        if k_bucket in self._mega_fns or not self.mega_ticks:
            return
        args = [self.params, self.state]
        if self.paged:
            args.append(self._tables_operand())
        args += [
            jnp.int32(k_bucket),
            jnp.full((self.num_slots, MEGA_STOP_SLOTS), -1, jnp.int32),
            jnp.zeros((self.num_slots,), jnp.int32),
            jnp.int32(k_bucket),
        ]
        self._compile_aot(
            "mega", self._mega_fns, k_bucket,
            self._make_mega_jit(k_bucket), tuple(args),
        )

    def compile_prefill_fn(self, bucket: int) -> None:
        if bucket in self._prefill_fns:
            return
        args = (
            self.params, self.state, jnp.zeros((1, bucket), jnp.int32),
            jnp.int32(0), jnp.int32(1), jnp.float32(0.0), jnp.float32(1.0),
        )
        if self.paged:
            args = args + (jnp.asarray(self.allocator.tables[0]),)
        self._compile_aot(
            "prefill", self._prefill_fns, bucket, self._make_prefill_jit(),
            args,
        )

    def compile_seq_prefill_fn(self, bucket: int) -> None:
        """Ensure the sequence-sharded prefill graph for ``bucket`` exists
        WITHOUT dispatching (warmup calls this for every bucket the
        routing floor + pool can reach, keeping the flat-compile-counters
        invariant). No-op where seq-sharded prefill is disarmed."""
        if self._seq_attn is None or bucket in self._seq_prefill_fns:
            return
        args = (
            self.params, self.state, jnp.zeros((1, bucket), jnp.int32),
            jnp.int32(0), jnp.int32(1), jnp.float32(0.0), jnp.float32(1.0),
            jnp.asarray(self.allocator.tables[0]),
        )
        self._compile_aot(
            "seq_prefill", self._seq_prefill_fns, bucket,
            self._make_seq_prefill_jit(), args,
        )

    def compile_chunk_fn(self, bucket: int, final: bool) -> None:
        key = (bucket, final)
        if key in self._chunk_fns:
            return
        args = [
            self.params, self.state, jnp.zeros((1, bucket), jnp.int32),
            jnp.int32(0), jnp.int32(0),
        ]
        if final:
            args += [jnp.int32(1), jnp.int32(1), jnp.float32(0.0),
                     jnp.float32(1.0)]
        if self.paged:
            args.append(jnp.asarray(self.allocator.tables[0]))
            if self.kv_compress_armed:
                # armed engines' chunk graphs carry the slot's live-window
                # start (a prompt can cross the compression threshold
                # mid-admission)
                args.append(jnp.int32(0))
        self._compile_aot(
            "chunk", self._chunk_fns, key, self._make_chunk_jit(final),
            tuple(args),
        )

    def compile_hist_fn(self, bucket: int) -> None:
        key = ("hist", bucket)
        if key in self._prefill_fns:
            return
        args = (
            self.state, jnp.zeros((1, bucket), jnp.int32), jnp.int32(0),
            jnp.int32(0),
        )
        self._compile_aot("hist", self._prefill_fns, key,
                          self._make_hist_jit(), args)

    def compile_restore_fn(self, nb: int) -> None:
        if nb in self._restore_fns or not self.paged:
            return
        cfg, P = self.cfg, self.allocator.page_size
        z = jnp.zeros(
            (cfg.num_layers, nb, P, cfg.num_kv_heads, cfg.head_dim),
            self.state["k"].dtype,
        )
        args = [self.state, z, z]
        if self.quant_cache:
            s = jnp.zeros((cfg.num_layers, nb, P, cfg.num_kv_heads),
                          jnp.float32)
            args += [s, s]
        args.append(jnp.zeros((nb,), jnp.int32))
        self._compile_aot(
            "restore", self._restore_fns, nb, self._make_restore_jit(),
            tuple(args),
        )

    # -- lazy getters (unwarmed engines compile on first dispatch) ----------

    def _step_fn(self, n_steps: int):
        fn = self._step_fns.get(n_steps)
        if fn is None:
            fn = self._instrument_compile(self._make_step_jit(n_steps), "step")
            self._step_fns[n_steps] = fn
        return fn

    def _unified_fn(self, n_steps: int, aot: bool = False):
        """The dynamic-n decode graph serving ``n_steps`` (unified_step
        mode): one graph per power-of-two max-steps bound, grown on
        demand. Returns (fn, max_steps)."""
        m = self._unified_max
        if m < n_steps:
            m = 1
            while m < n_steps:
                m *= 2
        key = ("uni", m)
        fn = self._step_fns.get(key)
        if fn is None:
            jitfn = self._make_unified_jit(m)
            if aot:
                self._compile_aot(
                    "step", self._step_fns, key, jitfn,
                    self._step_example() + (jnp.int32(1),),
                )
                fn = self._step_fns[key]
            else:
                fn = self._instrument_compile(jitfn, "step")
                self._step_fns[key] = fn
            self._unified_max = m
        return fn, m

    def _mega_fn(self, n_ticks: int):
        """The megagraph serving an ``n_ticks`` window: the power-of-two
        bucket >= n_ticks, compiled lazily on an unwarmed engine.
        Returns (fn, bucket)."""
        m = self.mega_bucket(n_ticks)
        fn = self._mega_fns.get(m)
        if fn is None:
            fn = self._instrument_compile(self._make_mega_jit(m), "mega")
            self._mega_fns[m] = fn
        return fn, m

    def _masked_step_fn(self):
        """1-step decode with an additive per-slot logits mask (grammar-
        constrained decoding); same donated state contract as _step_fn."""
        fn = self._step_fns.get("masked")
        if fn is None:
            fn = self._instrument_compile(self._make_masked_jit(), "masked")
            self._step_fns["masked"] = fn
        return fn

    def _prefill_fn(self, bucket: int):
        fn = self._prefill_fns.get(bucket)
        if fn is None:
            fn = self._instrument_compile(self._make_prefill_jit(), "prefill")
            self._prefill_fns[bucket] = fn
        return fn

    def _seq_prefill_fn(self, bucket: int):
        fn = self._seq_prefill_fns.get(bucket)
        if fn is None:
            fn = self._instrument_compile(
                self._make_seq_prefill_jit(), "seq_prefill"
            )
            self._seq_prefill_fns[bucket] = fn
        return fn

    def _spec_fn(self, n_rounds: int, draft_len: int, ngram: int):
        key = (n_rounds, draft_len, ngram)
        fn = self._spec_fns.get(key)
        if fn is None:
            fn = self._instrument_compile(self._make_spec_jit(key), "spec")
            self._spec_fns[key] = fn
        return fn

    def _draft_spec_fn(self, n_rounds: int, draft_len: int):
        key = (n_rounds, draft_len, draft_len + 1)
        fn = self._draft_fns.get(key)
        if fn is None:
            fn = self._instrument_compile(
                self._make_draft_spec_jit(key), "draft_spec"
            )
            self._draft_fns[key] = fn
        return fn

    def _draft_ingest_fn(self, width: int):
        key = ("ingest", width)
        fn = self._draft_fns.get(key)
        if fn is None:
            fn = self._instrument_compile(
                self._make_draft_ingest_jit(width), "draft_ingest"
            )
            self._draft_fns[key] = fn
        return fn

    def _jump_fn(self, k_bucket: int):
        fn = self._jump_fns.get(k_bucket)
        if fn is None:
            fn = self._instrument_compile(self._make_jump_jit(), "jump")
            self._jump_fns[k_bucket] = fn
        return fn

    def _chunk_fn(self, bucket: int, final: bool):
        key = (bucket, final)
        fn = self._chunk_fns.get(key)
        if fn is None:
            fn = self._instrument_compile(self._make_chunk_jit(final), "chunk")
            self._chunk_fns[key] = fn
        return fn

    def _hist_fn(self, bucket: int):
        key = ("hist", bucket)
        fn = self._prefill_fns.get(key)
        if fn is None:
            fn = self._make_hist_jit()
            self._prefill_fns[key] = fn
        return fn

    def _write_history(self, slot: int, ids: List[int], start: int = 0) -> None:
        """Backfill history cols [start, start+len(ids)) in bucket-sized
        dispatches (a matched prefix can exceed the largest bucket)."""
        pos = 0
        while pos < len(ids):
            seg = ids[pos : pos + self.buckets[-1]]
            bucket = self.bucket_for(len(seg))
            padded = np.zeros((1, bucket), dtype=np.int32)
            padded[0, : len(seg)] = seg
            self._devprof_note("hist", ("hist", bucket))
            self.state = self._hist_fn(bucket)(
                self.state, jnp.asarray(padded), jnp.int32(slot),
                jnp.int32(start + pos),
            )
            pos += len(seg)

    def _maybe_compress(self, slot: int, length: Optional[int] = None) -> None:
        """Window+sink KV compression (caller holds the engine lock):
        once ``slot``'s length exceeds the threshold, release the page
        range between the sink pages and the trailing window back to the
        pool and advance the slot's live-window start — the mask operand
        every subsequent dispatch reads. Pages shared with the prefix
        index keep their index references (and spill through the host
        tier under pressure like any cold prefix page); only this slot's
        references drop. Monotone: the window start never rewinds.
        ``length`` is passed explicitly by mid-admission callers (the
        slot is not active yet and its host length is still 0)."""
        if not self.kv_compress_armed:
            return
        if length is None:
            if not self.active[slot]:
                return
            L = int(self._host_lengths[slot])
        else:
            L = int(length)
        if L <= self.kv_compress_after:
            return
        P = self.allocator.page_size
        # last block fully below the trailing window [L - window_rows, L]
        wb = (L - self.kv_window_pages * P) // P
        if wb <= self.kv_sink_pages:
            return
        if self._win_starts[slot] == 0:
            self.kv_compress_slots += 1
            flightrec.RECORDER.model_event(
                self.cfg.name, "kv_compress", slot=slot, length=L,
            )
        freed = self.allocator.prune_range(slot, self.kv_sink_pages, wb)
        self.kv_pages_pruned += freed
        self._win_starts[slot] = wb * P

    def _back_active_slots(self, grow_rows: int) -> None:
        """Back every active slot's next ``grow_rows`` rows BEFORE a paged
        dispatch (PoolExhausted surfaces with state untouched so the
        batcher can retire a victim and retry); windowed models first
        return pages attention can no longer reach, and compression-armed
        engines prune past-threshold slots to sink + window. Caller holds
        the engine lock."""
        for s in range(self.num_slots):
            if self.active[s]:
                if self.cfg.sliding_window is not None:
                    self.allocator.trim_below_window(
                        s,
                        int(self._host_lengths[s]),
                        self.cfg.sliding_window,
                    )
                self._maybe_compress(s)
                self.allocator.ensure(
                    s,
                    min(
                        int(self._host_lengths[s]) + grow_rows,
                        self.max_context,
                    ),
                )

    # -- prefix caching (paged engines; paged.PrefixIndex) ------------------
    # -- + host spill tier (paged.HostPageStore) ----------------------------

    def _spill_pages(self, evicted) -> None:
        """PrefixIndex eviction hook: capture the evicted pages' KV with a
        device-side gather, then hand the copies to the spill worker —
        the device->host transfer and store insert run off the lock.

        The gather is BLOCKED until its buffers materialize, under the
        engine lock: the pages free (and can be rewritten) the moment
        this hook returns, and the pool buffer must be clean to donate to
        the next dispatch — so the lock pays for the in-flight dispatch
        queue draining plus the gather itself. That cost lands only on
        eviction paths (pool-pressure admissions and index overflow),
        where the alternative was a full prefill recompute anyway."""
        if self.host_store is None or self._spill_q is None:
            return
        with self._spill_lock:
            if self._spill_pending + len(evicted) > self._spill_max_pending:
                pending = self._spill_pending
            else:
                pending = -1
                self._spill_pending += len(evicted)
        if pending >= 0:
            # the worker is behind an eviction burst: drop this spill
            # BEFORE enqueuing the gather (pending batches pin device
            # memory) — the evicted pages degrade to plain eviction
            log.warning(
                "host-tier spill backlog at %d pages; dropping %d page(s)",
                pending, len(evicted),
            )
            return
        try:
            # aios: waive(lock-readback): host-side page-id list, no device sync
            pages = np.asarray([p for _, p in evicted], np.int32)
            arrs = [self.state["k"][:, pages], self.state["v"][:, pages]]
            if self.quant_cache:
                arrs.append(self.state["k_s"][:, pages])
                arrs.append(self.state["v_s"][:, pages])
            # aios: waive(lock-readback): PR-4 contract — the gather must materialize under the engine lock; the evicted pages free (and can be rewritten by the next donated dispatch) the moment this hook returns
            jax.block_until_ready(arrs)
        except BaseException:
            # a failed gather (e.g. RESOURCE_EXHAUSTED materializing the
            # copies on a full chip) must give its reservation back, or
            # the leaked count eventually pins the backlog gate shut and
            # silently disables the tier; _drop's handler degrades this
            # eviction to a plain one
            with self._spill_lock:
                self._spill_pending -= len(evicted)
            raise
        self._spill_q.put(([h for h, _ in evicted], arrs))
        # flight-recorder model lane: spills belong to the MODEL's story
        # (pressure from whichever request forced the eviction), not to
        # one request's timeline — /debug/trace renders them on tid 0
        flightrec.RECORDER.model_event(
            self.cfg.name, "spill", pages=len(evicted)
        )

    @staticmethod
    def _spill_worker(q, store, lock, eng_ref) -> None:
        """Daemon loop: device->host copies + HostPageStore inserts for
        spilled pages. Best-effort — a failed spill degrades that
        eviction to the pre-host-tier behavior (KV lost, recompute on the
        next hit), never corrupts. Static on purpose: the thread owns
        only the queue/store/lock (a close() that times out on a deep
        backlog must not crash it mid-drain) and reaches the pending
        counter through ``eng_ref``, so an engine dropped WITHOUT close()
        stays collectible — the periodic get() timeout notices the dead
        weakref and exits."""
        import queue as _queue

        keys = ("k", "v", "k_s", "v_s")
        while True:
            try:
                item = q.get(timeout=60)
            except _queue.Empty:
                if eng_ref() is None:
                    return  # engine collected without close(); wind down
                continue
            if item is None:
                return
            hashes, arrs = item
            try:
                host = [np.asarray(a) for a in arrs]
                for i, h in enumerate(hashes):
                    store.put(h, {
                        k: np.ascontiguousarray(host[j][:, i])
                        for j, k in enumerate(keys[: len(host)])
                    })
            except Exception:  # noqa: BLE001 - spill is best-effort
                log.exception("host-tier spill worker failed")
            finally:
                eng = eng_ref()
                if eng is not None:
                    with lock:
                        eng._spill_pending -= len(hashes)

    def _restore_fn(self, bucket: int):
        """Jitted per-layer pool scatter for a host-tier restore of up to
        ``bucket`` pages. Power-of-two buckets bound the compile count;
        pad entries land on the sacrificial page 0, which is never read.

        Deliberately NOT donated: a restore fires under the same HBM
        pressure that evicted the pages, and a dispatch-time failure of a
        donating call can consume the state buffers first — wedging every
        later dispatch on 'Array has been deleted', strictly worse than
        the transient pool copy the undonated scatter pays. A failure
        here instead leaves ``self.state`` intact and the caller falls
        back to normal prefill."""
        fn = self._restore_fns.get(bucket)
        if fn is None:
            fn = self._instrument_compile(self._make_restore_jit(), "restore")
            self._restore_fns[bucket] = fn
        return fn

    def _make_restore_jit(self):
        if self.quant_cache:
            def impl(state, kh, vh, ksh, vsh, pages):
                new = dict(state)
                new["k"] = state["k"].at[:, pages].set(kh)
                new["v"] = state["v"].at[:, pages].set(vh)
                new["k_s"] = state["k_s"].at[:, pages].set(ksh)
                new["v_s"] = state["v_s"].at[:, pages].set(vsh)
                return new
        else:
            def impl(state, kh, vh, pages):
                new = dict(state)
                new["k"] = state["k"].at[:, pages].set(kh)
                new["v"] = state["v"].at[:, pages].set(vh)
                return new
        return jax.jit(impl)

    def _restore_from_host(self, slot: int, entries, lead_hashes=(),
                           lead_pages=()) -> List[int]:
        """Allocate landing pages for a host-tier chain hit, scatter the
        stored KV back into the pool, map the pages as ``slot``'s next
        logical blocks, and re-register their hashes in the HBM index.
        Returns the new pages — empty when the pool cannot back them
        (the caller falls back to normal prefill; nothing was touched).
        Caller holds the engine lock; the scatter dispatch is async, so
        the copy-in overlaps the request's tail-prefill chunking (any
        later read orders after it through the state data dependency)."""
        # clamp the chain to what the pool can PLAUSIBLY back before
        # allocating: an uncapped alloc_pages would first evict (and
        # blocking-gather) cold HBM prefix entries via the reclaimer,
        # then fail on the remaining shortfall anyway — paying the
        # eviction thrash for a restore that never happens. Truncation
        # keeps a chain prefix, which is still a valid restore.
        avail = self.allocator.free_pages_for(slot) \
            + self.prefix_index.reclaimable()
        if len(entries) > avail:
            entries = entries[:avail]
            if len(entries) < self.host_restore_min_pages:
                return []
        try:
            pages = self.allocator.alloc_pages(len(entries))
        except paged.PoolExhausted:
            return []
        t0 = time.perf_counter()
        n = len(pages)
        nb = 1
        while nb < n:
            nb *= 2
        pad = np.zeros(nb, np.int32)  # pad rows -> sacrificial page 0
        pad[:n] = pages

        def stacked(key):
            a = np.stack([e[key] for _, e in entries], axis=1)
            if nb > n:
                shape = list(a.shape)
                shape[1] = nb - n
                a = np.concatenate(
                    [a, np.zeros(shape, a.dtype)], axis=1
                )
            return jnp.asarray(a)

        # restore samples are submit-side by design: the scatter is
        # deliberately async (it overlaps the tail prefill), so the
        # sample covers staging + dispatch, like the restore histogram
        dtok = self._devprof_note("restore", nb)
        try:
            act = faults.point("host_store.restore_fail", self.cfg.name)
            if act is not None:
                # chaos: the restore dies mid-flight — recovery is the
                # REAL fallback below (pages returned, normal prefill)
                raise faults.InjectedFault(
                    f"injected restore failure (hit {act.hit})"
                )
            args = [stacked("k"), stacked("v")]
            if self.quant_cache:
                args += [stacked("k_s"), stacked("v_s")]
            self.state = self._restore_fn(nb)(
                self.state, *args, jnp.asarray(pad)
            )
        except BaseException:
            # staging or the scatter dispatch failed (a restore fires
            # exactly under the HBM pressure that evicted these pages, so
            # RESOURCE_EXHAUSTED here is plausible): give the allocated
            # pages back — leaking them at refcount 1 would shrink the
            # pool forever — and fall back to normal prefill. The probe
            # counted a hit; the restore never happened, so the store
            # records a miss too (the ratio predicts recompute cost).
            for p in pages:
                self.allocator.decref(p)
            if self.host_store is not None:
                self.host_store.note_failed_restore()
            log.exception(
                "host-tier restore failed; recomputing %d page(s)", n
            )
            return []
        dt = time.perf_counter() - t0
        self.host_restore_seconds += dt
        if self._obs_restore_hist is not None:
            self._obs_restore_hist.observe(dt)
        self._devprof_sample(dtok)
        self.allocator.append_owned(slot, pages)
        hashes = [h for h, _ in entries]
        # back in HBM: re-register so the NEXT prompt maps these pages
        # directly, and drop the host copies (they respill on eviction).
        # The lead (HBM-matched) part of the chain rides along so the
        # radix index can graft the restored segment at its true tree
        # position — a mid-chain insert has no meaning in a tree (the
        # flat index just LRU-refreshes the already-present lead).
        self.prefix_index.put(
            list(lead_hashes) + hashes, list(lead_pages) + pages
        )
        self.host_store.discard(hashes, restored=True)
        self.prefix_rows_restored += n * self.allocator.page_size
        flightrec.RECORDER.model_event(
            self.cfg.name, "restore", pages=n,
            rows=n * self.allocator.page_size,
        )
        return pages

    def _match_prefix(self, slot: int, ids: List[int]):
        """Map the longest hash-matched prompt prefix into ``slot``'s page
        table and backfill its token history. HBM-resident blocks map as
        shared read-only pages (zero compute, zero new pages); when the
        hash chain continues into the host spill tier — and the run
        clears ``host_restore_min_pages`` — fresh pages are allocated and
        the stored KV scatters back in: a memcpy instead of a prefill
        forward pass. Restored pages get the same read-only guarantee by
        the same construction (matches cap at the prompt's last full
        block minus one row, so every tail/decode write lands past them).
        Returns (matched_rows, block_hashes). Caller holds the engine
        lock.

        matched_rows is page-aligned but NOT chunk-aligned — the tail's
        chunk starts inherit the misalignment, which the chunk writers are
        built for (prefill_chunk_paged's sacrificial-page slice padding,
        _chunk_history's clamped scatter)."""
        if self.prefix_index is None:
            return 0, []
        P = self.allocator.page_size
        full = (len(ids) - 1) // P  # cap: at least one tail row remains
        if full <= 0:
            return 0, []
        hashes = paged.chain_hashes(ids, P, full)
        pages = self.prefix_index.match(hashes)
        entries = []
        if self.host_store is not None and len(pages) < full:
            entries = self.host_store.match_chain(hashes[len(pages) :])
            if len(entries) < self.host_restore_min_pages:
                entries = []  # below the floor: recompute beats device_put
        if not pages and not entries:
            return 0, hashes
        if pages:
            # map the HBM hits FIRST: their index references alone are
            # reclaimable (refcount 1), so taking the slot reference
            # before the restore's alloc_pages keeps a pressure-reclaim
            # from freeing the very pages this prompt just matched
            self.allocator.map_shared(slot, pages)
            self.prefix_rows_reused += len(pages) * P
        restored = (
            self._restore_from_host(
                slot, entries, hashes[: len(pages)], pages
            )
            if entries else []
        )
        matched = (len(pages) + len(restored)) * P
        if not matched:
            return 0, hashes
        # the n-gram proposer reads history[0:length] — backfill the
        # shared region (padding past `matched` inside the last segment's
        # bucket is overwritten by the tail chunks writing [matched, len))
        self._write_history(slot, ids[:matched])
        return matched, hashes

    def _register_prefix(self, slot: int, ids: List[int], hashes) -> None:
        """After a successful admission, publish the slot's fully-covered
        prompt blocks to the index so the NEXT prompt with this prefix
        skips their prefill. Caller holds the engine lock."""
        if self.prefix_index is None or not hashes:
            return
        if int(self.allocator._trimmed[slot]):
            # sliding-window trimming released leading blocks during this
            # admission; their table entries are stale and a prefix chain
            # must start at block 0 — nothing registrable
            return
        if int(self.allocator._pruned_hi[slot]):
            # window+sink pruning released the middle during this
            # admission; the sink pages are still a valid (short) chain
            # prefix, the rest maps the sacrificial page
            hashes = hashes[: int(self.allocator._pruned_lo[slot])]
            if not hashes:
                return
        pages = [int(self.allocator.tables[slot, b]) for b in range(len(hashes))]
        self.prefix_index.put(hashes, pages)

    def prefix_hashes(self, token_ids: List[int]) -> List[bytes]:
        """Chain hashes of the prompt's full blocks, truncated exactly as
        admission truncates — computed ONCE per request by the serving
        pool and shared across its replicas' overlap probes (replicas of
        one model share page size and truncation)."""
        if self.prefix_index is None:
            return []
        ids = list(token_ids)[-(self.max_context - 1) :]
        P = self.allocator.page_size
        full = (len(ids) - 1) // P
        if full <= 0:
            return []
        return paged.chain_hashes(ids, P, full)

    def prefix_overlap_rows(self, token_ids: List[int],
                            hashes: Optional[List[bytes]] = None) -> int:
        """How many leading prompt rows this engine's prefix cache already
        holds — the serving router's cache-aware score. Read-only: no
        hit/miss counters move, no LRU refresh, no pages map (scoring N
        replicas per request must not perturb the index), and it takes
        only the index's (and host store's) own locks — never the
        dispatch lock, so a replica mid-dispatch (or mid-compile) cannot
        stall routing. Rows resident only in the host spill tier count at
        ``paged.HOST_OVERLAP_DISCOUNT`` — routing still prefers true HBM
        residency but credits a replica that can restore the prefix with
        a memcpy over one that must recompute it. 0 on non-paged engines
        or when no full block matches."""
        if self.prefix_index is None:
            return 0
        if hashes is None:
            hashes = self.prefix_hashes(token_ids)
        if not hashes:
            return 0
        P = self.allocator.page_size
        n_hbm = self.prefix_index.peek(hashes)
        rows = n_hbm * P
        if self.host_store is not None and n_hbm < len(hashes):
            n_host = self.host_store.peek_chain(hashes[n_hbm:])
            if n_host >= self.host_restore_min_pages:
                rows += int(n_host * P * paged.HOST_OVERLAP_DISCOUNT)
        return rows

    # -- fleet data plane (aios_tpu/fleet/) ---------------------------------

    def export_prefix(self, token_ids: List[int], max_pages: int = 0):
        """Device->host copy of the longest HBM-resident chain prefix of
        the prompt — the transfer plane's push-on-prefill source.
        Returns ``[(hash, entry)]`` in the HostPageStore entry layout
        (the receiver ``put``s them straight into its host tier, and its
        next ``_match_prefix`` restores them with a scatter instead of a
        prefill). Empty on non-paged engines or when no full block is
        resident.

        Lock discipline mirrors ``_spill_pages``: the gather must
        MATERIALIZE under the engine lock — the matched pages can be
        evicted and rewritten by the next dispatch the moment it
        releases — so the lock pays for the gather; the device->host
        copies then run outside it on the caller's (transfer) thread."""
        return self.export_hashes(self.prefix_hashes(token_ids), max_pages)

    def export_hashes(self, hashes: List[bytes], max_pages: int = 0):
        """Hash-keyed flavor of :meth:`export_prefix` — the transfer
        servicer's ``Fetch`` path, where the puller sends chain hashes,
        not token ids. Same return shape and lock discipline."""
        if self.prefix_index is None or not hashes:
            return []
        with self._lock:
            snap = self.prefix_index.snapshot()
            chain = []
            for h in hashes:
                page = snap.get(h)
                if page is None:
                    break
                chain.append((h, page))
            if max_pages:
                chain = chain[:max_pages]
            if not chain:
                return []
            # aios: waive(lock-readback): host-side page-id list, no device sync
            pages = np.asarray([p for _, p in chain], np.int32)
            arrs = [self.state["k"][:, pages], self.state["v"][:, pages]]
            if self.quant_cache:
                arrs.append(self.state["k_s"][:, pages])
                arrs.append(self.state["v_s"][:, pages])
            # aios: waive(lock-readback): _spill_pages contract — the gather must materialize before the lock releases, or the exported pages could be rewritten by the next donated dispatch mid-copy
            jax.block_until_ready(arrs)
        keys = ("k", "v", "k_s", "v_s")
        host = [np.asarray(a) for a in arrs]
        return [
            (
                h,
                {
                    k: np.ascontiguousarray(host[j][:, i])
                    for j, k in enumerate(keys[: len(host)])
                },
            )
            for i, (h, _) in enumerate(chain)
        ]

    def prefix_digest(self, max_tails: int = 256) -> Dict[str, int]:
        """Bounded digest of this engine's cached chains for the
        gossiped fleet prefix index: truncated-hex chain hash ->
        depth-in-blocks (0 = depth unknown). HBM entries first (they
        are the cheap hits), then host-tier hashes into whatever of the
        cap remains. 64-bit truncation keeps heartbeats small; a
        collision can only misroute — the transfer then misses and the
        request falls back to local prefill."""
        if self.prefix_index is None:
            return {}
        out: Dict[str, int] = {}
        for h, blocks in self.prefix_index.digest(max_tails):
            out[h.hex()[:16]] = blocks
        if self.host_store is not None and len(out) < max_tails:
            for h in self.host_store.stored_hashes(max_tails - len(out)):
                out.setdefault(h.hex()[:16], 0)
        return out

    # -- public API ---------------------------------------------------------

    def bucket_for(self, length: int) -> int:
        for b in self.buckets:
            if length <= b:
                return b
        return self.buckets[-1]

    def free_slots(self) -> List[int]:
        return [i for i in range(self.num_slots) if not self.active[i]]

    def prefill(
        self,
        slot: int,
        token_ids: List[int],
        temperature: float = 0.0,
        top_p: float = 1.0,
    ) -> int:
        """Fill ``slot`` with a prompt; returns the first generated token."""
        if not 0 <= slot < self.num_slots:
            raise ValueError(f"slot {slot} out of range")
        token_ids = list(token_ids)[-(self.max_context - 1) :]
        true_len = len(token_ids)
        if true_len == 0:
            raise ValueError("empty prompt")

        matched, hashes = 0, []
        if self.prefix_index is not None:
            with self._lock:
                matched, hashes = self._match_prefix(slot, token_ids)
        if matched:
            # tail-only admission through the chunked path, which attends
            # over the mapped prefix; release on failure so the shared
            # pages don't leak into the batcher's retry
            pc = ChunkedPrefill(
                self, slot, token_ids, temperature, top_p,
                self._prefix_chunk, start_pos=matched, hashes=hashes,
            )
            try:
                first = pc.step()
                while first is None:
                    first = pc.step()
            except BaseException:
                self.release(slot)
                raise
            return first

        if self._seq_route_ok(true_len):
            return self._seq_prefill(
                slot, token_ids, temperature, top_p, hashes
            )

        bucket = self.bucket_for(true_len)
        padded = np.zeros((1, bucket), dtype=np.int32)
        padded[0, :true_len] = token_ids

        with self._lock:
            args = [
                self.params,
                self.state,
                jnp.asarray(padded),
                jnp.int32(slot),
                jnp.int32(true_len),
                jnp.float32(temperature),
                jnp.float32(top_p),
            ]
            if self.paged:
                # back the prompt's rows NOW (raises PoolExhausted before
                # any state is touched); the bucket's padding rows beyond
                # true_len land on the sacrificial page and are never read
                self.allocator.ensure(slot, true_len)
                args.append(jnp.asarray(self.allocator.tables[slot]))
            dtok = self._devprof_note("prefill", bucket)
            self.state, first = self._prefill_fn(bucket)(*args)
            self.active[slot] = True
            self._host_greedy[slot] = temperature < sampling.GREEDY_EPS
            self._host_lengths[slot] = true_len
            self._register_prefix(slot, token_ids, hashes)
            first_token = int(first)
        # int(first) above blocked through completion, so the sample is
        # the dispatch->ready delta (landed outside the lock)
        self._devprof_sample(dtok)
        return first_token

    def _seq_route_ok(self, true_len: int) -> bool:
        """Whether a prompt of ``true_len`` rows routes through the
        sequence-sharded prefill: the path is armed, the prompt clears
        the routing floor, and the pool can in principle back the whole
        prompt at once (otherwise chunked admission — which composes
        with compression trimming — is the only admission that fits)."""
        return (
            self._seq_attn is not None
            and true_len >= self.seq_prefill_min
            and self.allocator.blocks_for(true_len)
            <= self.allocator.capacity_blocks()
        )

    def _seq_prefill(self, slot: int, ids: List[int], temperature: float,
                     top_p: float, hashes) -> int:
        """Whole-prompt prefill in ONE dispatch with the sequence sharded
        over the mesh's sp axis (parallel/ring_attention.py or
        ulysses.py): every chip works a T/sp slice of the prompt instead
        of one replica grinding chunks serially. The resulting KV lands
        in the normal paged layout (the shared ``_prefill_impl_paged``
        scatter), so decode, prefix registration, spill/restore and
        failover are indistinguishable from a chunked admission. With
        compression armed the slot prunes immediately after admission —
        before prefix registration, so only the sink chain registers."""
        true_len = len(ids)
        bucket = self.bucket_for(true_len)
        padded = np.zeros((1, bucket), dtype=np.int32)
        padded[0, :true_len] = ids
        with self._lock:
            self.allocator.ensure(slot, true_len)
            dtok = self._devprof_note("seq_prefill", bucket)
            self.state, first = self._seq_prefill_fn(bucket)(
                self.params,
                self.state,
                jnp.asarray(padded),
                jnp.int32(slot),
                jnp.int32(true_len),
                jnp.float32(temperature),
                jnp.float32(top_p),
                jnp.asarray(self.allocator.tables[slot]),
            )
            self.active[slot] = True
            self._host_greedy[slot] = temperature < sampling.GREEDY_EPS
            self._host_lengths[slot] = true_len
            self.prefill_seq_sharded += 1
            flightrec.RECORDER.model_event(
                self.cfg.name, "seq_prefill", slot=slot, rows=true_len,
            )
            self._maybe_compress(slot)
            self._register_prefix(slot, ids, hashes)
            first_token = int(first)
        self._devprof_sample(dtok)
        return first_token

    def start_chunked_prefill(
        self,
        slot: int,
        token_ids: List[int],
        temperature: float = 0.0,
        top_p: float = 1.0,
        chunk: int = 512,
    ) -> "ChunkedPrefill":
        """Begin an incremental prefill of ``slot``; the caller drives it by
        calling ``.step()`` once per chunk and may run decode dispatches for
        the other slots in between (the continuous batcher does exactly
        that). Requires ``chunk`` to be a prefill bucket dividing
        max_context so chunk writes never spill past the cache end."""
        if not 0 <= slot < self.num_slots:
            raise ValueError(f"slot {slot} out of range")
        if chunk not in self.buckets or self.max_context % chunk:
            raise ValueError(
                f"chunk {chunk} must be a prefill bucket dividing "
                f"max_context={self.max_context}"
            )
        if self.pool_replicas > 1:
            raise ValueError(
                "chunked admission is unsupported with a dp-replicated "
                "page pool (chunks read the pool during admission); use "
                "whole-prompt prefill"
            )
        ids = list(token_ids)[-(self.max_context - 1) :]
        matched, hashes = 0, []
        if self.prefix_index is not None:
            with self._lock:
                matched, hashes = self._match_prefix(slot, ids)
        if not matched and self._seq_route_ok(len(ids)):
            # the whole mesh prefills this prompt in one dispatch; the
            # driver keeps the ChunkedPrefill duck interface so the
            # batcher's admission loop (and its PoolExhausted recovery)
            # need not know which path ran
            return _SeqShardedPrefill(
                self, slot, ids, temperature, top_p, hashes
            )
        return ChunkedPrefill(
            self, slot, ids, temperature, top_p, chunk,
            start_pos=matched, hashes=hashes,
        )

    def step(self, n_steps: int = 1) -> np.ndarray:
        """Run ``n_steps`` batched decode steps in one dispatch.

        Returns tokens [n_steps, num_slots]; only columns where
        ``self.active`` are meaningful. Lengths advance for every slot
        (fixed-shape graph), clamped at the cache end.
        """
        tokens, _, _ = self._step_dispatch(n_steps)
        return tokens

    def _step_dispatch(
        self, n_steps: int, started: Optional[threading.Event] = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """The decode dispatch body: lock, graph call (donated state
        swap), host-length advance, then the blocking device->host token
        readback OUTSIDE the lock. Returns (tokens [n_steps, S] host
        array, post-dispatch host lengths). ``started`` (the step_async
        worker path) is set the moment the engine lock is held, so a
        caller can fence later engine calls behind this dispatch."""
        try:
            with self._lock:
                if started is not None:
                    started.set()
                tables = ()
                if self.paged:
                    self._back_active_slots(n_steps)
                    tables = (self._tables_operand(),)
                if self.unified_step:
                    fn, m = self._unified_fn(n_steps)
                    # worker dispatches sample only with double-buffer
                    # slack (nothing queued behind this one), so a
                    # measurement never delays the next submission
                    dtok = self._devprof_note(
                        "step", ("uni", m), need_slack=started is not None
                    )
                    self.state, tokens = fn(
                        self.params, self.state, *tables, jnp.int32(n_steps)
                    )
                else:
                    fn = self._step_fn(n_steps)
                    dtok = self._devprof_note(
                        "step", n_steps, need_slack=started is not None
                    )
                    self.state, tokens = fn(
                        self.params, self.state, *tables
                    )
                self.decode_steps += n_steps
                self._obs_decode_steps.inc(n_steps)
                self._host_lengths = np.minimum(
                    self._host_lengths + n_steps, self.max_context - 1
                )
                lengths = self._host_lengths.copy()
            host_tokens = np.asarray(tokens)[:n_steps]
            # the readback above already blocked until the tokens
            # materialized, so the sample is the graph-call -> ready
            # delta at zero extra synchronization
            sample_s = self._devprof_sample(dtok)
            return host_tokens, lengths, sample_s
        finally:
            if started is not None and self._devprof is not None:
                self._devprof.dequeue()

    def step_async(self, n_steps: int = 1) -> PendingDecode:
        """Run ``n_steps`` batched decode steps on the engine's dispatch
        worker thread and return WITHOUT blocking
        (``PendingDecode.wait()`` yields the host [n_steps, num_slots]
        array). The caller's thread is free through the whole dispatch —
        graph call AND token readback — so the pipelined continuous
        batcher (AIOS_TPU_DECODE_PIPELINE) emits/detokenizes/retires
        dispatch N's tokens while dispatch N+1 executes. A PoolExhausted
        from backing the slots surfaces at ``wait()`` with engine state
        untouched, exactly like the sync path.

        Dispatches are FIFO (single worker) and serialize with every
        other engine call through the engine lock; use
        ``wait_started()`` before issuing engine calls that must order
        AFTER this dispatch."""
        if self._dispatch_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._dispatch_pool = ThreadPoolExecutor(
                max_workers=1,
                thread_name_prefix=f"decode-dispatch-{self.cfg.name}",
            )
        started = threading.Event()
        if self._devprof is not None:
            # backlog accounting for the sampling slack check: the
            # worker only times a dispatch with nothing queued behind it
            self._devprof.enqueue()
        fut = self._dispatch_pool.submit(
            self._step_dispatch, n_steps, started
        )
        return PendingDecode(fut, n_steps, started)

    def _mega_dispatch(
        self, n_ticks: int, stops: np.ndarray, budgets: np.ndarray,
        started: Optional[threading.Event] = None,
    ) -> Tuple[np.ndarray, np.ndarray, int, Optional[float]]:
        """The megagraph dispatch body: lock, while-loop graph call
        (donated state swap), the k readback, host-length advance by the
        REAL tick count, then the token-block readback outside the lock.
        Returns (tokens [k, S], per-tick length snapshots [k, S], k,
        sample_s). Unlike ``_step_dispatch``, the scalar k readback
        blocks UNDER the engine lock — the host-length advance depends
        on it, and the CPU backend already executes the graph inline
        under the lock in ``_step_dispatch``; on TPU this serializes
        admissions behind the window's device execution (the documented
        K>1 tradeoff, docs/ENGINE_PERF.md)."""
        try:
            with self._lock:
                if started is not None:
                    started.set()
                tables = ()
                if self.paged:
                    self._back_active_slots(n_ticks)
                    tables = (self._tables_operand(),)
                abort_after = n_ticks
                act = faults.point("pool.megatick_abort", self.cfg.name)
                if act is not None and n_ticks > 1:
                    # injected host-attention demand: cap the device loop
                    # mid-window (ticks param, default half the window) —
                    # the early-exit path fires with slots still live
                    abort_after = min(
                        max(act.ticks or n_ticks // 2, 1), n_ticks - 1
                    )
                fn, m = self._mega_fn(n_ticks)
                dtok = self._devprof_note(
                    "mega", m, need_slack=started is not None
                )
                self.state, tokens, k_dev = fn(
                    self.params, self.state, *tables, jnp.int32(n_ticks),
                    jnp.asarray(stops, jnp.int32),
                    jnp.asarray(budgets, jnp.int32),
                    jnp.int32(abort_after),
                )
                k = int(k_dev)
                self.mega_dispatches += 1
                self.mega_tick_total += k
                self.decode_steps += k
                self._obs_decode_steps.inc(k)
                base = self._host_lengths.copy()
                self._host_lengths = np.minimum(
                    base + k, self.max_context - 1
                )
            # per-tick length snapshots: row j holds every slot's length
            # AS OF tick j, so retirement anchors on the dispatch tick
            # that produced each token (the K=1 loop's post-dispatch
            # snapshot, per tick) — never on the window's requested n
            lengths = np.minimum(
                base[None, :] + np.arange(1, k + 1, dtype=np.int64)[:, None],
                self.max_context - 1,
            )
            host_tokens = np.asarray(tokens)[:k]
            sample_s = self._devprof_sample(dtok)
            return host_tokens, lengths, k, sample_s
        finally:
            if started is not None and self._devprof is not None:
                self._devprof.dequeue()

    def mega_step(
        self, n_ticks: int, stops: np.ndarray, budgets: np.ndarray,
    ) -> Tuple[np.ndarray, np.ndarray, int]:
        """Run up to ``n_ticks`` decode ticks in ONE device-resident
        while-loop dispatch (the multi-tick megagraph, ``_mega_impl``)
        with early exit the moment no slot needs another tick.

        ``stops`` [num_slots, MEGA_STOP_SLOTS] int32 carries each slot's
        stop ids (pad -1); ``budgets`` [num_slots] int32 the remaining
        token budget per slot. Returns (tokens [k, num_slots], per-tick
        length snapshots [k, num_slots], k) where k <= n_ticks is the
        REAL tick count the loop ran."""
        tokens, lengths, k, _ = self._mega_dispatch(n_ticks, stops, budgets)
        return tokens, lengths, k

    def mega_step_async(
        self, n_ticks: int, stops: np.ndarray, budgets: np.ndarray,
    ) -> PendingDecode:
        """``mega_step`` on the engine's dispatch worker thread —
        the same depth-2 pipelined contract as ``step_async``; the
        returned handle's ``ticks`` holds the real k after ``wait()``
        and ``lengths`` the per-tick [k, S] snapshots."""
        if self._dispatch_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._dispatch_pool = ThreadPoolExecutor(
                max_workers=1,
                thread_name_prefix=f"decode-dispatch-{self.cfg.name}",
            )
        started = threading.Event()
        if self._devprof is not None:
            self._devprof.enqueue()
        fut = self._dispatch_pool.submit(
            self._mega_dispatch, n_ticks, stops, budgets, started
        )
        return PendingDecode(fut, n_ticks, started)

    def step_masked(self, mask: np.ndarray) -> np.ndarray:
        """One batched decode step with a per-slot ADDITIVE logits mask
        [num_slots, vocab] fp32 (0 = allowed, -inf = forbidden) applied
        before sampling — grammar-constrained decoding (jsonmode.py).
        Returns tokens [1, num_slots]."""
        with self._lock:
            m = jnp.asarray(mask, jnp.float32)
            dtok = self._devprof_note("masked", "masked")
            if self.paged:
                self._back_active_slots(1)
                self.state, tokens = self._masked_step_fn()(
                    self.params, self.state, self._tables_operand(), m,
                )
            else:
                self.state, tokens = self._masked_step_fn()(
                    self.params, self.state, m
                )
            self.decode_steps += 1
            self._obs_decode_steps.inc()
            self._host_lengths = np.minimum(
                self._host_lengths + 1, self.max_context - 1
            )
        # readback OUTSIDE the lock (like _step_dispatch): concurrent
        # engine calls — force_pending_token, release, overlap probes that
        # do take the lock — need not wait for this dispatch to finish
        host_tokens = np.asarray(tokens)
        self._devprof_sample(dtok)
        return host_tokens

    def jump_step(self, forced: np.ndarray, counts: np.ndarray) -> None:
        """Append grammar-FORCED token runs in ONE multi-token dispatch
        (compressed-FSM jump-ahead; the batcher's constrained tick).

        ``forced`` [num_slots, K] int32 holds each jumping slot's run
        (padded past its count); ``counts`` [num_slots] int32 in [0, K] —
        0 marks a slot this dispatch must not advance. K buckets up to
        the smallest ``JUMP_BUCKETS`` size (run-length-bucketed graphs,
        AOT-warmed), so steady-state constrained serving never
        recompiles. The caller must clamp each run so
        ``slot_length + counts[s] <= max_context - 2`` (the verify-write
        contract) and emits the run tokens itself — the forced tokens
        ARE the dispatch's output by construction."""
        if not self.spec_supported:
            raise ValueError(
                "jump-ahead dispatches are unsupported with a "
                "dp-replicated page pool (verify_step_paged has no "
                "shard_map pool twin)"
            )
        k = int(forced.shape[1])
        # round up to a JUMP_BUCKETS size (the exact set warmup compiled
        # — any other width would lazily build a graph mid-serving)
        kb = next((b for b in JUMP_BUCKETS if b >= k), None)
        if kb is None:
            raise ValueError(
                f"jump run of {k} tokens exceeds the largest bucket "
                f"({JUMP_BUCKETS[-1]}); clamp runs to jump_max"
            )
        forced = np.asarray(forced, np.int32)
        if kb > k:
            forced = np.concatenate(
                [forced, np.zeros((self.num_slots, kb - k), np.int32)],
                axis=1,
            )
        counts = np.asarray(counts, np.int32)
        with self._lock:
            args = ()
            if self.paged:
                self._back_active_slots(kb + 1)
                args = (self._tables_operand(),)
            dtok = self._devprof_note("jump", kb)
            self.state = self._jump_fn(kb)(
                self.params, self.state, *args,
                jnp.asarray(forced), jnp.asarray(counts),
            )
            self.decode_steps += 1
            self._obs_decode_steps.inc()
            self.jump_dispatches += 1
            self.jump_tokens += int(counts.sum())
            self._host_lengths = np.minimum(
                self._host_lengths + counts, self.max_context - 1
            )
            sync_ref = self.state["lengths"] if dtok is not None else None
        if dtok is not None:
            # jump has no token readback (the forced run IS the output);
            # a sampled dispatch blocks on the new state OUTSIDE the lock
            # — the constrained tick already drained the pipeline, so
            # nothing queues behind this
            self._devprof_sample_sync(dtok, sync_ref)

    def force_pending_token(self, slot: int, token_id: int) -> None:
        """Replace ``slot``'s pending (sampled-but-not-yet-consumed) token.

        Grammar-constrained requests use this right after prefill: the
        prefill graph samples the first token UNMASKED, so the batcher
        overwrites it with the grammar's forced opener (e.g. "{" for
        json_object mode) before any decode dispatch consumes it."""
        with self._lock:
            col = int(self._host_lengths[slot])
            self.state["last_tokens"] = (
                self.state["last_tokens"].at[slot].set(token_id)
            )
            self.state["history"] = (
                self.state["history"].at[slot, col].set(token_id)
            )

    def spec_step(
        self, n_rounds: int = 8, draft_len: int = 7, ngram: int = 3
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Run ``n_rounds`` speculative decode rounds in one dispatch.

        Returns (tokens [n_rounds, num_slots, draft_len+1],
        counts [n_rounds, num_slots]): in round r, slot s emitted the first
        ``counts[r, s]`` entries of ``tokens[r, s]`` — at least 1 (a plain
        decode step's token), up to ``draft_len+1`` when the whole n-gram
        draft was accepted. Greedy slots emit exactly the plain-greedy
        sequence; temp>0 slots never speculate and emit 1 sampled
        token/round. Only columns where ``self.active`` are meaningful.
        """
        # upper bound keeps active slots' history writes strictly below the
        # sacrificial last pad column reserved for inactive slots
        if not 1 <= draft_len <= spec.HISTORY_PAD - 2:
            raise ValueError(
                f"draft_len must be in [1, {spec.HISTORY_PAD - 2}]"
            )
        if ngram < 1:
            raise ValueError("ngram must be >= 1")
        if not self.spec_supported:
            raise ValueError(
                "speculative decoding is unsupported with a dp-replicated "
                "page pool (verify_step_paged has no shard_map pool twin)"
            )
        if not self.track_history:
            raise ValueError(
                "speculative decoding needs the token history "
                "(track_history=True; the n-gram proposer reads it)"
            )
        with self._lock:
            if self.paged:
                # worst case: full acceptance every round; unused pages
                # recycle at release
                self._back_active_slots(n_rounds * (draft_len + 1))
                args = (self._tables_operand(),)
            else:
                args = ()
            dtok = self._devprof_note(
                "spec", (n_rounds, draft_len, ngram)
            )
            self.state, (tokens, counts) = self._spec_fn(
                n_rounds, draft_len, ngram
            )(self.params, self.state, *args)
            self.decode_steps += n_rounds
            self._obs_decode_steps.inc(n_rounds)
            self.spec_rounds += n_rounds
            self.spec_proposer_rounds["ngram"] += n_rounds
            # acceptance denominator: (round, active-slot) pairs — a
            # per-slot rate that doesn't scale with batch occupancy
            active_rounds = n_rounds * int(self.active.sum())
            self.spec_slot_rounds += active_rounds
        # the device->host readback happens OUTSIDE the engine lock
        # (the step()/step_masked() discipline, lock-readback rule):
        # concurrent peek/stats callers must not wait on the transfer
        counts = np.asarray(counts)
        tokens = np.asarray(tokens)
        self._devprof_sample(dtok)
        # fold the data-dependent length advance back in under the lock;
        # dispatches all come from the scheduler thread (spec ticks flush
        # the pipeline first), so nothing interleaves between the two
        # critical sections
        with self._lock:
            emitted = int(counts[:, self.active].sum())
            self.spec_tokens += emitted
            self.spec_proposer_accepted["ngram"] += max(
                emitted - active_rounds, 0
            )
            self._host_lengths = np.minimum(
                self._host_lengths + counts.sum(axis=0), self.max_context - 1
            )
        return tokens, counts

    def spec_step_draft(
        self, n_rounds: int = 8, draft_len: int = 7
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Run ``n_rounds`` DRAFT-MODEL speculative rounds: the attached
        small model (spec.DraftModel, int4 weights) proposes K tokens per
        greedy slot and the serving model verifies them — propose,
        verify, accept, draft-KV sync all inside ONE fused dispatch per
        call (bulk draft catch-up for freshly admitted slots runs as
        separate ingest dispatches first).

        Returns (tokens [n_rounds, num_slots, draft_len+1],
        counts [n_rounds, num_slots], proposed [n_rounds, num_slots]) —
        tokens/counts exactly as ``spec_step``; ``proposed`` is the draft
        tokens offered per (round, slot) (0 or draft_len), the honest
        acceptance denominator for the per-proposer EWMA. Greedy slots
        emit exactly the plain-greedy sequence; temp>0 slots never
        speculate."""
        if self.draft is None:
            raise ValueError(
                "no draft model attached (TPUEngine(draft=...) / "
                "AIOS_TPU_DRAFT_MODEL)"
            )
        if not 1 <= draft_len <= spec.HISTORY_PAD - 2:
            raise ValueError(
                f"draft_len must be in [1, {spec.HISTORY_PAD - 2}]"
            )
        self._draft_catchup(headroom=draft_len + 1)
        with self._lock:
            if self.paged:
                self._back_active_slots(n_rounds * (draft_len + 1))
                args = (self._tables_operand(),)
            else:
                args = ()
            dtok = self._devprof_note(
                "draft_spec", (n_rounds, draft_len, draft_len + 1)
            )
            self.state, self.draft_state, (tokens, counts, proposed) = (
                self._draft_spec_fn(n_rounds, draft_len)(
                    self.params, self.draft.params, self.state,
                    self.draft_state, *args,
                )
            )
            self.decode_steps += n_rounds
            self._obs_decode_steps.inc(n_rounds)
            self.spec_rounds += n_rounds
            self.spec_proposer_rounds["draft"] += n_rounds
            active_rounds = n_rounds * int(self.active.sum())
            self.spec_slot_rounds += active_rounds
        # readbacks OUTSIDE the lock (lock-readback discipline); the
        # draft host-length mirror reads the post-dispatch device value
        # rather than replaying R rounds of catchup/propose/clamp math
        counts = np.asarray(counts)
        tokens = np.asarray(tokens)
        proposed = np.asarray(proposed)
        d_len = np.asarray(self.draft_state["lengths"])
        self._devprof_sample(dtok)
        with self._lock:
            emitted = int(counts[:, self.active].sum())
            self.spec_tokens += emitted
            self.spec_proposer_accepted["draft"] += max(
                emitted - active_rounds, 0
            )
            self.draft_proposed_tokens += int(proposed[:, self.active].sum())
            self._host_lengths = np.minimum(
                self._host_lengths + counts.sum(axis=0), self.max_context - 1
            )
            self._draft_host_lengths = d_len.astype(np.int64)
        return tokens, counts, proposed

    def _draft_catchup(self, headroom: int) -> None:
        """Bulk-ingest history into the draft KV until every active
        slot's draft gap fits inside the fused rounds' per-round
        catch-up width (``headroom``). Freshly admitted slots arrive
        with a whole-prompt gap; each pass advances every lagging slot
        by up to one ingest bucket. Dispatches all come from the
        scheduler thread (like spec_step), so the host mirrors can't
        race the device state."""
        buckets = self._draft_ingest_buckets()
        while True:
            gaps = (
                self._host_lengths - self._draft_host_lengths
            )[self.active & self._host_greedy]
            gap_max = int(gaps.max()) if gaps.size else 0
            if gap_max <= headroom:
                return
            w = next((b for b in buckets if b >= gap_max), buckets[-1])
            with self._lock:
                dtok = self._devprof_note("draft_ingest", ("ingest", w))
                self.draft_state = self._draft_ingest_fn(w)(
                    self.draft.params, self.draft_state,
                    self.state["history"], self.state["lengths"],
                    self.state["active"], self.state["temps"],
                )
                self.draft_ingest_dispatches += 1
            self._draft_host_lengths = np.asarray(
                self.draft_state["lengths"]
            ).astype(np.int64)
            self._devprof_sample(dtok)

    def release(self, slot: int) -> None:
        self.active[slot] = False
        self._host_lengths[slot] = 0
        self._draft_host_lengths[slot] = 0
        self._host_greedy[slot] = False
        self._win_starts[slot] = 0  # next occupant starts uncompressed
        with self._lock:
            if self.allocator is not None:
                self.allocator.free_slot(slot)  # pages recycle instantly
            self.state["lengths"] = self.state["lengths"].at[slot].set(0)
            self.state["active"] = self.state["active"].at[slot].set(False)
            if self.draft_state is not None:
                # the next occupant's draft KV rebuilds from history via
                # ingest; zeroing the length is the whole reset
                self.draft_state["lengths"] = (
                    self.draft_state["lengths"].at[slot].set(0)
                )

    def slot_length(self, slot: int) -> int:
        return int(self._host_lengths[slot])

    def compressed_resident_pages(self) -> int:
        """Pages currently resident for slots pruned by window+sink
        compression (sink + trailing window + the partial block) — what
        ``aios_tpu_kv_compress_resident_pages`` reports, and the number
        the long-context bench compares against the uncompressed
        footprint."""
        if not self.kv_compress_armed or self.allocator is None:
            return 0
        return sum(
            self.allocator.slot_pages_resident(s)
            for s in range(self.num_slots)
            if self._win_starts[s] > 0
        )

    def stats(self) -> Dict[str, float]:
        """Serving counters for observability (HealthCheck details, the
        monitoring agent's metric push — the reference's llama-server
        exposes nothing comparable)."""
        out: Dict[str, float] = {
            "decode_steps": self.decode_steps,
            "active_slots": int(self.active.sum()),
            "batch_occupancy": round(
                float(self.active.sum()) / self.num_slots, 3
            ) if self.num_slots else 0.0,
            "xla_compiles": self.compile_events,
            "xla_compile_s": round(self.compile_seconds, 2),
        }
        if self.spec_rounds:
            out["spec_rounds"] = self.spec_rounds
            # mean tokens emitted per slot per verify round (1.0 = nothing
            # accepted; draft_len+1 = every draft accepted)
            out["spec_tokens_per_round"] = round(
                self.spec_tokens / max(self.spec_slot_rounds, 1), 2
            )
            out["spec_accepted"] = max(
                self.spec_tokens - self.spec_slot_rounds, 0
            )
            for p in spec.SPEC_PROPOSERS:
                if self.spec_proposer_rounds[p]:
                    out[f"spec_{p}_rounds"] = self.spec_proposer_rounds[p]
                    out[f"spec_{p}_accepted"] = (
                        self.spec_proposer_accepted[p]
                    )
        if self.draft is not None:
            out["draft_ingest_dispatches"] = self.draft_ingest_dispatches
            out["draft_proposed_tokens"] = self.draft_proposed_tokens
            if self.draft_proposed_tokens:
                out["draft_acceptance"] = round(
                    self.spec_proposer_accepted["draft"]
                    / self.draft_proposed_tokens, 3
                )
        if self.jump_dispatches:
            out["jump_dispatches"] = self.jump_dispatches
            out["jump_tokens"] = self.jump_tokens
        if self.mega_dispatches:
            out["mega_dispatches"] = self.mega_dispatches
            # REAL ticks run (k per dispatch, <= K on early exit);
            # mega_ticks * dispatches - this = the early-exit savings
            out["mega_ticks"] = self.mega_tick_total
        if self.allocator is not None:
            out["kv_pages_in_use"] = self.allocator.pages_in_use()
            out["kv_pages_free"] = self.allocator.free_pages
        if self.kv_compress_armed:
            out["kv_compress_slots"] = self.kv_compress_slots
            out["kv_compress_pages_pruned"] = self.kv_pages_pruned
            out["kv_compress_resident_pages"] = self.compressed_resident_pages()
        if self._seq_attn is not None:
            out["prefill_seq_sharded"] = self.prefill_seq_sharded
        if self.prefix_index is not None:
            out["prefix_hits"] = self.prefix_index.hits
            out["prefix_misses"] = self.prefix_index.misses
            out["prefix_rows_reused"] = self.prefix_rows_reused
        if self.host_store is not None:
            s = self.host_store
            out["prefix_rows_restored"] = self.prefix_rows_restored
            out["host_tier_bytes"] = s.bytes_resident
            out["host_tier_capacity_bytes"] = s.max_bytes
            out["host_tier_spills"] = s.spills
            out["host_tier_restores"] = s.restores
            out["host_tier_hits"] = s.hits
            out["host_tier_misses"] = s.misses
            out["host_tier_corrupt"] = s.corruptions
            out["host_tier_restore_s"] = round(self.host_restore_seconds, 3)
        return out

    def close(self) -> None:
        """Release device memory NOW. The jitted step fns close over
        ``self`` (self._step_fns -> lambda -> self), so a dropped engine is
        an uncollected reference CYCLE and its HBM survives until a gc pass
        — on a 16 GB chip that breaks the next model load. Explicitly
        breaking the cycle and dropping the arrays frees the buffers
        deterministically (model_manager.unload_model and the bench rely on
        this)."""
        import gc

        if self._spill_q is not None:
            # stop accepting spills, then drain + stop the worker BEFORE
            # dropping the state (its queued items hold materialized
            # gather results, independent of the pool buffer). _spill_q
            # itself stays set: a worker that outlives the join (deep
            # backlog) drains through its local reference and exits on
            # the sentinel — nulling it would crash the worker mid-drain.
            if self.prefix_index is not None:
                self.prefix_index.spill = None
            self._spill_q.put(None)
            if self._spill_thread is not None:
                self._spill_thread.join(timeout=5)
            self._spill_thread = None
        if self.host_store is not None:
            # after the worker exited this empties the store for good; on
            # a timed-out join the straggler's late inserts are bounded
            # by the store budget and freed when the engine is collected
            self.host_store.clear()
        if self._dispatch_pool is not None:
            # drain the decode-dispatch worker BEFORE dropping the state:
            # a queued dispatch running against cleared state would die on
            # a confusing error inside the worker instead of here
            self._dispatch_pool.shutdown(wait=True)
            self._dispatch_pool = None
        with self._lock:
            self._step_fns.clear()
            self._prefill_fns.clear()
            self._chunk_fns.clear()
            self._spec_fns.clear()
            self._restore_fns.clear()
            self._jump_fns.clear()
            self._mega_fns.clear()
            self._draft_fns.clear()
            self._seq_prefill_fns.clear()
            self._seq_attn = None
            self.state = {}
            self.params = None
            self.draft = None  # DraftModel params may be pool-shared
            self.draft_state = None
            self._attn_impl = None
        gc.collect()

    # Admission granularity for long prompts; the batcher's default chunk
    # size and warmup's pre-compiled chunk graphs both read this, so the
    # production graphs and the readiness gate can't drift apart.
    prefill_chunk_default = 512

    def warmup(
        self,
        # must cover every step size the continuous batcher dispatches
        # (admit_chunk_steps=2, chunk_steps=16) — a size missing here
        # compiles for multiple seconds ON the scheduler thread at first
        # use, stalling every live request (measured: ~2 s added to all 8
        # agents' TTFT)
        step_sizes: Tuple[int, ...] = (1, 2, 8, 16),
        prefill_chunk: Optional[int] = None,  # None -> prefill_chunk_default
        masked_step: bool = False,  # also compile the grammar-masked step
        spec_sizes: Tuple[int, ...] = (),  # speculative round counts
        spec_draft_len: int = 7,
        spec_ngram: int = 3,
        # jump-ahead run buckets; None -> JUMP_BUCKETS when masked_step
        # (constrained deployments dispatch jump_step), () to skip
        jump_sizes: Optional[Tuple[int, ...]] = None,
    ) -> None:
        """AOT-compile every graph the serving path can hit (LoadModel
        readiness gate — the reference's /health polling equivalent,
        model_manager.rs:222-263; without this the first Infer would eat
        20-40 s of XLA compile).

        Dispatch-free: each graph is ``jit.lower(...).compile()``d against
        the live param/state avals, so warmup moves no device state — no
        synthetic prompts, no page allocations, no prefix-index or
        host-store pollution to roll back — and ``engine.stats()`` compile
        counters stay FLAT afterwards (the no-compile-after-warmup
        regression gate in tests/test_decode_pipeline.py).

        Coverage: every power-of-two prefill bucket the pool can back, the
        chunked-admission graphs (mid chunk + every final bucket <=
        ``prefill_chunk``; pass the batcher's size if it overrides the
        shared default, 0 to skip), the prefix-HIT graphs (history
        backfill per bucket + the prefix-chunk tail graphs), every
        ``step_sizes`` decode graph (ONE dynamic-n graph in unified_step
        mode), the grammar-masked step when ``masked_step``, speculative
        round graphs for ``spec_sizes``, every power-of-two multi-tick
        megagraph bucket when ``mega_ticks`` is armed, and the host-tier
        restore scatter buckets.
        """
        t0 = time.perf_counter()
        before = self.compile_events
        for bucket in self.buckets:
            if self.paged and self.allocator.blocks_for(
                bucket // 2 + 1
            ) > self.allocator.capacity_blocks():
                continue  # pool can't back prompts of this bucket anyway
            self.compile_prefill_fn(bucket)
            if (
                self._seq_attn is not None
                and bucket >= self.bucket_for(self.seq_prefill_min)
            ):
                # every bucket the routing floor can reach gets its
                # sp-sharded twin, so a huge admission never compiles
                # on the scheduler thread
                self.compile_seq_prefill_fn(bucket)
        ck = self.prefill_chunk_default if prefill_chunk is None else prefill_chunk
        if ck and ck in self.buckets and self.max_context % ck == 0:
            self.compile_chunk_fn(ck, final=False)
            for b in self.buckets:
                if b > ck:
                    break
                self.compile_chunk_fn(b, final=True)
        if self.prefix_index is not None:
            # the HIT path: history backfill for the matched rows + the
            # tail's chunk graphs at the prefix chunk size (distinct from
            # the batcher's chunk size when they diverge)
            for b in self.buckets:
                self.compile_hist_fn(b)
            pc = self._prefix_chunk
            if pc:
                self.compile_chunk_fn(pc, final=False)
                for b in self.buckets:
                    if b > pc:
                        break
                    self.compile_chunk_fn(b, final=True)
        # largest first: in unified_step mode the first compile sets
        # _unified_max, so ONE dynamic-n graph serves every smaller size
        # (ascending order would compile one graph per power of two)
        for n in sorted(step_sizes, reverse=True):
            self.compile_step_fn(n)
        if masked_step:  # json-mode deployments dispatch step_masked
            self.compile_masked_fn()
        if jump_sizes is None:
            # jump-ahead rides the constrained path, but respect the
            # escape hatch: a deployment that disabled it must not pay
            # len(JUMP_BUCKETS) jump-graph compiles (and resident
            # executables) at every engine start
            enabled = _env_flag("AIOS_TPU_JUMP_AHEAD")
            if enabled is None:
                enabled = bool(getattr(self.cfg, "jump_ahead", True))
            jump_sizes = JUMP_BUCKETS if (masked_step and enabled) else ()
        for k in jump_sizes:
            self.compile_jump_fn(k)
        if self.mega_ticks:
            # every power-of-two megagraph bucket up to the armed cap:
            # the batcher's window is min(chunk, mega_ticks) so the top
            # bucket covers it, and short tails (budget remainders,
            # admission windows) bucket downward — a size missing here
            # would compile on the scheduler thread mid-serving, exactly
            # the stall the flat-compile-counters gate exists to catch
            m = 1
            top = self.mega_bucket(self.mega_ticks)
            while m <= top:
                self.compile_mega_fn(m)
                m *= 2
        for n in spec_sizes:
            self.compile_spec_fn(n, spec_draft_len, spec_ngram)
            # the draft proposer serves the same round sizes; its n-gram
            # twin above stays warm too (the batcher's auto-disable
            # ladder falls back draft -> ngram without a compile stall)
            self.compile_draft_spec_fn(n, spec_draft_len)
        if spec_sizes and self.draft is not None:
            self.compile_draft_ingest_fns()
        if self.host_store is not None:
            # a restore chain is bounded by the prompt's full blocks AND
            # the pool; the last bucket rounds UP past capacity (a 10-page
            # restore on a 15-page pool buckets to 16 — stopping at
            # nb <= cap would leave exactly that bucket to compile
            # mid-serving)
            cap = min(
                self.allocator.capacity_blocks(),
                (self.max_context - 1) // self.allocator.page_size,
            )
            nb = 1
            while True:
                self.compile_restore_fn(nb)
                if nb >= cap:
                    break
                nb *= 2
        log.info(
            "%s: warmup AOT-compiled %d graph(s) in %.1fs",
            self.cfg.name, self.compile_events - before,
            time.perf_counter() - t0,
        )

    # -- convenience (tests, single-shot CLI) -------------------------------

    def generate(
        self,
        token_ids: List[int],
        max_new_tokens: int = 32,
        temperature: float = 0.0,
        top_p: float = 1.0,
        stop_tokens: Tuple[int, ...] = (),
        slot: int = 0,
        chunk: int = 8,
        speculative: bool = False,
        draft_len: int = 7,
        ngram: int = 3,
    ) -> List[int]:
        """Single-request generation loop (the continuous-batching scheduler
        in engine/batching.py is the production path). ``speculative=True``
        decodes via n-gram speculative rounds (spec.py) — identical greedy
        output, fewer dispatches; ``speculative="draft"`` uses the
        attached draft model instead; sampling requests fall back to
        plain stepping on their own."""
        first = self.prefill(slot, token_ids, temperature, top_p)
        out = [first]
        while len(out) < max_new_tokens and out[-1] not in stop_tokens:
            budget = min(chunk, max_new_tokens - len(out))
            room = self.max_context - 1 - self.slot_length(slot)
            if room <= 0:
                break
            if speculative:
                pre = self.slot_length(slot)  # before the dispatch mutates it
                if speculative == "draft":
                    toks, counts, _ = self.spec_step_draft(
                        min(budget, room), draft_len=draft_len
                    )
                else:
                    toks, counts = self.spec_step(
                        min(budget, room), draft_len=draft_len, ngram=ngram
                    )
                flat: List[int] = []
                for r in range(toks.shape[0]):
                    if pre >= self.max_context - 1:
                        # slot saturated mid-dispatch: later rounds' cache
                        # writes collapse onto the last row (verify_step's
                        # scatter contract) — their tokens are indeterminate
                        # and must not be consumed
                        break
                    flat.extend(int(t) for t in toks[r, slot, : counts[r, slot]])
                    pre += int(counts[r, slot])
                toks = flat
            else:
                toks = self.step(min(budget, room))[:, slot].tolist()
            for t in toks:
                out.append(int(t))
                if t in stop_tokens:
                    break
            if len(out) > max_new_tokens:  # speculative overshoot
                del out[max_new_tokens:]
        self.release(slot)
        if stop_tokens:
            for i, t in enumerate(out):
                if t in stop_tokens:
                    return out[: i + 1]
        return out


class ChunkedPrefill:
    """Driver for an in-flight incremental prefill of one slot.

    Each ``step()`` call processes one chunk (holding the engine lock only
    for that chunk's dispatch); between calls the owner may run
    ``engine.step`` for the other slots. The final chunk samples the first
    token, activates the slot, and is returned from ``step()``.

    While chunks are in flight the slot's device-side ``active`` flag stays
    False, so interleaved decode dispatches write this slot's (ignored) K/V
    to the sacrificial last cache row — never corrupting rows the prefill
    has already filled — and stream zero cache rows for it
    (model.decode_step's ``active`` gating). The sacrificial row is never
    read: the mask only exposes rows [0, length] and a request retires when
    its length reaches max_context - 1.
    """

    def __init__(
        self,
        engine: TPUEngine,
        slot: int,
        token_ids: List[int],
        temperature: float,
        top_p: float,
        chunk: int,
        start_pos: int = 0,  # rows already in the cache (matched prefix)
        hashes=(),  # block hashes to publish to the prefix index when done
    ) -> None:
        ids = list(token_ids)[-(engine.max_context - 1) :]
        if not ids:
            raise ValueError("empty prompt")
        self.engine = engine
        self.slot = slot
        self.ids = ids
        self.temperature = float(temperature)
        self.top_p = float(top_p)
        self.chunk = int(chunk)
        self.pos = int(start_pos)
        self.hashes = hashes
        self.first_token: Optional[int] = None

    @property
    def done(self) -> bool:
        return self.first_token is not None

    def step(self) -> Optional[int]:
        """Process the next chunk; returns the first sampled token when the
        prompt is fully admitted, else None."""
        if self.done:
            return self.first_token
        eng = self.engine
        remaining = len(self.ids) - self.pos
        final = remaining <= self.chunk
        n = min(self.chunk, remaining)
        bucket = eng.bucket_for(n) if final else self.chunk
        padded = np.zeros((1, bucket), dtype=np.int32)
        padded[0, :n] = self.ids[self.pos : self.pos + n]
        with eng._lock:
            extra = ()
            if eng.paged:
                # back this chunk's rows before dispatching; PoolExhausted
                # surfaces to the batcher with all state untouched. On
                # windowed models, blocks the remaining chunks can no
                # longer attend to free as admission advances — a 64k
                # prompt's residency is bounded by the window, not the
                # prompt (registration then skips the trimmed slot).
                # Compression-armed engines prune the same way: once the
                # admitted rows cross the threshold, the middle pages
                # free and later chunks mask them, so a long prompt's
                # peak residency is sink + window + one chunk.
                if eng.cfg.sliding_window is not None:
                    eng.allocator.trim_below_window(
                        self.slot, self.pos, eng.cfg.sliding_window
                    )
                eng._maybe_compress(self.slot, length=self.pos)
                eng.allocator.ensure(self.slot, self.pos + n)
                extra = (jnp.asarray(eng.allocator.tables[self.slot]),)
                if eng.kv_compress_armed:
                    extra += (
                        jnp.int32(int(eng._win_starts[self.slot])),
                    )
            dtok = eng._devprof_note("chunk", (bucket, final))
            if final:
                eng.state, first = eng._chunk_fn(bucket, True)(
                    eng.params,
                    eng.state,
                    jnp.asarray(padded),
                    jnp.int32(self.slot),
                    jnp.int32(self.pos),
                    jnp.int32(n),
                    jnp.int32(len(self.ids)),
                    jnp.float32(self.temperature),
                    jnp.float32(self.top_p),
                    *extra,
                )
                eng.active[self.slot] = True
                eng._host_greedy[self.slot] = (
                    self.temperature < sampling.GREEDY_EPS
                )
                eng._host_lengths[self.slot] = len(self.ids)
                eng._register_prefix(self.slot, self.ids, self.hashes)
                self.first_token = int(first)
            else:
                eng.state = eng._chunk_fn(bucket, False)(
                    eng.params,
                    eng.state,
                    jnp.asarray(padded),
                    jnp.int32(self.slot),
                    jnp.int32(self.pos),
                    *extra,
                )
        # final chunks blocked on int(first) above; mid-chunk samples
        # are submit-side (their writes overlap the next chunk's staging)
        eng._devprof_sample(dtok)
        self.pos += n
        return self.first_token


class _SeqShardedPrefill:
    """ChunkedPrefill-shaped driver for the sequence-sharded prefill:
    ONE ``step()`` runs the whole sp-sharded admission dispatch
    (engine._seq_prefill), so the batcher's incremental-admission loop —
    including its PoolExhausted eviction/retry recovery — drives both
    paths identically. ``pos`` moves 0 -> len(ids) in that single step,
    which is what the flight recorder's per-chunk rows-consumed
    accounting reads."""

    def __init__(self, engine: TPUEngine, slot: int, token_ids: List[int],
                 temperature: float, top_p: float, hashes) -> None:
        self.engine = engine
        self.slot = slot
        self.ids = list(token_ids)
        self.temperature = float(temperature)
        self.top_p = float(top_p)
        self.hashes = hashes
        self.pos = 0
        self.first_token: Optional[int] = None

    @property
    def done(self) -> bool:
        return self.first_token is not None

    def step(self) -> Optional[int]:
        if self.done:
            return self.first_token
        self.first_token = self.engine._seq_prefill(
            self.slot, self.ids, self.temperature, self.top_p, self.hashes
        )
        self.pos = len(self.ids)
        return self.first_token
