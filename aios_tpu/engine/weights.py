"""Weight loading: GGUF files and HF checkpoints -> engine params.

Replaces the reference's model-file handling (runtime/src/model_manager.rs
auto-loads `*.gguf` from AIOS_MODEL_DIR); here GGUF tensors are dequantized
host-side (engine/gguf.py) and stacked into the scan-ready [L, ...] layout of
engine/model.py, ready for `jax.device_put` with mesh shardings.

Two subtleties handled here:
  * llama.cpp's GGUF converter permutes attn_q/attn_k rows from the HF
    half-rotation RoPE layout to its interleaved layout; our model uses the
    HF convention, so llama-arch GGUF q/k weights are inverse-permuted.
  * GGUF/HF linear weights are stored (out, in); the engine stores (in, out)
    so forward passes are plain `x @ w` einsums.
"""

from __future__ import annotations

from typing import Callable, Dict

import numpy as np

from . import gguf as gguf_mod
from .config import ModelConfig, from_gguf_metadata

Array = np.ndarray


def _unpermute_llamacpp(w: Array, n_heads: int) -> Array:
    """Invert convert_hf_to_gguf's q/k row permutation (interleaved -> HF)."""
    out_dim, in_dim = w.shape
    half = out_dim // n_heads // 2
    return (
        w.reshape(n_heads, half, 2, in_dim)
        .swapaxes(1, 2)
        .reshape(out_dim, in_dim)
    )


def _stack(layers: list) -> Dict[str, Array]:
    return {k: np.stack([layer[k] for layer in layers]) for k in layers[0]}


# ---------------------------------------------------------------------------
# GGUF
# ---------------------------------------------------------------------------


def params_from_gguf(
    path: str, cfg: ModelConfig | None = None, dtype=np.float32
) -> tuple[Dict, ModelConfig]:
    """Load a GGUF model file into engine params. Returns (params, config)."""
    f = gguf_mod.GGUFFile(path)
    if cfg is None:
        cfg = from_gguf_metadata(f.metadata)
    permute_qk = f.architecture in ("llama", "mistral")

    def t(name: str) -> Array:
        return f.load_tensor(name, dtype=dtype)

    layers = []
    for i in range(cfg.num_layers):
        p = f"blk.{i}."
        wq = t(p + "attn_q.weight")
        wk = t(p + "attn_k.weight")
        if permute_qk:
            wq = _unpermute_llamacpp(wq, cfg.num_heads)
            wk = _unpermute_llamacpp(wk, cfg.num_kv_heads)
        layer = {
            "attn_norm": t(p + "attn_norm.weight"),
            "ffn_norm": t(p + "ffn_norm.weight"),
            "wq": wq.T,
            "wk": wk.T,
            "wv": t(p + "attn_v.weight").T,
            "wo": t(p + "attn_output.weight").T,
        }
        if cfg.moe:
            # expert-stacked tensors load as [X, out, in] (row-major of the
            # GGML innermost-first dims); engine layout is [X, in, out]
            layer["w_router"] = t(p + "ffn_gate_inp.weight").T
            layer["we_gate"] = t(p + "ffn_gate_exps.weight").swapaxes(-1, -2)
            layer["we_up"] = t(p + "ffn_up_exps.weight").swapaxes(-1, -2)
            layer["we_down"] = t(p + "ffn_down_exps.weight").swapaxes(-1, -2)
        else:
            layer["w_gate"] = t(p + "ffn_gate.weight").T
            layer["w_up"] = t(p + "ffn_up.weight").T
            layer["w_down"] = t(p + "ffn_down.weight").T
        if cfg.qk_norm:
            layer["q_norm"] = t(p + "attn_q_norm.weight")
            layer["k_norm"] = t(p + "attn_k_norm.weight")
        layers.append(layer)

    params = {
        "embed": t("token_embd.weight"),
        "layers": _stack(layers),
        "final_norm": t("output_norm.weight"),
    }
    if "output.weight" in f.tensors:
        params["lm_head"] = t("output.weight").T
    return params, cfg


# ---------------------------------------------------------------------------
# HF transformers state dicts (parity tests + safetensors checkpoints)
# ---------------------------------------------------------------------------


def params_from_hf_state_dict(
    sd: Dict[str, Array], cfg: ModelConfig, dtype=np.float32
) -> Dict:
    """Convert a transformers Llama/Mistral/Qwen3 state dict to engine params.

    ``sd`` values may be torch tensors or numpy arrays.
    """

    def get(name: str) -> Array:
        v = sd[name]
        if hasattr(v, "detach"):
            v = v.detach().cpu().numpy()
        return np.asarray(v, dtype=dtype)

    layers = []
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        layer = {
            "attn_norm": get(p + "input_layernorm.weight"),
            "ffn_norm": get(p + "post_attention_layernorm.weight"),
            "wq": get(p + "self_attn.q_proj.weight").T,
            "wk": get(p + "self_attn.k_proj.weight").T,
            "wv": get(p + "self_attn.v_proj.weight").T,
            "wo": get(p + "self_attn.o_proj.weight").T,
        }
        if cfg.moe:
            # qwen3_moe: mlp.gate + mlp.experts.N.{gate,up,down}_proj
            # mixtral: block_sparse_moe.gate + experts.N.{w1,w3,w2}
            if p + "mlp.gate.weight" in sd:
                m, eg, eu, ed = (
                    "mlp.gate", "gate_proj", "up_proj", "down_proj",
                )
                ep_ = "mlp.experts."
            else:
                m, eg, eu, ed = ("block_sparse_moe.gate", "w1", "w3", "w2")
                ep_ = "block_sparse_moe.experts."
            layer["w_router"] = get(f"{p}{m}.weight").T
            layer["we_gate"] = np.stack([
                get(f"{p}{ep_}{j}.{eg}.weight").T
                for j in range(cfg.num_experts)
            ])
            layer["we_up"] = np.stack([
                get(f"{p}{ep_}{j}.{eu}.weight").T
                for j in range(cfg.num_experts)
            ])
            layer["we_down"] = np.stack([
                get(f"{p}{ep_}{j}.{ed}.weight").T
                for j in range(cfg.num_experts)
            ])
        else:
            layer["w_gate"] = get(p + "mlp.gate_proj.weight").T
            layer["w_up"] = get(p + "mlp.up_proj.weight").T
            layer["w_down"] = get(p + "mlp.down_proj.weight").T
        if cfg.qk_norm:
            layer["q_norm"] = get(p + "self_attn.q_norm.weight")
            layer["k_norm"] = get(p + "self_attn.k_norm.weight")
        layers.append(layer)

    params = {
        "embed": get("model.embed_tokens.weight"),
        "layers": _stack(layers),
        "final_norm": get("model.norm.weight"),
    }
    if "lm_head.weight" in sd:
        params["lm_head"] = get("lm_head.weight").T
    return params


def map_params(params: Dict, fn: Callable[[Array], Array]) -> Dict:
    """Apply ``fn`` to every leaf array (e.g. dtype casts, device_put)."""
    out = {}
    for k, v in params.items():
        out[k] = map_params(v, fn) if isinstance(v, dict) else fn(v)
    return out
