"""Fleet-level routing: the pool's ladder extended across hosts.

Within one process the :class:`~aios_tpu.serving.router.Router` walks
sticky -> overlap -> least-loaded across replicas. This module adds the
fleet rung on top: before a request prefills locally, compare the LOCAL
cache's overlap (``engine.prefix_overlap_rows``) against what live
peers advertise through the gossiped prefix index (fleet/gprefix.py),
and when a peer's promised chain is deep enough to beat a local
recompute — transfer cost included — pull it over the kvx plane into
the local host tier, so the very next ``_match_prefix`` restores it
with a memcpy instead of a prefill forward pass.

The decision is priced, not just scored: fetching ``rows`` costs
``rows x bytes_per_row / AIOS_TPU_FLEET_KVX_GBPS`` seconds of wire
time, recomputing them costs ``rows / prefill_rate`` seconds off the
devprof ledger's sampled prefill throughput (the same ledger the
admission deadline gate trusts). When devprof has no samples yet the
cost gate abstains and the overlap-gain threshold alone decides.

Every decision lands on ``aios_tpu_fleet_route_total`` under the closed
:data:`FLEET_ROUTE_REASONS` enum — the disagg handoff outcomes
(fleet/disagg.py) share the same family, so one counter tells the whole
fleet-routing story.
"""

from __future__ import annotations

import logging
import os
from typing import Dict, List, Optional, Tuple

from ..obs import instruments as obs
from . import gprefix

log = logging.getLogger("aios.fleet.router")

# Fleet routing decisions — THE closed enum (pinned by test_obs_lint):
#   local           fleet rung consulted, local cache already wins (or
#                   the gain/cost gates said the transfer isn't worth it)
#   no_peer         wanted a remote chain but no live peer advertises one
#   remote_pull     pulled a peer's chain into the local host tier
#   handoff         prefill host handed the stream to a decode host
#   handoff_resume  re-handed to a survivor after a decode host died
#   fallback_local  a transfer/handoff failed; the request ran locally
FLEET_ROUTE_REASONS = (
    "local", "no_peer", "remote_pull", "handoff", "handoff_resume",
    "fallback_local",
)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def min_gain_rows(prompt_rows: int) -> int:
    """How many MORE rows a peer must promise over the local cache
    before a pull is considered: ``AIOS_TPU_FLEET_OVERLAP_GAIN`` as a
    fraction of the prompt (default 0.25 — the pool router's overlap
    threshold, one level up), floored at one page-worth of progress."""
    frac = _env_float("AIOS_TPU_FLEET_OVERLAP_GAIN", 0.25)
    return max(1, int(prompt_rows * frac))


def wire_gbps() -> float:
    """Assumed cross-host transfer bandwidth in GB/s
    (AIOS_TPU_FLEET_KVX_GBPS) for the fetch-vs-recompute price."""
    return max(_env_float("AIOS_TPU_FLEET_KVX_GBPS", 10.0), 1e-3)


def register_route_metrics(model: str) -> None:
    """Pre-register every fleet-route child for ``model`` by iterating
    the closed reason enum (same pattern as kvx.register_kvx_metrics)."""
    for reason in FLEET_ROUTE_REASONS:
        obs.FLEET_ROUTE.labels(model=model, reason=reason)


def count_route(model: str, reason: str) -> None:
    obs.FLEET_ROUTE.labels(model=model, reason=reason).inc()


def _prefill_rate(pool) -> float:
    """Sampled prefill throughput (rows/sec) off the devprof ledger —
    0.0 (cost gate abstains) until devprof has prefill samples."""
    from ..obs import devprof

    means = [
        m for m in (
            led.mean_s("prefill") for led in devprof.ledgers_for(pool.name)
        ) if m
    ]
    if not means:
        return 0.0
    reps = pool.replicas
    if not reps:
        return 0.0
    # one prefill graph run fills one padded bucket; the engine's
    # smallest bucket is the conservative rows-per-run estimate
    rows = float(getattr(reps[0].engine, "buckets", (0,))[0] or 0)
    if rows <= 0:
        return 0.0
    return rows / (sum(means) / len(means))


def _bytes_per_row(engine) -> int:
    """Wire bytes one KV row costs: per-page entry bytes / page size,
    derived from the live cache arrays' dtypes and dims (shape/metadata
    reads only — no device sync)."""
    P = int(engine.allocator.page_size)
    per_page = 0
    for key in ("k", "v", "k_s", "v_s"):
        a = engine.state.get(key) if hasattr(engine.state, "get") else None
        if a is not None:
            per_page += int(a.nbytes) // max(int(a.shape[1]), 1)
    return max(per_page // P, 1)


class FleetRouter:
    """Per-process fleet routing rung. Stateless beyond the manager
    handle — peers and digests come from the membership table each
    decision (they age with the heartbeat, not with this object)."""

    def __init__(self, manager) -> None:
        self.manager = manager

    def _peers(self) -> List[dict]:
        from ..obs import fleet

        reg = fleet.FLEET
        return reg.members() if reg is not None else []

    def decide_pull(self, m, route_ids: List[int]) -> Tuple[str, dict]:
        """The fleet rung for one request on model ``m`` (a
        ManagedModel): -> ``(reason, detail)`` where reason is "local" /
        "no_peer", or "remote_pull" with ``detail`` carrying the chosen
        peer, its transfer addr, and the chain hashes to fetch."""
        engine = m.engine
        if engine is None or getattr(engine, "prefix_index", None) is None:
            return "local", {}
        hashes = engine.prefix_hashes(route_ids)
        if not hashes:
            return "local", {}
        local_rows = engine.prefix_overlap_rows(route_ids, hashes)
        prompt_rows = len(route_ids)
        peer, remote_rows = gprefix.best_peer(self._peers(), m.name, hashes)
        gain = remote_rows - local_rows
        if peer is None or remote_rows <= 0:
            # only count no_peer when a remote chain could actually have
            # helped — a fully-local-cached prompt is a "local" decision
            if local_rows < prompt_rows - min_gain_rows(prompt_rows):
                return "no_peer", {}
            return "local", {}
        if gain < min_gain_rows(prompt_rows):
            return "local", {}
        rate = _prefill_rate(m.pool) if m.pool is not None else 0.0
        if rate > 0.0:
            fetch_s = gain * _bytes_per_row(engine) / (wire_gbps() * 1e9)
            recompute_s = gain / rate
            if fetch_s >= recompute_s:
                log.debug(
                    "%s: fleet pull rejected on cost (fetch %.4fs >= "
                    "recompute %.4fs for %d rows)",
                    m.name, fetch_s, recompute_s, gain,
                )
                return "local", {}
        P = int(engine.allocator.page_size)
        return "remote_pull", {
            "peer": peer["host"],
            "addr": peer["kvx_addr"],
            "hashes": hashes[: max(remote_rows // P, 1)],
            "rows": remote_rows,
            "local_rows": local_rows,
        }

    def pull_before_submit(self, m, route_ids: List[int]) -> str:
        """Run the fleet rung and, on a remote win, fetch the chain into
        the local host tier so the imminent local submit restores it.
        Returns the counted reason. All RPC happens here, outside every
        declared lock, before the pool ever sees the request."""
        from . import kvx

        reason, detail = self.decide_pull(m, route_ids)
        if reason == "remote_pull":
            store = m.engine.host_store
            got = kvx.fetch_chain(
                detail["addr"], m.name, detail["hashes"],
                peer=detail["peer"],
            ) if store is not None else []
            if not got:
                reason = "fallback_local"  # transfer failed; kvx counted why
            else:
                for h, entry in got:
                    store.put(h, entry)
                log.info(
                    "%s: pulled %d pages from %s (%d promised rows, "
                    "%d local)", m.name, len(got), detail["peer"],
                    detail["rows"], detail["local_rows"],
                )
        count_route(m.name, reason)
        return reason
