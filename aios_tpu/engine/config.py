"""Model configurations for the Llama-family decoder.

One architecture class covers every local model tier the reference routes to
(runtime/src/model_manager.rs:462-518): TinyLlama-1.1B (operational),
Mistral-7B (tactical, GQA + sliding window), DeepSeek-R1-Distill-8B
(tactical, Llama-3 shape), Qwen3-14B (strategic, QK-norm). Configs can be
built from presets, GGUF metadata, or HF config dicts.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any, Dict, Optional


@dataclass(frozen=True)
class ModelConfig:
    name: str
    vocab_size: int
    hidden_size: int
    intermediate_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    max_context: int = 4096
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-5
    sliding_window: Optional[int] = None
    tie_word_embeddings: bool = False
    qk_norm: bool = False  # Qwen3-style per-head RMSNorm on q/k
    # Mixture-of-experts (0 experts = dense FFN). The router picks
    # num_experts_per_tok experts per token; their gate weights are softmax
    # probabilities renormalized over the selected set when norm_topk_prob.
    num_experts: int = 0
    num_experts_per_tok: int = 2
    moe_intermediate_size: Optional[int] = None
    norm_topk_prob: bool = True
    # serving replicas per managed model (aios_tpu/serving/): N independent
    # engine+batcher replicas behind one cache-aware router. 1 = the
    # single-engine layout; AIOS_TPU_REPLICAS overrides at load time.
    replicas: int = 1
    # host-RAM spill tier behind the prefix cache (engine/paged.py
    # HostPageStore): byte budget for evicted prefix pages' KV, restored
    # device-side on a later hash-chain hit instead of re-prefilled.
    # 0 = off; AIOS_TPU_PREFIX_HOST_BYTES overrides at load time.
    prefix_host_bytes: int = 0
    # pipelined decode loop (engine/batching.py): decode dispatch N+1 is
    # enqueued before dispatch N's tokens are emitted/detokenized, so the
    # host phase overlaps device execution instead of idling it.
    # AIOS_TPU_DECODE_PIPELINE overrides at load time (docs/ENGINE_PERF.md).
    decode_pipeline: bool = False
    # unified dynamic-step decode graph (engine/engine.py _unified_impl):
    # one compiled fori_loop serves every decode chunk size instead of one
    # scan graph per size. Greedy-identical; sampled sequences draw from a
    # different key fanout. AIOS_TPU_UNIFIED_STEP overrides at load time.
    unified_step: bool = False
    # device-resident multi-tick decode megagraph (engine/engine.py
    # _mega_impl): up to this many decode ticks run per dispatch inside
    # one lax.while_loop — sampling, EOS/stop detection, per-slot budget
    # and context-cap checks all on device — with early exit the moment
    # no slot needs another tick, so host work (readback, detokenize,
    # flight recorder, SLO sampling) amortizes K-fold. 0 = off (the
    # per-dispatch scan graphs serve). AIOS_TPU_MEGA_TICKS overrides at
    # load time (docs/ENGINE_PERF.md "Device-resident multi-tick decode").
    mega_ticks: int = 0
    # grammar jump-ahead for constrained decoding (engine/batching.py
    # _jump_tick): chains of grammar-FORCED tokens (singleton masks —
    # schema key literals, '":', '",', closers) emit host-side and append
    # their KV in ONE multi-token dispatch instead of one masked dispatch
    # each. Greedy-identical to the per-step path; AIOS_TPU_JUMP_AHEAD
    # overrides at load time (docs/ENGINE_PERF.md).
    jump_ahead: bool = True
    # auto-disable speculation per batcher and PER PROPOSER when that
    # proposer's EWMA draft-acceptance ratio collapses below this floor
    # (the ladder falls draft -> ngram -> off; plain/pipelined decode
    # serves meanwhile and probe dispatches re-measure periodically).
    # 0 = never auto-disable. AIOS_TPU_SPEC_MIN_ACCEPT overrides.
    spec_min_accept: float = 0.0
    # how long an auto-disabled proposer stays suspended before its probe
    # dispatches re-measure (engine/batching.py SPEC_PROBE_DISPATCHES of
    # them re-judge on a fresh cumulative average).
    # AIOS_TPU_SPEC_REPROBE_SECS overrides at load time.
    spec_reprobe_secs: float = 10.0
    # draft-model speculation (engine/spec.py DraftModel): the model
    # source — a preset name like "tinyllama" or a weights path — loaded
    # as an int4 draft whose proposals the serving model verifies in one
    # dispatch (docs/ENGINE_PERF.md). "" = n-gram prompt-lookup only.
    # Requires the serving and draft models to share a tokenizer/vocab;
    # single-device pools only (dp-replicated pools fall back to n-gram).
    # AIOS_TPU_DRAFT_MODEL overrides at load time.
    draft_model: str = ""
    # radix-tree prefix index (engine/paged.py RadixPrefixIndex): cross-
    # request prefix sharing by construction with leaf-LRU eviction and
    # partial-node overlap credit for the router. False = the legacy flat
    # hash-chain map (escape hatch). AIOS_TPU_PREFIX_RADIX overrides.
    prefix_radix: bool = True
    # Long-context tier (docs/ENGINE_PERF.md "Long-context tier"):
    # window+sink KV compression — once a slot's length exceeds this many
    # rows, its paged KV is pruned to kv_sink_pages leading pages (the
    # attention sinks) plus a sliding window of kv_window_pages trailing
    # pages; the freed middle pages return to the pool and decode masks
    # attend only to the live rows (SnapStream/StreamingLLM-style,
    # PAPERS.md). 0 = off (exact full attention). Below the threshold
    # streams are token-exact; above it they are a deterministic
    # approximation. Paged engines with an unreplicated pool only.
    # AIOS_TPU_KV_COMPRESS_AFTER overrides at load time.
    kv_compress_after: int = 0
    # leading pages kept live under KV compression (attention sinks —
    # the first tokens anchor the softmax; >= 1).
    # AIOS_TPU_KV_SINK_PAGES overrides.
    kv_sink_pages: int = 1
    # trailing sliding-window pages kept live under KV compression
    # (>= 1). AIOS_TPU_KV_WINDOW_PAGES overrides.
    kv_window_pages: int = 8
    # sequence-sharded prefill (parallel/ring_attention.py / ulysses.py):
    # prompts at least this many rows long prefill in ONE dispatch with
    # the sequence sharded over the mesh's sp axis instead of serially
    # through chunked admission — the whole mesh works one huge prompt's
    # prefill, and the resulting KV scatters back into the normal paged
    # layout so decode/prefix-cache/spill/failover see nothing new.
    # 0 = off. Needs a sharding plan with sp > 1 and an unreplicated
    # paged pool. AIOS_TPU_SEQ_PREFILL_MIN overrides.
    seq_prefill_min: int = 0

    @property
    def moe(self) -> bool:
        return self.num_experts > 0

    @property
    def expert_dim(self) -> int:
        return self.moe_intermediate_size or self.intermediate_size

    @property
    def q_dim(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.num_kv_heads * self.head_dim

    @property
    def group_size(self) -> int:
        return self.num_heads // self.num_kv_heads

    def scaled(self, **overrides) -> "ModelConfig":
        return replace(self, **overrides)

    def num_params(self) -> int:
        """Approximate parameter count (embeddings + blocks + head)."""
        e = self.vocab_size * self.hidden_size
        attn = self.hidden_size * self.q_dim * 2 + self.hidden_size * self.kv_dim * 2
        if self.moe:
            mlp = self.hidden_size * self.num_experts + (
                self.num_experts * 3 * self.hidden_size * self.expert_dim
            )
        else:
            mlp = 3 * self.hidden_size * self.intermediate_size
        norms = 2 * self.hidden_size
        head = 0 if self.tie_word_embeddings else e
        return e + self.num_layers * (attn + mlp + norms) + self.hidden_size + head

    def active_params(self) -> int:
        """Params touched per token (MoE: only the routed experts' FFNs) —
        the number that sets decode FLOPs, vs num_params() which sets HBM
        footprint."""
        if not self.moe:
            return self.num_params()
        e = self.vocab_size * self.hidden_size
        attn = self.hidden_size * self.q_dim * 2 + self.hidden_size * self.kv_dim * 2
        mlp = self.hidden_size * self.num_experts + (
            self.num_experts_per_tok * 3 * self.hidden_size * self.expert_dim
        )
        head = 0 if self.tie_word_embeddings else e
        return e + self.num_layers * (attn + mlp) + head


# ---------------------------------------------------------------------------
# Presets — the model tiers of the reference intelligence hierarchy
# ---------------------------------------------------------------------------

TINYLLAMA_1_1B = ModelConfig(
    name="tinyllama-1.1b",
    vocab_size=32000,
    hidden_size=2048,
    intermediate_size=5632,
    num_layers=22,
    num_heads=32,
    num_kv_heads=4,
    head_dim=64,
    max_context=2048,
    rope_theta=10000.0,
)

MISTRAL_7B = ModelConfig(
    name="mistral-7b",
    vocab_size=32000,
    hidden_size=4096,
    intermediate_size=14336,
    num_layers=32,
    num_heads=32,
    num_kv_heads=8,
    head_dim=128,
    max_context=8192,
    rope_theta=10000.0,
    sliding_window=4096,
)

DEEPSEEK_R1_8B = ModelConfig(
    # DeepSeek-R1-Distill-Llama-8B: Llama-3.1-8B geometry
    name="deepseek-r1-8b",
    vocab_size=128256,
    hidden_size=4096,
    intermediate_size=14336,
    num_layers=32,
    num_heads=32,
    num_kv_heads=8,
    head_dim=128,
    max_context=8192,
    rope_theta=500000.0,
)

QWEN3_14B = ModelConfig(
    name="qwen3-14b",
    vocab_size=151936,
    hidden_size=5120,
    intermediate_size=17408,
    num_layers=40,
    num_heads=40,
    num_kv_heads=8,
    head_dim=128,
    max_context=8192,
    rope_theta=1000000.0,
    rms_norm_eps=1e-6,
    qk_norm=True,
)

QWEN3_30B_A3B = ModelConfig(
    # The MoE tier the reference only reaches via the cloud gateway
    # (qwen3:30b-128k @ api.viwoapp.net, api-gateway/src/main.rs:70-88):
    # served locally here — 30B params in HBM, ~3B active per token.
    name="qwen3-30b-a3b",
    vocab_size=151936,
    hidden_size=2048,
    intermediate_size=6144,
    num_layers=48,
    num_heads=32,
    num_kv_heads=4,
    head_dim=128,
    max_context=32768,
    rope_theta=1000000.0,
    rms_norm_eps=1e-6,
    qk_norm=True,
    num_experts=128,
    num_experts_per_tok=8,
    moe_intermediate_size=768,
)

MIXTRAL_8X7B = ModelConfig(
    name="mixtral-8x7b",
    vocab_size=32000,
    hidden_size=4096,
    intermediate_size=14336,
    num_layers=32,
    num_heads=32,
    num_kv_heads=8,
    head_dim=128,
    max_context=32768,
    rope_theta=1000000.0,
    num_experts=8,
    num_experts_per_tok=2,
)

PRESETS: Dict[str, ModelConfig] = {
    c.name: c
    for c in (
        TINYLLAMA_1_1B,
        MISTRAL_7B,
        DEEPSEEK_R1_8B,
        QWEN3_14B,
        QWEN3_30B_A3B,
        MIXTRAL_8X7B,
    )
}

# Tiny variants for tests / dry runs (same code paths, trivial sizes).
# vocab 512 covers the ByteTokenizer's 258 ids (bos=256, eos=257).
TINY_TEST = ModelConfig(
    name="tiny-test",
    vocab_size=512,
    hidden_size=64,
    intermediate_size=128,
    num_layers=2,
    num_heads=4,
    num_kv_heads=2,
    head_dim=16,
    max_context=128,
)

TINY_MOE = ModelConfig(
    name="tiny-moe",
    vocab_size=512,
    hidden_size=64,
    intermediate_size=128,
    num_layers=2,
    num_heads=4,
    num_kv_heads=2,
    head_dim=16,
    max_context=128,
    num_experts=4,
    num_experts_per_tok=2,
    moe_intermediate_size=32,
)


def resolve(name: str) -> ModelConfig:
    """Case-insensitive partial matching, like the reference's
    select_model_for_level (model_manager.rs:506-518)."""
    low = name.lower()
    if low in PRESETS:
        return PRESETS[low]
    for key, cfg in PRESETS.items():
        if low in key or key in low:
            return cfg
    raise KeyError(f"unknown model config: {name}")


def from_gguf_metadata(md: Dict[str, Any]) -> ModelConfig:
    """Build a config from GGUF metadata keys (llama/mistral/qwen archs)."""
    arch = md.get("general.architecture", "llama")

    def key(suffix: str, default=None):
        return md.get(f"{arch}.{suffix}", default)

    heads = int(key("attention.head_count"))
    kv_heads = int(key("attention.head_count_kv", heads))
    hidden = int(key("embedding_length"))
    head_dim = int(key("attention.key_length", hidden // heads))
    vocab = int(md.get("tokenizer.ggml.tokens and vocab", 0)) or len(
        md.get("tokenizer.ggml.tokens", [])
    ) or int(key("vocab_size", 32000))
    num_experts = int(key("expert_count", 0) or 0)
    return ModelConfig(
        num_experts=num_experts,
        num_experts_per_tok=int(key("expert_used_count", 2) or 2),
        moe_intermediate_size=(
            int(key("expert_feed_forward_length"))
            if key("expert_feed_forward_length")
            else None
        ),
        norm_topk_prob=bool(key("expert_weights_norm", True)),
        name=md.get("general.name", arch).lower().replace(" ", "-"),
        vocab_size=vocab,
        hidden_size=hidden,
        intermediate_size=int(key("feed_forward_length")),
        num_layers=int(key("block_count")),
        num_heads=heads,
        num_kv_heads=kv_heads,
        head_dim=head_dim,
        max_context=int(key("context_length", 4096)),
        rope_theta=float(key("rope.freq_base", 10000.0)),
        rms_norm_eps=float(key("attention.layer_norm_rms_epsilon", 1e-5)),
        sliding_window=(
            int(key("attention.sliding_window")) if key("attention.sliding_window") else None
        ),
        qk_norm=arch.startswith("qwen3"),
    )


def from_hf_config(hf: Dict[str, Any], name: str = "hf-model") -> ModelConfig:
    """Build a config from a HuggingFace config dict
    (Llama/Mistral/Qwen3/Mixtral/Qwen3-MoE)."""
    heads = hf["num_attention_heads"]
    # num_local_experts (mixtral) / num_experts (qwen3_moe)
    num_experts = hf.get("num_local_experts") or hf.get("num_experts") or 0
    return ModelConfig(
        name=name,
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        intermediate_size=hf["intermediate_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=heads,
        num_kv_heads=hf.get("num_key_value_heads", heads),
        head_dim=hf.get("head_dim") or hf["hidden_size"] // heads,
        max_context=hf.get("max_position_embeddings", 4096),
        rope_theta=hf.get("rope_theta", 10000.0),
        rms_norm_eps=hf.get("rms_norm_eps", 1e-5),
        sliding_window=hf.get("sliding_window"),
        tie_word_embeddings=hf.get("tie_word_embeddings", False),
        qk_norm=hf.get("model_type", "") in ("qwen3", "qwen3_moe"),
        num_experts=num_experts,
        num_experts_per_tok=hf.get("num_experts_per_tok", 2),
        moe_intermediate_size=hf.get("moe_intermediate_size"),
        # mixtral always renormalizes the top-k weights; qwen3_moe gates it
        norm_topk_prob=hf.get("norm_topk_prob", True),
    )
