"""Training: sharded LM train step (next-token cross-entropy + AdamW).

The reference is inference-only, but the TPU framework treats training as a
first-class capability: the same Llama-family model code trains under a
(dp, sp, tp) mesh — batch over dp, ring-attention sequence parallelism over
sp for long contexts, Megatron TP over tp — with XLA inserting all
collectives from the sharding annotations. `jax.checkpoint` rematerializes
each transformer block so activation memory stays flat in depth.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.ring_attention import make_ring_attn_fn
from . import model
from .config import ModelConfig

TrainState = Dict  # {"params": pytree, "opt_state": pytree, "step": int32}


def token_cross_entropy(logits, tokens, loss_mask):
    """Next-token NLL. Returns (masked nll sum, mask sum) — the label/mask
    convention shared by the GSPMD and pipeline-parallel train steps:
    position t's label is tokens[t+1], the last column is ignored."""
    labels = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits[:, :-1])
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    mask = loss_mask[:, :-1]
    return -(ll * mask).sum(), mask.sum()


def make_optimizer(
    learning_rate: float = 3e-4,
    weight_decay: float = 0.1,
    b1: float = 0.9,
    b2: float = 0.95,
    grad_clip: float = 1.0,
    warmup_steps: int = 100,
    total_steps: int = 10_000,
) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=learning_rate,
        warmup_steps=warmup_steps,
        decay_steps=max(total_steps, warmup_steps + 1),
    )
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(schedule, b1=b1, b2=b2, weight_decay=weight_decay),
    )


def make_train_step(
    cfg: ModelConfig,
    mesh: Optional[Mesh] = None,
    optimizer: Optional[optax.GradientTransformation] = None,
    remat: bool = True,
    seq_parallel: str = "ring",
    moe_aux_coef: float = 0.01,
) -> Tuple[Callable, Callable]:
    """Returns (init_state, train_step), both jittable.

    With a mesh whose `sp` axis is >1, attention runs sequence-parallel —
    ``seq_parallel`` picks the sharding: "ring" (K/V rotate via ppermute;
    bandwidth-optimal at very long T) or "ulysses" (two all-to-alls swap
    sequence<->head sharding; wins at modest sp with plentiful heads, needs
    heads % sp == 0). Otherwise in-core GQA attention. Batches are
    dicts {"tokens": [B, T] int32, "loss_mask": [B, T] float32} where
    position t's label is tokens[t+1] (last column is ignored).
    """
    optimizer = optimizer or make_optimizer()
    if seq_parallel not in ("ring", "ulysses"):
        raise ValueError(
            f"seq_parallel must be 'ring' or 'ulysses', got {seq_parallel!r}"
        )
    attn_fn = None
    if mesh is not None and mesh.shape.get("sp", 1) > 1:
        if seq_parallel == "ring":
            attn_fn = make_ring_attn_fn(mesh, window=cfg.sliding_window)
        else:
            from ..parallel.ulysses import make_ulysses_attn_fn

            attn_fn = make_ulysses_attn_fn(mesh, window=cfg.sliding_window)

    # kernels=False: the Pallas flash kernel is forward-only; training must
    # take the differentiable XLA attention (or the explicit ring attn_fn)
    forward = model.forward_full
    if remat:
        forward = jax.checkpoint(forward, static_argnums=(1, 3, 4, 5))

    def loss_fn(params, tokens, loss_mask):
        if mesh is not None:
            tokens = jax.lax.with_sharding_constraint(
                tokens, NamedSharding(mesh, P("dp", "sp"))
            )
        if cfg.moe:
            logits, aux = forward(params, cfg, tokens, attn_fn, False, True)
        else:
            logits = forward(params, cfg, tokens, attn_fn, False, False)
            aux = jnp.float32(0.0)
        nll, denom = token_cross_entropy(logits, tokens, loss_mask)
        return nll / jnp.maximum(denom, 1.0) + moe_aux_coef * aux, aux

    def init_state(params) -> TrainState:
        return {
            "params": params,
            "opt_state": optimizer.init(params),
            "step": jnp.zeros((), jnp.int32),
        }

    def train_step(state: TrainState, batch: Dict) -> Tuple[TrainState, Dict]:
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state["params"], batch["tokens"], batch["loss_mask"]
        )
        updates, opt_state = optimizer.update(
            grads, state["opt_state"], state["params"]
        )
        params = optax.apply_updates(state["params"], updates)
        new_state = {
            "params": params,
            "opt_state": opt_state,
            "step": state["step"] + 1,
        }
        gnorm = optax.global_norm(grads)
        return new_state, {"loss": loss, "grad_norm": gnorm, "moe_aux": aux}

    return init_state, train_step


def train_loop(
    cfg: ModelConfig,
    params,
    batches,
    *,
    mesh: Optional[Mesh] = None,
    optimizer: Optional[optax.GradientTransformation] = None,
    checkpoint_dir: Optional[str] = None,
    save_every: int = 100,
    max_steps: Optional[int] = None,
    on_metrics: Optional[Callable[[int, Dict], None]] = None,
) -> TrainState:
    """Run train steps over ``batches`` with crash-safe checkpoint/resume.

    The resume pattern mirrors the reference's goal-state recovery (SQLite
    survives restarts, in-progress work resets and continues,
    goal_engine.rs:493-518) applied to model state: if ``checkpoint_dir``
    holds a checkpoint, training restarts from its exact {params, opt_state,
    step} — the incoming ``params`` only define shapes/shardings.
    """
    init_state, train_step = make_train_step(cfg, mesh, optimizer)
    state = init_state(params)
    manager = None
    if checkpoint_dir is not None:
        from .checkpoint import CheckpointManager

        manager = CheckpointManager(checkpoint_dir)
        if manager.latest_step() is not None:
            state = manager.restore(like=state)

    step_fn = jax.jit(train_step, donate_argnums=(0,))
    step = int(state["step"])
    for batch in batches:
        if max_steps is not None and step >= max_steps:
            break
        state, metrics = step_fn(state, batch)
        step += 1
        if on_metrics is not None:
            on_metrics(step, metrics)
        if manager is not None and step % save_every == 0:
            manager.save(step, state)
    if manager is not None:
        manager.save(step, state)
        manager.close()
    return state
