"""AIRuntime service: RPC surface, routing ladders, error codes, streaming.

Mirrors the reference's runtime service tests (grpc_service.rs:240-336 test
the Unavailable/InvalidArgument/FailedPrecondition paths by direct handler
invocation) but goes over a live localhost socket with a real tiny engine.
"""

import grpc
import pytest

from aios_tpu import rpc, services
from aios_tpu.proto_gen import common_pb2, runtime_pb2
from aios_tpu.runtime.model_manager import ModelManager
from aios_tpu.runtime.service import RuntimeService, serve


@pytest.fixture(scope="module")
def runtime_stub():
    manager = ModelManager(num_slots=2, warm_compile=False)
    server, service, port = serve(address="127.0.0.1:0", manager=manager, block=False)
    channel = rpc.insecure_channel(f"127.0.0.1:{port}")
    yield services.AIRuntimeStub(channel), manager
    channel.close()
    server.stop(grace=None)


def test_no_models_unavailable(runtime_stub):
    stub, _ = runtime_stub
    with pytest.raises(grpc.RpcError) as err:
        stub.Infer(runtime_pb2.InferRequest(prompt="hi"))
    assert err.value.code() == grpc.StatusCode.UNAVAILABLE


def test_reactive_level_rejected(runtime_stub):
    stub, _ = runtime_stub
    with pytest.raises(grpc.RpcError) as err:
        stub.Infer(
            runtime_pb2.InferRequest(prompt="hi", intelligence_level="reactive")
        )
    assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT


def test_load_model_and_infer(runtime_stub):
    stub, _ = runtime_stub
    status = stub.LoadModel(
        runtime_pb2.LoadModelRequest(
            model_name="tinyllama-test", model_path="synthetic://tiny-test"
        )
    )
    assert status.status == "ready"
    assert status.port == 0  # no HTTP sidecar on the TPU backend

    resp = stub.Infer(
        runtime_pb2.InferRequest(prompt="hello", max_tokens=8, temperature=0.0)
    )
    assert resp.model_used == "tinyllama-test"
    assert resp.tokens_used > 0
    assert resp.latency_ms >= 0

    models = stub.ListModels(common_pb2.Empty())
    assert [m.model_name for m in models.models] == ["tinyllama-test"]
    assert models.models[0].request_count >= 1


def test_operational_level_routes_to_tinyllama(runtime_stub):
    stub, _ = runtime_stub
    resp = stub.Infer(
        runtime_pb2.InferRequest(
            prompt="status?", intelligence_level="operational", max_tokens=4
        )
    )
    assert resp.model_used == "tinyllama-test"


def test_strategic_without_big_model_failed_precondition(runtime_stub):
    stub, _ = runtime_stub
    with pytest.raises(grpc.RpcError) as err:
        stub.Infer(
            runtime_pb2.InferRequest(
                prompt="plan", intelligence_level="strategic", max_tokens=4
            )
        )
    assert err.value.code() == grpc.StatusCode.FAILED_PRECONDITION
    assert "api-gateway" in err.value.details()


def test_explicit_unknown_model_not_found(runtime_stub):
    stub, _ = runtime_stub
    with pytest.raises(grpc.RpcError) as err:
        stub.Infer(runtime_pb2.InferRequest(prompt="x", model="nonexistent-13b"))
    assert err.value.code() == grpc.StatusCode.NOT_FOUND


def test_partial_name_matching(runtime_stub):
    stub, _ = runtime_stub
    resp = stub.Infer(
        runtime_pb2.InferRequest(prompt="x", model="TinyLlama", max_tokens=4)
    )
    assert resp.model_used == "tinyllama-test"


def test_stream_infer_token_by_token(runtime_stub):
    stub, _ = runtime_stub
    chunks = list(
        stub.StreamInfer(
            runtime_pb2.InferRequest(prompt="hello", max_tokens=6, temperature=0.0)
        )
    )
    assert chunks[-1].done
    assert all(not c.done for c in chunks[:-1])
    # genuinely incremental: more than one content chunk
    assert len(chunks) >= 2


def test_health_reports_models(runtime_stub):
    stub, _ = runtime_stub
    h = stub.HealthCheck(common_pb2.Empty())
    assert h.healthy
    assert h.details["backend"] == "jax-tpu"
    assert h.details["tinyllama-test"] == "ready"
    # serving counters ride the details map (additive observability)
    serving = h.details["tinyllama-test.serving"]
    assert "decode_steps=" in serving
    assert "completed=" in serving


def test_unload_model(runtime_stub):
    stub, manager = runtime_stub
    stub.LoadModel(
        runtime_pb2.LoadModelRequest(
            model_name="scratch", model_path="synthetic://tiny-test"
        )
    )
    out = stub.UnloadModel(runtime_pb2.UnloadModelRequest(model_name="scratch"))
    assert out.success
    out2 = stub.UnloadModel(runtime_pb2.UnloadModelRequest(model_name="scratch"))
    assert not out2.success
    assert manager.get("scratch") is None


def test_load_error_returns_internal(runtime_stub):
    stub, _ = runtime_stub
    with pytest.raises(grpc.RpcError) as err:
        stub.LoadModel(
            runtime_pb2.LoadModelRequest(
                model_name="bad", model_path="/nonexistent/file.gguf"
            )
        )
    assert err.value.code() == grpc.StatusCode.INTERNAL


def test_paged_auto_sizes_pool_from_slots_and_context(monkeypatch):
    """AIOS_TPU_PAGED_KV=auto (the production boot default) serves over a
    paged pool sized (num_slots + 1) x context with the prefix index on —
    the dense cache's HBM plus one slot of prefix-retention slack."""
    monkeypatch.setenv("AIOS_TPU_PAGED_KV", "auto")
    from aios_tpu.runtime.model_manager import ModelManager

    mgr = ModelManager(num_slots=2, warm_compile=False)
    assert mgr.paged_pool_rows == "auto"
    m = mgr.load_model("tiny", "synthetic://tiny-test", context_length=128)
    try:
        eng = m.engine
        assert eng.paged
        assert eng.prefix_index is not None
        rows = (2 + 1) * 128
        # pool pages = 1 sacrificial + rows/page_size (page_size 128)
        assert eng.allocator.num_pages == 1 + rows // 128
    finally:
        mgr.unload_model("tiny")
