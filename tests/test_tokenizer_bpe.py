"""Byte-level BPE tokenizer (GPT-2 family): parity vs the HF `tokenizers`
library as ground truth, special-token handling, GGUF dispatch.

The reference tokenizes inside llama.cpp; our GGUF path must reproduce the
same two tokenizer families from the embedded vocab alone:
SentencePiece-BPE (llama/mistral — test_gguf_spec_fixture.py) and GPT-2
byte-level BPE with rank-ordered merges (qwen3 / qwen3-moe /
deepseek-r1-distill's llama-3 vocab — this file).
"""

import numpy as np
import pytest

from aios_tpu.engine.tokenizer import (
    ByteLevelBPE,
    _bytes_to_unicode,
    gguf_tokenizer,
    tokenizer_from_dict,
    tokenizer_to_dict,
)


def _build_pair(merge_pairs, specials=()):
    """(our ByteLevelBPE, HF tokenizers.Tokenizer) over the same vocab."""
    from tokenizers import Tokenizer, decoders, models, pre_tokenizers

    alphabet = sorted(set(_bytes_to_unicode().values()))
    vocab_list = alphabet + ["".join(m) for m in merge_pairs] + list(specials)
    vocab = {t: i for i, t in enumerate(vocab_list)}
    hf = Tokenizer(models.BPE(vocab=vocab, merges=list(merge_pairs)))
    hf.pre_tokenizer = pre_tokenizers.ByteLevel(
        add_prefix_space=False, use_regex=True
    )
    hf.decoder = decoders.ByteLevel()
    types = [1] * (len(vocab_list) - len(specials)) + [3] * len(specials)
    mine = ByteLevelBPE(
        tokens=vocab_list,
        merges=[" ".join(m) for m in merge_pairs],
        token_types=types,
        bos_id=None,
        eos_id=None,
        pre="gpt2",
    )
    return mine, hf


MERGES = [
    ("Ġ", "h"), ("e", "l"), ("l", "o"), ("Ġh", "el"), ("Ġhel", "lo"),
    ("Ġ", "w"), ("o", "r"), ("l", "d"), ("Ġw", "or"), ("Ġwor", "ld"),
    ("1", "2"), ("12", "3"),
]

SAMPLES = [
    "hello world",
    "hello hello world!",
    "  leading and   multiple spaces",
    "tabs\tand\nnewlines\r\n",
    "numbers 123456 mixed42",
    "punct!!! ...and, (parens) [brackets]",
    "unicode héllo wörld — em-dash … ellipsis",
    "emoji 🙂 and CJK 你好世界",
    "don't stop can't won't it's",
    "CamelCase and snake_case and SCREAMING",
    "",
    " ",
    "\n\n\n",
]


@pytest.mark.parametrize("text", SAMPLES)
def test_parity_with_hf_tokenizers_gpt2(text):
    mine, hf = _build_pair(MERGES)
    assert mine.encode(text, add_bos=False) == hf.encode(text).ids


def test_decode_roundtrips():
    mine, hf = _build_pair(MERGES)
    for text in SAMPLES:
        ids = mine.encode(text, add_bos=False)
        assert mine.decode(ids) == text
        assert mine.decode(ids) == hf.decode(hf.encode(text).ids)


def test_parity_fuzz_random_strings():
    mine, hf = _build_pair(MERGES)
    rng = np.random.default_rng(0)
    pool = list("helo wrd123!?.éß中\n\t'")
    for _ in range(50):
        n = int(rng.integers(1, 40))
        text = "".join(rng.choice(pool) for _ in range(n))
        assert mine.encode(text, add_bos=False) == hf.encode(text).ids, text
        assert mine.decode(mine.encode(text, add_bos=False)) == text


def test_special_tokens_encode_to_single_ids():
    specials = ["<|im_start|>", "<|im_end|>"]
    mine, _ = _build_pair(MERGES, specials=specials)
    start_id = mine.tokens.index("<|im_start|>")
    end_id = mine.tokens.index("<|im_end|>")
    ids = mine.encode(
        "<|im_start|>hello world<|im_end|>", add_bos=False
    )
    assert ids[0] == start_id and ids[-1] == end_id
    inner = mine.encode("hello world", add_bos=False)
    assert ids[1:-1] == inner
    # control tokens are skipped on decode (chat scaffolding vanishes)
    assert mine.decode(ids) == "hello world"


def test_qwen2_pattern_splits_digits_individually():
    """The qwen2 pretokenizer splits every digit; gpt2 keeps runs."""
    mine_gpt2, _ = _build_pair(MERGES)
    mine_qwen = ByteLevelBPE(
        tokens=mine_gpt2.tokens,
        merges=mine_gpt2.merges,
        token_types=mine_gpt2.token_types,
        pre="qwen2",
    )
    g = mine_gpt2.encode("123", add_bos=False)
    q = mine_qwen.encode("123", add_bos=False)
    # gpt2 merges "123" via the 12+3 merges; qwen2 never sees the pair
    assert g == [mine_gpt2.tokens.index("123")]
    assert q == [mine_qwen.tokens.index(c) for c in "123"]


def test_serialization_roundtrip():
    mine, _ = _build_pair(MERGES, specials=["<|endoftext|>"])
    d = tokenizer_to_dict(mine)
    assert d["type"] == "blbpe"
    back = tokenizer_from_dict(d)
    for text in SAMPLES:
        assert back.encode(text, add_bos=False) == mine.encode(
            text, add_bos=False
        )


def test_gguf_dispatch_by_tokenizer_model():
    md_bpe = {
        "tokenizer.ggml.model": "gpt2",
        "tokenizer.ggml.pre": "qwen2",
        "tokenizer.ggml.tokens": ["a", "b", "<|im_start|>"],
        "tokenizer.ggml.merges": ["a b"],
        "tokenizer.ggml.token_type": [1, 1, 3],
        "tokenizer.ggml.eos_token_id": 2,
    }
    tok = gguf_tokenizer(md_bpe)
    assert isinstance(tok, ByteLevelBPE)
    assert tok.pre == "qwen2"
    assert tok.bos_id is None and tok.eos_id == 2

    from aios_tpu.engine.tokenizer import SentencePieceBPE

    md_sp = {
        "tokenizer.ggml.model": "llama",
        "tokenizer.ggml.tokens": ["<unk>", "<s>", "</s>", "▁hi"],
        "tokenizer.ggml.scores": [0.0, 0.0, 0.0, -1.0],
        "tokenizer.ggml.token_type": [2, 3, 3, 1],
        "tokenizer.ggml.bos_token_id": 1,
        "tokenizer.ggml.eos_token_id": 2,
    }
    assert isinstance(gguf_tokenizer(md_sp), SentencePieceBPE)
    # absent key defaults to the SentencePiece family (llama/mistral)
    assert isinstance(
        gguf_tokenizer({k: v for k, v in md_sp.items()
                        if k != "tokenizer.ggml.model"}),
        SentencePieceBPE,
    )


def test_no_bos_when_vocab_declares_none():
    mine, _ = _build_pair(MERGES)
    assert mine.encode("hello", add_bos=True) == mine.encode(
        "hello", add_bos=False
    )


def test_bos_requires_add_bos_token_flag():
    """Real Qwen GGUFs declare bos_token_id=<endoftext> WITH
    add_bos_token=false — a declared bos id alone must not prepend."""
    md = {
        "tokenizer.ggml.model": "gpt2",
        "tokenizer.ggml.tokens": ["a", "b", "<|endoftext|>"],
        "tokenizer.ggml.merges": [],
        "tokenizer.ggml.token_type": [1, 1, 3],
        "tokenizer.ggml.bos_token_id": 2,
        "tokenizer.ggml.eos_token_id": 2,
    }
    tok = gguf_tokenizer(md)
    assert tok.encode("a", add_bos=True) == [0]
    md["tokenizer.ggml.add_bos_token"] = True
    tok2 = gguf_tokenizer(md)
    assert tok2.encode("a", add_bos=True) == [2, 0]
    # the flag survives checkpoint serialization
    back = tokenizer_from_dict(tokenizer_to_dict(tok2))
    assert back.encode("a", add_bos=True) == [2, 0]


def test_pre_aliases_map_real_gguf_names():
    """convert_hf_to_gguf writes pre="llama-bpe" for Llama-3 vocabs and
    "deepseek-r1-qwen" for R1-distill-qwen; both must leave the gpt2
    fallback (digit-run handling differs)."""
    base = dict(
        tokens=sorted(set(_bytes_to_unicode().values())) + ["123"],
        merges=["1 2", "12 3"],
        token_types=None,
    )
    toks = {}
    for pre in ("llama-bpe", "deepseek-r1-qwen", "gpt2"):
        toks[pre] = ByteLevelBPE(
            tokens=base["tokens"],
            merges=base["merges"],
            token_types=[1] * len(base["tokens"]),
            pre=pre,
        )
    # gpt2 merges the digit run "1234" into 123+4; llama3 (llama-bpe)
    # splits digit runs into <=3-char groups; qwen2-family splits singly
    g = toks["gpt2"].encode("1234", add_bos=False)
    l3 = toks["llama-bpe"].encode("1234", add_bos=False)
    qw = toks["deepseek-r1-qwen"].encode("1234", add_bos=False)
    idx = {t: i for i, t in enumerate(base["tokens"])}
    assert g == [idx["123"], idx["4"]]
    assert l3 == [idx["123"], idx["4"]]
    assert qw == [idx["1"], idx["2"], idx["3"], idx["4"]]
