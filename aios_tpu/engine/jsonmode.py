"""Grammar-constrained JSON decoding: byte-level pushdown automaton +
per-state token masks.

The reference forces ``response_format={"type": "json_object"}`` on every
non-streaming local inference (runtime/src/inference.rs:114-122) and relies
on llama-server's GBNF grammar engine to make the output parse. The TPU
engine has no llama-server underneath, so this module provides the
equivalent: a bounded-depth JSON automaton over BYTES, compiled lazily into
per-state vocabulary masks that the decode step adds to the logits
(TPUEngine.step_masked) — sampling can only pick tokens every byte of which
keeps the output inside the JSON grammar.

Design notes (TPU-first):
  * the automaton lives on the HOST; the device sees only a [slots, vocab]
    additive fp32 mask per constrained step. The jitted graph is unchanged
    in shape, so no recompiles — constrained slots simply ride a 1-step
    dispatch cadence (the batcher's choice) while unconstrained slots in
    the same batch decode unmasked. The multi-tick decode megagraph
    (AIOS_TPU_MEGA_TICKS) keeps this split: the mask for tick t+1 depends
    on the token the automaton consumed at tick t, so constrained slots
    route through the same 1-step masked dispatches while mega windows
    only ever carry unconstrained slots — "constrained-mask selection on
    device" means the ROUTE is selected per slot on the host, not that
    the automaton was traced into the device loop.
  * masks are cached per automaton state. Generations revisit a small set
    of states (in-string, after-comma, ...), so the vocab walk
    (~vocab x token-length byte transitions, pure numpy/python) amortizes
    to near zero after the first few steps; the cache is shared by every
    request on the model.
  * token -> bytes comes from the tokenizer (`token_bytes_table`): GPT-2
    byte-level vocabs map through the byte<->unicode table,
    SentencePiece vocabs through the ▁ convention and <0xNN> byte tokens;
    control/special tokens get None and are never sampled inside JSON.

States are small tuples (phase, stack, ...); ``stack`` is a string of
'o'/'a' frames capped at ``max_depth`` (deeper nesting is simply
disallowed — the model must close something first).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

NEG_INF = -1e30
_WS = frozenset(b" \t\n\r")
_HEX = frozenset(b"0123456789abcdefABCDEF")
_DIGITS = frozenset(b"0123456789")
# number sub-states where the number is a complete value
_NUM_DONE = frozenset("0if E")  # '0'=lone zero, 'i'=int, 'f'=frac, 'E'=exp

State = Tuple


def start_state(require_object: bool = True) -> State:
    """Initial state: json_object mode only admits whitespace then '{'."""
    return ("V0", "") if require_object else ("V", "")


def is_terminal(state: State) -> bool:
    """EOS is legal here: one complete top-level value, nothing open."""
    return state[0] == "E" and state[1] == ""


def next_state(state: State, b: int, max_depth: int = 16,
               compact: bool = False) -> Optional[State]:
    """One byte transition; None = the byte leaves the grammar.

    ``compact`` disallows inter-element whitespace (string CONTENT keeps
    its spaces): the grammar then admits exactly canonical compact JSON.
    Generation-side callers (the batcher's mask caches) use it so that
    structural positions become SINGLETON states — the compressed-FSM
    property jump-ahead decoding collapses into multi-token runs — and
    so a constrained model can never dither on whitespace at the budget
    edge. Acceptor-side callers keep the default lenient grammar."""
    phase, stack = state[0], state[1]

    # -- value-complete: expect ',' / closer / ws (or nothing at top level)
    if phase == "E":
        if b in _WS:
            return None if compact else state
        if not stack:
            return None
        top = stack[-1]
        if b == ord(","):
            return ("K1", stack) if top == "o" else ("V", stack)
        if b == ord("}") and top == "o":
            return ("E", stack[:-1])
        if b == ord("]") and top == "a":
            return ("E", stack[:-1])
        return None

    # -- expecting a value ('V0' top-level object-only; 'A' value-or-']')
    if phase in ("V", "V0", "A"):
        if b in _WS:
            return None if compact else state
        if phase == "A" and b == ord("]"):
            return ("E", stack[:-1])
        if b == ord("{"):
            if phase == "A":
                pass  # value inside array: fall through with same stack
            if len(stack) >= max_depth:
                return None
            return ("K", stack + "o")
        if phase == "V0":
            return None  # top level must be an object
        if b == ord("["):
            if len(stack) >= max_depth:
                return None
            return ("A", stack + "a")
        if b == ord('"'):
            return ("S", stack, False)
        if b == ord("-"):
            return ("N", stack, "-")
        if b == ord("0"):
            return ("N", stack, "0")
        if b in _DIGITS:
            return ("N", stack, "i")
        if b == ord("t"):
            return ("L", stack, "true", 1)
        if b == ord("f"):
            return ("L", stack, "false", 1)
        if b == ord("n"):
            return ("L", stack, "null", 1)
        return None

    # -- object: expecting a key ('K' also allows '}'; 'K1' after comma)
    if phase in ("K", "K1"):
        if b in _WS:
            return None if compact else state
        if b == ord('"'):
            return ("S", stack, True)
        if phase == "K" and b == ord("}"):
            return ("E", stack[:-1])
        return None

    # -- expecting ':' after a key
    if phase == "C":
        if b in _WS:
            return None if compact else state
        if b == ord(":"):
            return ("V", stack)
        return None

    # -- inside a string (value or key); bytes >= 0x20 except '"' and '\'
    if phase == "S":
        is_key = state[2]
        if b == ord('"'):
            return ("C", stack) if is_key else ("E", stack)
        if b == ord("\\"):
            return ("X", stack, is_key)
        if b >= 0x20:  # includes UTF-8 continuation bytes
            return state
        return None

    # -- escape after backslash
    if phase == "X":
        is_key = state[2]
        if b in b'"\\/bfnrt':
            return ("S", stack, is_key)
        if b == ord("u"):
            return ("U", stack, is_key, 0)
        return None

    # -- \uXXXX hex digits
    if phase == "U":
        is_key, n = state[2], state[3]
        if b in _HEX:
            if n == 3:
                return ("S", stack, is_key)
            return ("U", stack, is_key, n + 1)
        return None

    # -- literal true/false/null
    if phase == "L":
        lit, pos = state[2], state[3]
        if b == ord(lit[pos]):
            if pos + 1 == len(lit):
                return ("E", stack)
            return ("L", stack, lit, pos + 1)
        return None

    # -- number; sub: '-', '0' (lone zero), 'i' int digits, '.', 'f' frac
    #    digits, 'e', 's' exp sign, 'E' exp digits
    if phase == "N":
        sub = state[2]
        if sub == "-":
            if b == ord("0"):
                return ("N", stack, "0")
            if b in _DIGITS:
                return ("N", stack, "i")
            return None
        if sub in ("0", "i"):
            if sub == "i" and b in _DIGITS:
                return state
            if b == ord("."):
                return ("N", stack, ".")
            if b in (ord("e"), ord("E")):
                return ("N", stack, "e")
        if sub == ".":
            if b in _DIGITS:
                return ("N", stack, "f")
            return None
        if sub == "f":
            if b in _DIGITS:
                return state
            if b in (ord("e"), ord("E")):
                return ("N", stack, "e")
        if sub == "e":
            if b in (ord("+"), ord("-")):
                return ("N", stack, "s")
            if b in _DIGITS:
                return ("N", stack, "E")
            return None
        if sub == "s":
            if b in _DIGITS:
                return ("N", stack, "E")
            return None
        if sub == "E" and b in _DIGITS:
            return state
        # a complete number is terminated by whatever may follow a value
        if sub in _NUM_DONE:
            return next_state(("E", stack), b, max_depth, compact)
        return None

    return None


def run_bytes(state: State, data: bytes, max_depth: int = 16,
              compact: bool = False) -> Optional[State]:
    for b in data:
        state = next_state(state, b, max_depth, compact)
        if state is None:
            return None
    return state


# ---------------------------------------------------------------------------
# token byte tables
# ---------------------------------------------------------------------------


def token_bytes_table(tokenizer, vocab_size: int) -> List[Optional[bytes]]:
    """Per-token raw bytes for mask computation; None = never sample inside
    JSON (control/special tokens, unknowable pieces)."""
    from .tokenizer import (
        SPIECE_SPACE,
        TOKEN_TYPE_BYTE,
        TOKEN_TYPE_CONTROL,
        TOKEN_TYPE_USER_DEFINED,
        ByteLevelBPE,
        ByteTokenizer,
        SentencePieceBPE,
    )

    table: List[Optional[bytes]] = [None] * vocab_size
    if isinstance(tokenizer, ByteLevelBPE):
        for i, tok in enumerate(tokenizer.tokens[:vocab_size]):
            typ = (
                tokenizer.token_types[i]
                if i < len(tokenizer.token_types)
                else 1
            )
            if typ in (TOKEN_TYPE_CONTROL, TOKEN_TYPE_USER_DEFINED):
                continue
            table[i] = bytes(
                tokenizer._u2b[c] for c in tok if c in tokenizer._u2b
            )
    elif isinstance(tokenizer, SentencePieceBPE):
        for i, tok in enumerate(tokenizer.tokens[:vocab_size]):
            typ = (
                tokenizer.token_types[i]
                if i < len(tokenizer.token_types)
                else 1
            )
            if typ == TOKEN_TYPE_CONTROL:
                continue
            if typ == TOKEN_TYPE_BYTE:
                table[i] = bytes([int(tok[3:-1], 16)])
            else:
                table[i] = tok.replace(SPIECE_SPACE, " ").encode("utf-8")
    elif isinstance(tokenizer, ByteTokenizer):
        for i in range(min(256, vocab_size)):
            table[i] = bytes([i])
    else:  # HFTokenizer: map via the underlying vocab's token STRINGS —
        # per-id decode() would strip SentencePiece's leading-space marker
        # (decode(["▁7"]) == "7") and the automaton would track different
        # bytes than the emitted text, breaking the parse guarantee
        conv = getattr(tokenizer, "_tok", None)
        if conv is not None and hasattr(conv, "convert_ids_to_tokens"):
            toks = conv.convert_ids_to_tokens(list(range(vocab_size)))
            specials = set(getattr(conv, "all_special_tokens", ()))
            from .tokenizer import _bytes_to_unicode

            u2b = {c: b for b, c in _bytes_to_unicode().items()}
            # byte-level vocabs (GPT-2/Llama-3/Qwen HF tokenizers) encode
            # space/newline as Ġ/Ċ; SentencePiece ones use ▁
            byte_level = any(
                t and ("Ġ" in t or "Ċ" in t)
                for t in toks[: min(4096, vocab_size)]
                if isinstance(t, str)
            )
            for i, t in enumerate(toks):
                if not isinstance(t, str) or t in specials:
                    continue
                if t.startswith("<0x") and t.endswith(">") and len(t) == 6:
                    table[i] = bytes([int(t[3:5], 16)])
                elif byte_level:
                    table[i] = bytes(u2b[c] for c in t if c in u2b)
                else:
                    table[i] = t.replace(SPIECE_SPACE, " ").encode("utf-8")
        else:  # last resort: per-id decode (loses space markers)
            for i in range(vocab_size):
                try:
                    s = tokenizer.decode([i])
                # aios: waive(silent-except): one-time vocab-table build — an undecodable id simply has no byte mapping (masked out)
                except Exception:  # noqa: BLE001
                    continue
                if s:
                    table[i] = s.encode("utf-8")
    return table


def distance_to_terminal(state: State) -> int:
    """Minimal BYTES to reach a terminal state — an upper bound on the
    tokens a completion needs (every token carries >= 1 byte). The budget
    feasibility gate and the closing walk both rely on this being exact:
    an underestimate admits tokens whose completion cannot fit the
    remaining budget (observed: truncation inside a \\uXXXX escape)."""
    phase, stack = state[0], state[1]
    d = len(stack)  # one closer byte per open container
    if phase == "E":
        return d
    if phase == "N":
        return d if state[2] in _NUM_DONE else d + 1
    if phase in ("S", "X", "U"):
        is_key = state[2]
        # finish the string itself...
        if phase == "S":
            extra = 1  # closing quote
        elif phase == "X":
            extra = 2  # escape char + closing quote
        else:  # U: remaining hex digits + closing quote
            extra = (4 - state[3]) + 1
        # ...keys additionally need ':' and a minimal value ('0')
        return d + extra + (2 if is_key else 0)
    if phase == "C":
        return d + 2  # ':' + minimal value
    if phase == "K1":
        return d + 4  # '""' + ':' + minimal value (empty key is legal)
    if phase == "K":
        return d  # '}' closes (counted in the stack)
    if phase == "L":
        return d + len(state[2]) - state[3]
    if phase == "V0":
        return d + 2  # '{}'
    if phase in ("V", "A"):
        return d + (0 if phase == "A" else 1)  # A may close; V needs '0'
    return d + 1


class JsonMaskCache:
    """Per-model shared cache: automaton state -> additive logits row."""

    def __init__(
        self,
        token_bytes: List[Optional[bytes]],
        eos_id: Optional[int],
        require_object: bool = True,
        max_depth: int = 16,
        byte_matrix=None,  # prebuilt (mat, lens) shared across caches
        compact: bool = False,  # canonical compact JSON (no structural ws)
    ) -> None:
        self.token_bytes = token_bytes
        self.vocab_size = len(token_bytes)
        self.eos_id = eos_id
        self.require_object = require_object
        self.max_depth = max_depth
        self.compact = compact
        self._masks: Dict[State, np.ndarray] = {}
        self._closing: Dict[State, np.ndarray] = {}
        self._dist_rows: Dict[State, np.ndarray] = {}
        # singleton cache: state -> the ONE admissible token id, or None.
        # Jump-ahead decoding (engine/batching.py) chains these into
        # multi-token forced runs emitted in a single dispatch.
        self._singleton: Dict[State, Optional[int]] = {}
        self._dev: Dict[int, object] = {}  # id(np row) -> (row, device)
        self._row_state: object = None  # state of the last mask_row call
        # vectorized-walk precompute: padded byte matrix + global automaton
        # state registry (row construction is numpy over the whole vocab
        # per byte position, not a python loop per token — a fresh state's
        # row costs ~ms even on 150k vocabs, cheap enough for the
        # scheduler thread)
        if byte_matrix is not None:
            self._byte_mat, self._byte_lens = byte_matrix
        else:
            lens = np.array(
                [len(tb) if tb else 0 for tb in token_bytes], np.int32
            )
            lmax = int(lens.max()) if len(lens) else 1
            mat = np.zeros((self.vocab_size, max(lmax, 1)), np.uint8)
            for i, tb in enumerate(token_bytes):
                if tb:
                    mat[i, : len(tb)] = np.frombuffer(tb, np.uint8)
            self._byte_mat = mat
            self._byte_lens = lens
        self._states: List[State] = []
        self._sindex: Dict[State, int] = {}
        self._dists: List[int] = []
        self._trans: Dict[Tuple[int, int], int] = {}
        # the canonical forced first token: "{" (single byte)
        self.start_token_id: Optional[int] = None
        for i, tb in enumerate(token_bytes):
            if tb == b"{":
                self.start_token_id = i
                break

    # -- grammar hooks (override for other grammars, e.g. jsonschema.py) ---

    def start(self) -> State:
        return start_state(self.require_object)

    def _transition(self, state: State, b: int) -> Optional[State]:
        return next_state(state, b, self.max_depth, self.compact)

    def _terminal(self, state: State) -> bool:
        return is_terminal(state)

    def _distance(self, state: State) -> int:
        return distance_to_terminal(state)

    def run(self, state: State, data: bytes) -> Optional[State]:
        for byte in data:
            state = self._transition(state, byte)
            if state is None:
                return None
        return state

    # ----------------------------------------------------------------------

    def _state_idx(self, state: State) -> int:
        i = self._sindex.get(state)
        if i is None:
            i = len(self._states)
            self._states.append(state)
            self._sindex[state] = i
            self._dists.append(self._distance(state))
        return i

    def _walk_vocab(self, state: State) -> np.ndarray:
        """Run every token's bytes through the automaton AT ONCE: returns
        [vocab] int32 of final global state indices (-1 = leaves the
        grammar). One numpy pass per byte position; per-(state, byte)
        transitions memoized globally across rows."""
        cur = np.full((self.vocab_size,), self._state_idx(state), np.int32)
        cur[self._byte_lens == 0] = -1  # specials / empties: never allowed
        for p in range(self._byte_mat.shape[1]):
            act = (cur >= 0) & (p < self._byte_lens)
            if not act.any():
                break
            keys = cur[act] * 256 + self._byte_mat[act, p].astype(np.int32)
            uniq = np.unique(keys)
            dest = np.empty(len(uniq), np.int32)
            for j, k in enumerate(uniq):
                si, b = divmod(int(k), 256)
                t = self._trans.get((si, b))
                if t is None:
                    ns = self._transition(self._states[si], b)
                    t = -1 if ns is None else self._state_idx(ns)
                    self._trans[(si, b)] = t
                dest[j] = t
            cur[act] = dest[np.searchsorted(uniq, keys)]
        return cur

    def mask_row(self, state: State) -> np.ndarray:
        """fp32 [vocab]: 0 where the token keeps the output in-grammar,
        NEG_INF elsewhere; EOS unmasked only at terminal states."""
        row = self._masks.get(state)
        if row is not None:
            return row
        final = self._walk_vocab(state)
        row = np.where(final >= 0, np.float32(0.0), np.float32(NEG_INF))
        if self.eos_id is not None and self._terminal(state):
            row[self.eos_id] = 0.0
        if not (row == 0.0).any():
            # dead end (can't happen from reachable states — whitespace and
            # closers are always single-byte tokens in real vocabs); fail
            # open rather than forcing argmax over -inf everywhere
            row[:] = 0.0
        self._masks[state] = row
        return row

    def closing_row(self, state: State) -> np.ndarray:
        """Like mask_row but keeps only the allowed tokens whose resulting
        state minimizes distance_to_terminal — used when a request's token
        budget is nearly spent, so the output CLOSES instead of truncating
        mid-structure (every closing step strictly walks toward terminal:
        '}'/']' pop, '\"' ends strings, digits complete numbers). At a
        terminal state only EOS survives."""
        row = self._closing.get(state)
        if row is not None:
            return row
        if self.eos_id is not None and self._terminal(state):
            row = np.full((self.vocab_size,), NEG_INF, np.float32)
            row[self.eos_id] = 0.0
            self._closing[state] = row
            return row
        fd = self.dist_row(state)
        row = np.full((self.vocab_size,), NEG_INF, np.float32)
        if fd.min() < np.iinfo(np.int32).max:
            row[fd == fd.min()] = 0.0
        else:
            row[:] = 0.0  # same fail-open rule as mask_row
        self._closing[state] = row
        return row

    def dist_row(self, state: State) -> np.ndarray:
        """int32 [vocab]: distance-to-terminal of the state each token
        leads to (INT32_MAX for out-of-grammar tokens). The budget
        feasibility gate reads this; cached per state."""
        cached = self._dist_rows.get(state)
        if cached is not None:
            return cached
        final = self._walk_vocab(state)
        valid = final >= 0
        dists = np.asarray(self._dists, np.int32)
        fd = np.where(
            valid, dists[np.maximum(final, 0)], np.iinfo(np.int32).max
        ).astype(np.int32)
        self._dist_rows[state] = fd
        return fd

    def effective_row(self, state: State, remaining: Optional[int] = None
                      ) -> np.ndarray:
        """The row a constrained dispatch actually applies from ``state``.
        With ``remaining`` (token budget left), tokens are additionally
        gated on BUDGET FEASIBILITY: a token is allowed only if the state
        it leads to can still complete within remaining-1 more tokens
        (distances are bytes, an upper bound on tokens, so feasibility is
        conservative). By induction the output always completes once the
        budget ever covered the current distance; a budget infeasible
        from the start degrades to the pure min-distance closing walk."""
        self._row_state = state  # device_row cacheability hint
        base = self.mask_row(state)
        if remaining is None:
            return base
        fd = self.dist_row(state)
        finite = fd[fd < np.iinfo(np.int32).max]
        if finite.size and int(finite.min()) > remaining - 1:
            # nothing fits: close as fast as possible (margin was blown
            # before the constraint started, e.g. max_tokens < minimal
            # completion)
            return self.closing_row(state)
        if finite.size and int(finite.max()) <= remaining - 1:
            return base  # every in-grammar token fits: cached row as-is
        row = np.where(
            (base == 0.0) & (fd <= remaining - 1),
            np.float32(0.0),
            np.float32(NEG_INF),
        )
        if self.eos_id is not None and self._terminal(state):
            row[self.eos_id] = 0.0
        return row

    def singleton_token(self, state: State) -> Optional[int]:
        """The single admissible token from ``state``, or None when the
        mask admits several (or fail-opened). Singleton states are where
        the grammar FORCES the next token — schema key literals, ``":``,
        ``",``, closing braces — and chains of them are emitted as one
        jump-ahead run instead of one masked dispatch each."""
        tok = self._singleton.get(state, -1)
        if tok != -1:
            return tok
        row = self.mask_row(state)
        nz = np.flatnonzero(row == 0.0)
        tok = int(nz[0]) if nz.size == 1 else None
        self._singleton[state] = tok
        return tok

    def device_row(self, row: np.ndarray):
        """Device-resident copy of a mask row — the per-step [slots, vocab]
        mask is then assembled ON DEVICE (jnp.stack of cached rows), so
        steady-state constrained decoding moves no mask bytes over PCIe.

        The cache entry PINS the numpy row (id()-keyed lookups are only
        sound while the array is alive — a temporary row's recycled id
        must never alias a stale device mask) and the dict is bounded:
        budget-hybrid rows near the end of a generation are fresh arrays,
        one per step."""
        import jax.numpy as jnp

        key = id(row)
        got = self._dev.get(key)
        if got is not None and got[0] is row:
            return got[1]
        dev = jnp.asarray(row)
        # only PERSISTENT rows (the per-state entries of _masks/_closing)
        # earn a cache slot — budget-hybrid rows are one-shot temporaries
        # and would pin host+device memory until the wholesale clear
        if row is self._masks.get(self._row_state) or row is (
            self._closing.get(self._row_state)
        ):
            if len(self._dev) > 512:
                self._dev.clear()
            self._dev[key] = (row, dev)
        return dev

    def zeros_row(self):
        """Device-resident all-zeros (unconstrained) row. The batcher no
        longer stacks this per unconstrained slot — it scatters only the
        constrained rows into a cached [slots, vocab] zeros base — but
        single-row callers (tests, external grammars) keep the helper."""
        import jax.numpy as jnp

        got = self._dev.get("zeros")
        if got is None:
            got = jnp.zeros((self.vocab_size,), jnp.float32)
            self._dev["zeros"] = got
        return got


class JsonConstraint:
    """Per-request automaton cursor over a shared JsonMaskCache."""

    def __init__(self, cache: JsonMaskCache) -> None:
        self.cache = cache
        self.state: State = cache.start()
        self.failed = False

    def mask_row(self, remaining: Optional[int] = None) -> np.ndarray:
        """Mask for the next step — ``JsonMaskCache.effective_row`` at the
        cursor's state (budget-feasibility gating documented there)."""
        return self.cache.effective_row(self.state, remaining)

    def forced_run(
        self,
        max_len: int,
        remaining: Optional[int] = None,
        stop_ids: Tuple[int, ...] = (),
    ) -> List[int]:
        """Longest chain of grammar-FORCED tokens from the current state
        (compressed-FSM jump-ahead): each step's effective mask admits
        exactly one token, so ANY sampler must emit it — the batcher
        emits the whole run host-side and appends its KV in one
        multi-token dispatch (engine.jump_step) instead of len(run)
        masked single-token dispatches. Does NOT advance the cursor
        (``advance`` each token after the dispatch lands).

        Detection stops — conservatively, keeping token streams identical
        to the per-step path — when the budget-feasibility gate would
        alter the cached base row, at EOS/stop tokens, or at ``max_len``.
        """
        if self.failed or max_len <= 0:
            return []
        out: List[int] = []
        cache, state, rem = self.cache, self.state, remaining
        imax = np.iinfo(np.int32).max
        while len(out) < max_len:
            if rem is not None:
                fd = cache.dist_row(state)
                finite = fd[fd < imax]
                if not finite.size or int(finite.max()) > rem - 1:
                    break  # budget gating kicks in: per-step path decides
            tok = cache.singleton_token(state)
            if tok is None:
                break
            out.append(tok)
            if tok == cache.eos_id or tok in stop_ids:
                break
            tb = (
                cache.token_bytes[tok]
                if 0 <= tok < cache.vocab_size
                else None
            )
            if not tb:
                break  # byteless singleton: the cursor would freeze
            nxt = cache.run(state, tb)
            if nxt is None:
                break  # unreachable for an admitted token; fail safe
            state = nxt
            if rem is not None:
                rem -= 1
        return out

    def device_mask(self, remaining: Optional[int] = None):
        """Device-resident mask row for the next step (no per-step PCIe)."""
        return self.cache.device_row(self.mask_row(remaining))

    def advance(self, token_id: int) -> None:
        """Feed an emitted token. EOS (or any masked-out id, which only a
        raced/failed state produces) freezes the cursor."""
        if self.failed:
            return
        if token_id == self.cache.eos_id:
            return
        tb = (
            self.cache.token_bytes[token_id]
            if 0 <= token_id < self.cache.vocab_size
            else None
        )
        if not tb:
            self.failed = True
            return
        nxt = self.cache.run(self.state, tb)
        if nxt is None:
            self.failed = True
            return
        self.state = nxt

    @property
    def satisfied(self) -> bool:
        return not self.failed and self.cache._terminal(self.state)
