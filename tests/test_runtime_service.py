"""AIRuntime service: RPC surface, routing ladders, error codes, streaming.

Mirrors the reference's runtime service tests (grpc_service.rs:240-336 test
the Unavailable/InvalidArgument/FailedPrecondition paths by direct handler
invocation) but goes over a live localhost socket with a real tiny engine.
"""

import grpc
import pytest

from aios_tpu import rpc, services
from aios_tpu.proto_gen import common_pb2, runtime_pb2
from aios_tpu.runtime.model_manager import ModelManager
from aios_tpu.runtime.service import RuntimeService, serve


@pytest.fixture(scope="module")
def runtime_stub():
    manager = ModelManager(num_slots=2, warm_compile=False)
    server, service, port = serve(address="127.0.0.1:0", manager=manager, block=False)
    channel = rpc.insecure_channel(f"127.0.0.1:{port}")
    yield services.AIRuntimeStub(channel), manager
    channel.close()
    server.stop(grace=None)


def test_no_models_unavailable(runtime_stub):
    stub, _ = runtime_stub
    with pytest.raises(grpc.RpcError) as err:
        stub.Infer(runtime_pb2.InferRequest(prompt="hi"))
    assert err.value.code() == grpc.StatusCode.UNAVAILABLE


def test_reactive_level_rejected(runtime_stub):
    stub, _ = runtime_stub
    with pytest.raises(grpc.RpcError) as err:
        stub.Infer(
            runtime_pb2.InferRequest(prompt="hi", intelligence_level="reactive")
        )
    assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT


def test_load_model_and_infer(runtime_stub):
    stub, _ = runtime_stub
    status = stub.LoadModel(
        runtime_pb2.LoadModelRequest(
            model_name="tinyllama-test", model_path="synthetic://tiny-test"
        )
    )
    assert status.status == "ready"
    assert status.port == 0  # no HTTP sidecar on the TPU backend

    resp = stub.Infer(
        runtime_pb2.InferRequest(prompt="hello", max_tokens=8, temperature=0.0)
    )
    assert resp.model_used == "tinyllama-test"
    assert resp.tokens_used > 0
    assert resp.latency_ms >= 0

    models = stub.ListModels(common_pb2.Empty())
    assert [m.model_name for m in models.models] == ["tinyllama-test"]
    assert models.models[0].request_count >= 1


def test_operational_level_routes_to_tinyllama(runtime_stub):
    stub, _ = runtime_stub
    resp = stub.Infer(
        runtime_pb2.InferRequest(
            prompt="status?", intelligence_level="operational", max_tokens=4
        )
    )
    assert resp.model_used == "tinyllama-test"


def test_strategic_without_big_model_failed_precondition(runtime_stub):
    stub, _ = runtime_stub
    with pytest.raises(grpc.RpcError) as err:
        stub.Infer(
            runtime_pb2.InferRequest(
                prompt="plan", intelligence_level="strategic", max_tokens=4
            )
        )
    assert err.value.code() == grpc.StatusCode.FAILED_PRECONDITION
    assert "api-gateway" in err.value.details()


def test_explicit_unknown_model_not_found(runtime_stub):
    stub, _ = runtime_stub
    with pytest.raises(grpc.RpcError) as err:
        stub.Infer(runtime_pb2.InferRequest(prompt="x", model="nonexistent-13b"))
    assert err.value.code() == grpc.StatusCode.NOT_FOUND


def test_partial_name_matching(runtime_stub):
    stub, _ = runtime_stub
    resp = stub.Infer(
        runtime_pb2.InferRequest(prompt="x", model="TinyLlama", max_tokens=4)
    )
    assert resp.model_used == "tinyllama-test"


def test_stream_infer_token_by_token(runtime_stub):
    stub, _ = runtime_stub
    chunks = list(
        stub.StreamInfer(
            runtime_pb2.InferRequest(prompt="hello", max_tokens=6, temperature=0.0)
        )
    )
    assert chunks[-1].done
    assert all(not c.done for c in chunks[:-1])
    # genuinely incremental: more than one content chunk
    assert len(chunks) >= 2


def test_health_reports_models(runtime_stub):
    stub, _ = runtime_stub
    h = stub.HealthCheck(common_pb2.Empty())
    assert h.healthy
    assert h.details["backend"] == "jax-tpu"
    assert h.details["tinyllama-test"] == "ready"
    # serving counters ride the details map (additive observability)
    serving = h.details["tinyllama-test.serving"]
    assert "decode_steps=" in serving
    assert "completed=" in serving


def test_unload_model(runtime_stub):
    stub, manager = runtime_stub
    stub.LoadModel(
        runtime_pb2.LoadModelRequest(
            model_name="scratch", model_path="synthetic://tiny-test"
        )
    )
    out = stub.UnloadModel(runtime_pb2.UnloadModelRequest(model_name="scratch"))
    assert out.success
    out2 = stub.UnloadModel(runtime_pb2.UnloadModelRequest(model_name="scratch"))
    assert not out2.success
    assert manager.get("scratch") is None


def test_load_error_returns_internal(runtime_stub):
    stub, _ = runtime_stub
    with pytest.raises(grpc.RpcError) as err:
        stub.LoadModel(
            runtime_pb2.LoadModelRequest(
                model_name="bad", model_path="/nonexistent/file.gguf"
            )
        )
    assert err.value.code() == grpc.StatusCode.INTERNAL


def test_paged_auto_sizes_pool_from_slots_and_context(monkeypatch):
    """AIOS_TPU_PAGED_KV=auto (the production boot default) serves over a
    paged pool sized (num_slots + 1) x context with the prefix index on —
    the dense cache's HBM plus one slot of prefix-retention slack."""
    monkeypatch.setenv("AIOS_TPU_PAGED_KV", "auto")
    from aios_tpu.runtime.model_manager import ModelManager

    mgr = ModelManager(num_slots=2, warm_compile=False)
    assert mgr.paged_pool_rows == "auto"
    m = mgr.load_model("tiny", "synthetic://tiny-test", context_length=128)
    try:
        eng = m.engine
        assert eng.paged
        assert eng.prefix_index is not None
        rows = (2 + 1) * 128
        # pool pages = 1 sacrificial + rows/page_size (page_size 128)
        assert eng.allocator.num_pages == 1 + rows // 128
    finally:
        mgr.unload_model("tiny")


def test_mesh_env_builds_sharding_plan(monkeypatch):
    """AIOS_TPU_MESH (the [models] mesh boot knob) gives the production
    runtime a multi-chip plan; malformed or oversized specs degrade to
    single-chip serving instead of failing boot."""
    from aios_tpu.runtime.model_manager import ModelManager

    monkeypatch.setenv("AIOS_TPU_MESH", "dp=2,tp=2")
    mgr = ModelManager(num_slots=2, warm_compile=False)
    assert mgr.plan is not None
    assert mgr.plan.dp == 2 and mgr.plan.tp == 2 and mgr.plan.sp == 1
    m = mgr.load_model("tiny", "synthetic://tiny-test", context_length=128)
    try:
        assert m.state == "ready"
        assert m.engine.step(2).shape[1] == 2
    finally:
        mgr.unload_model("tiny")

    monkeypatch.setenv("AIOS_TPU_MESH", "tp=999")
    assert ModelManager(num_slots=2, warm_compile=False).plan is None
    monkeypatch.setenv("AIOS_TPU_MESH", "bogus")
    assert ModelManager(num_slots=2, warm_compile=False).plan is None
    monkeypatch.setenv("AIOS_TPU_MESH", "tp=1")
    assert ModelManager(num_slots=2, warm_compile=False).plan is None


def test_long_context_auto_degrades_to_seq_sharded(monkeypatch):
    """With sp > 1 in the mesh, a model whose KV cache exceeds the
    per-chip HBM budget automatically gives up the paged pool and shards
    its context axis over sp (VERDICT r4 item 7's graceful path) — while a
    model that fits keeps paging."""
    from aios_tpu.runtime.model_manager import ModelManager

    monkeypatch.setenv("AIOS_TPU_MESH", "sp=2")
    monkeypatch.setenv("AIOS_TPU_PAGED_KV", "auto")

    # tiny budget: even the tiny-test cache overflows -> seq-sharded
    monkeypatch.setenv("AIOS_TPU_HBM_GB", "0.000001")
    mgr = ModelManager(num_slots=2, warm_compile=False)
    assert mgr.plan is not None and mgr.plan.sp == 2
    m = mgr.load_model("tiny", "synthetic://tiny-test", context_length=128)
    try:
        assert m.engine.seq_sharded and not m.engine.paged
        assert m.state == "ready"
        assert m.engine.step(2).shape[1] == 2
    finally:
        mgr.unload_model("tiny")

    # ample budget: paging is kept — the pool replicates over the unused
    # sp axis and decode still executes
    monkeypatch.setenv("AIOS_TPU_HBM_GB", "16")
    mgr2 = ModelManager(num_slots=2, warm_compile=False)
    m2 = mgr2.load_model("tiny", "synthetic://tiny-test", context_length=128)
    try:
        assert m2.engine.paged and not m2.engine.seq_sharded
        assert m2.state == "ready"
        assert m2.engine.step(2).shape[1] == 2
    finally:
        mgr2.unload_model("tiny")


def test_hbm_budget_counts_co_resident_models(monkeypatch):
    """The auto-degrade budget charges models already resident in the
    manager: with a budget sized for ~one model, the first keeps its paged
    pool and the second (identical) model degrades to the seq-sharded
    cache instead of overflowing HBM."""
    from aios_tpu.runtime.model_manager import ModelManager

    monkeypatch.setenv("AIOS_TPU_MESH", "sp=2")
    monkeypatch.setenv("AIOS_TPU_PAGED_KV", "auto")
    monkeypatch.setenv("AIOS_TPU_HBM_GB", "16")  # ample: measure footprint
    probe = ModelManager(num_slots=2, warm_compile=False)
    ma = probe.load_model("a", "synthetic://tiny-test", context_length=128)
    footprint = ma.hbm_chip_bytes
    assert footprint > 0
    probe.unload_model("a")

    # budget ~= 2x one model's footprint minus a sliver: model A fits
    # paged; model B's KV no longer does once A is counted
    monkeypatch.setenv(
        "AIOS_TPU_HBM_GB", str((2 * footprint - 1) / 0.85 / 1e9)
    )
    mgr = ModelManager(num_slots=2, warm_compile=False)
    a = mgr.load_model("a", "synthetic://tiny-test", context_length=128)
    b = mgr.load_model("b", "synthetic://tiny-test", context_length=128)
    try:
        assert a.engine.paged and not a.engine.seq_sharded
        assert b.engine.seq_sharded and not b.engine.paged
    finally:
        mgr.unload_model("a")
        mgr.unload_model("b")


def test_seq_shard_force_wins_over_paging(monkeypatch):
    """An explicit AIOS_TPU_SEQ_SHARD_KV=1 drops the default paged pool
    and shards the context axis (the operator's force outranks the paging
    default — they are exclusive on one engine)."""
    from aios_tpu.runtime.model_manager import ModelManager

    monkeypatch.setenv("AIOS_TPU_MESH", "sp=2")
    monkeypatch.setenv("AIOS_TPU_PAGED_KV", "auto")
    monkeypatch.setenv("AIOS_TPU_SEQ_SHARD_KV", "1")
    monkeypatch.setenv("AIOS_TPU_HBM_GB", "16")
    mgr = ModelManager(num_slots=2, warm_compile=False)
    m = mgr.load_model("tiny", "synthetic://tiny-test", context_length=128)
    try:
        assert m.engine.seq_sharded and not m.engine.paged
    finally:
        mgr.unload_model("tiny")


def test_hbm_shortfall_warns_without_sp_axis(monkeypatch, caplog):
    """A KV cache that cannot fit per-chip HBM on a mesh with no sp axis
    (or a single chip) still WARNS at load, so the first symptom is not a
    serve-time OOM."""
    import logging

    from aios_tpu.runtime.model_manager import ModelManager

    monkeypatch.setenv("AIOS_TPU_HBM_GB", "0.000001")
    monkeypatch.delenv("AIOS_TPU_MESH", raising=False)
    mgr = ModelManager(num_slots=2, warm_compile=False)
    with caplog.at_level(logging.WARNING, logger="aios.runtime.models"):
        m = mgr.load_model("tiny", "synthetic://tiny-test", context_length=128)
    try:
        assert not m.engine.seq_sharded  # nothing to degrade onto
        assert any(
            "seq-sharded degradation is unavailable" in r.message
            for r in caplog.records
        )
    finally:
        mgr.unload_model("tiny")
