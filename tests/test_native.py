"""Native C++ primitives: SHA-256 vs hashlib, ring semantics, token bucket."""

import hashlib
import time

import pytest

from aios_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)


def test_sha256_matches_hashlib():
    for payload in (b"", b"a", b"hello world", bytes(range(256)) * 10):
        assert native.sha256_hex(payload) == hashlib.sha256(payload).hexdigest()


def test_chain_hash_matches_python_composition():
    prev = "0" * 64
    payload = b'{"record": 1}'
    want = hashlib.sha256(prev.encode() + payload).hexdigest()
    assert native.chain_hash(prev, payload) == want


def test_ring_capacity_and_order():
    r = native.NativeRing(capacity=3)
    for i in range(5):
        r.push(f"event-{i}".encode())
    assert len(r) == 3
    assert r.total_pushed == 5
    assert r.recent(10) == [b"event-4", b"event-3", b"event-2"]


def test_ring_large_items():
    r = native.NativeRing(capacity=2)
    big = b"x" * (100 * 1024)  # larger than the 64 KiB default read buffer
    r.push(big)
    assert r.recent(1) == [big]


def test_token_bucket_burst_and_refill():
    b = native.NativeTokenBucket(rate=1000.0, capacity=5.0)
    allowed = sum(1 for _ in range(10) if b.try_acquire())
    assert allowed == 5  # burst capped at capacity
    time.sleep(0.01)  # 1000/s refills ~10 tokens -> capped at 5
    assert b.try_acquire()


def test_token_bucket_denies_past_capacity():
    b = native.NativeTokenBucket(rate=0.001, capacity=1.0)
    assert b.try_acquire()
    assert not b.try_acquire()
