"""AI reasoning path over real sockets (VERDICT r2 weak #8).

The multi-round reasoning loop previously ran only against injected fake
backends in unit tests. Here the WHOLE wire path runs: goal over orchestrator
gRPC -> autonomy loop -> gateway gRPC -> scripted qwen3 HTTP provider
emitting tool_calls JSON -> REAL tool-registry gRPC executions -> goal
completion; plus the awaiting_input 3-strike flow (autonomy.rs:100-224,
2431-2480) and the per-level token budget visible in the intercepted
provider request (autonomy.rs:596-607).
"""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from aios_tpu import rpc, services
from aios_tpu.proto_gen import common_pb2, orchestrator_pb2

# compile-heavy tier: excluded from the fast commit gate (pytest -m fast)
pytestmark = pytest.mark.slow


class _ScriptedProvider(BaseHTTPRequestHandler):
    """OpenAI-protocol stub: pops scripted replies; records request bodies."""

    replies: list = []
    requests: list = []
    default_reply = json.dumps(
        {"thought": "what exactly should I do?", "tool_calls": [], "done": True}
    )

    def do_POST(self):
        body = json.loads(self.rfile.read(int(self.headers["Content-Length"])))
        cls = type(self)
        cls.requests.append(body)
        prompt = body["messages"][-1]["content"]
        if "Decompose this goal" in prompt:
            # planner's AI decomposition round: keep the goal as one task so
            # the scripted replies below drive the REASONING loop
            text = json.dumps([
                {"description": prompt.split("Goal: ", 1)[1].split("\n")[0],
                 "required_tools": ["monitor"]}
            ])
        else:
            text = cls.replies.pop(0) if cls.replies else cls.default_reply
        resp = {
            "model": body.get("model", "qwen3"),
            "choices": [{"message": {"content": text}}],
            "usage": {"prompt_tokens": 50, "completion_tokens": 30},
        }
        out = json.dumps(resp).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(out)))
        self.end_headers()
        self.wfile.write(out)

    def log_message(self, *args):
        pass


@pytest.fixture(scope="module")
def stack(tmp_path_factory, module_monkeypatch=None):
    import os

    tmp = tmp_path_factory.mktemp("e2e-ai")
    servers = []

    http_server = HTTPServer(("127.0.0.1", 0), _ScriptedProvider)
    threading.Thread(target=http_server.serve_forever, daemon=True).start()

    old_env = {}

    def setenv(k, v):
        old_env.setdefault(k, os.environ.get(k))
        os.environ[k] = v

    setenv("QWEN3_API_KEY", "scripted")
    setenv("QWEN3_BASE_URL", f"http://127.0.0.1:{http_server.server_port}")
    for var in ("CLAUDE_API_KEY", "OPENAI_API_KEY"):
        old_env.setdefault(var, os.environ.get(var))
        os.environ.pop(var, None)

    from aios_tpu.tools.executor import ToolExecutor
    from aios_tpu.tools.service import serve as serve_tools

    tools_server, _, tools_port = serve_tools(
        address="127.0.0.1:0",
        executor=ToolExecutor(
            audit_path=str(tmp / "audit.db"),
            backup_dir=str(tmp / "backups"),
            plugin_dir=str(tmp / "plugins"),
        ),
        block=False,
    )
    servers.append(tools_server)

    from aios_tpu.memory.service import serve as serve_memory

    mem_server, _, mem_port = serve_memory(address="127.0.0.1:0", block=False)
    servers.append(mem_server)

    # runtime service with no model loaded: the scripted gateway never
    # falls through to it, but the socket must exist for the clients
    from aios_tpu.runtime.model_manager import ModelManager
    from aios_tpu.runtime.service import serve as serve_runtime

    rt_server, _, rt_port = serve_runtime(
        address="127.0.0.1:0",
        manager=ModelManager(num_slots=2, warm_compile=False),
        block=False,
    )
    servers.append(rt_server)

    from aios_tpu.gateway.router import RequestRouter
    from aios_tpu.gateway.service import serve as serve_gateway

    gw_server, _, gw_port = serve_gateway(
        address="127.0.0.1:0",
        router=RequestRouter(runtime_address=f"127.0.0.1:{rt_port}"),
        block=False,
    )
    servers.append(gw_server)

    from aios_tpu.orchestrator.autonomy import AutonomyConfig
    from aios_tpu.orchestrator.clients import ServiceClients
    from aios_tpu.orchestrator.main import build_orchestrator
    from aios_tpu.orchestrator.service import serve as serve_orch

    clients = ServiceClients(
        runtime_addr=f"127.0.0.1:{rt_port}",
        tools_addr=f"127.0.0.1:{tools_port}",
        memory_addr=f"127.0.0.1:{mem_port}",
        gateway_addr=f"127.0.0.1:{gw_port}",
    )
    (service, autonomy, scheduler, proactive, health, bus,
     _serving) = build_orchestrator(
        data_dir=str(tmp / "orch"),
        clients=clients,
        autonomy_config=AutonomyConfig(
            tick_interval=0.05, preferred_provider="qwen3"
        ),
    )
    autonomy.start()
    orch_server, _, orch_port = serve_orch(
        address="127.0.0.1:0", service=service, block=False
    )
    servers.append(orch_server)

    channel = rpc.insecure_channel(f"127.0.0.1:{orch_port}")
    yield services.OrchestratorStub(channel)

    autonomy.stop()
    channel.close()
    for server in servers:
        server.stop(grace=None)
    http_server.shutdown()
    for k, v in old_env.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def _wait_goal(stub, goal_id, want_states, timeout=30):
    deadline = time.time() + timeout
    status = None
    while time.time() < deadline:
        status = stub.GetGoalStatus(common_pb2.GoalId(id=goal_id))
        if status.goal.status in want_states:
            return status
        time.sleep(0.2)
    return status


def test_ai_reasoning_rounds_with_real_tools(stack):
    """Two scripted rounds: tool_calls -> real tools gRPC -> done."""
    _ScriptedProvider.requests = []
    _ScriptedProvider.replies = [
        json.dumps({
            "thought": "inspect the system first",
            "tool_calls": [{"tool": "monitor.cpu", "args": {}},
                           {"tool": "monitor.memory", "args": {}}],
            "done": False,
        }),
        json.dumps({
            "thought": "system is healthy, nothing anomalous",
            "tool_calls": [],
            "done": True,
        }),
    ]
    gid = stack.SubmitGoal(orchestrator_pb2.SubmitGoalRequest(
        description="investigate strange log entries", source="e2e",
    ))
    status = _wait_goal(stack, gid.id, ("completed", "failed"))
    assert status.goal.status == "completed", status
    reasoning = [
        r["messages"][-1]["content"] for r in _ScriptedProvider.requests
        if "Decompose this goal" not in r["messages"][-1]["content"]
    ]
    # both scripted rounds consumed over the wire
    assert len(reasoning) >= 2
    # round 2's prompt contains the REAL tool results relayed from the
    # tool-registry service, proving the tools ran over gRPC
    assert "monitor.cpu" in reasoning[1]
    assert '"success": true' in reasoning[1]


def test_reasoning_request_carries_tactical_token_budget(stack):
    """The intercepted provider request shows the per-level budget
    (tactical = 8192) flowing goal -> autonomy -> gateway -> provider."""
    _ScriptedProvider.requests = []
    _ScriptedProvider.replies = [
        json.dumps({"thought": "done", "tool_calls": [
            {"tool": "monitor.cpu", "args": {}}], "done": True}),
    ]
    gid = stack.SubmitGoal(orchestrator_pb2.SubmitGoalRequest(
        description="investigate flaky scheduled reports", source="e2e",
    ))
    status = _wait_goal(stack, gid.id, ("completed", "failed"))
    assert status.goal.status == "completed", status
    budgets = {
        r["max_tokens"] for r in _ScriptedProvider.requests
        if "Decompose this goal" not in r["messages"][-1]["content"]
    }
    assert budgets == {8192}, budgets


def test_awaiting_input_three_strikes_fails_goal(stack):
    """A provider that never emits tool calls: the goal goes through the
    awaiting-input retry flow and fails after MAX_AI_MESSAGES strikes."""
    _ScriptedProvider.requests = []
    _ScriptedProvider.replies = []  # default_reply: clarifying question only
    gid = stack.SubmitGoal(orchestrator_pb2.SubmitGoalRequest(
        description="investigate mysterious intermittent anomaly", source="e2e",
    ))
    status = _wait_goal(stack, gid.id, ("failed",), timeout=45)
    assert status is not None and status.goal.status == "failed", status
    reasoning = [
        r for r in _ScriptedProvider.requests
        if "Decompose this goal" not in r["messages"][-1]["content"]
    ]
    # three clarifying-question rounds crossed the wire before the strike-out
    assert len(reasoning) >= 3
