"""Agent framework: BaseAgent lifecycle + all 10 agents with mocked tools.

Follows the reference's mocked-gRPC pattern (python tests conftest.py:29-37
injects MagicMock channels; per-agent tests patch call_tool/think) — here the
tool/think/memory layers are patched directly on the agent instances.
"""

from unittest.mock import MagicMock, patch

import pytest

from aios_tpu.agents import AGENT_TYPES, agent_class
from aios_tpu.agents.base import BaseAgent
from aios_tpu.agents.catalog import (
    CreatorAgent,
    MonitoringAgent,
    NetworkAgent,
    PackageAgent,
    SecurityAgent,
    StorageAgent,
    SystemAgent,
    TaskAgent,
    WebAgent,
)
from aios_tpu.agents.spawner import AgentConfig, load_agent_configs


class HarnessAgent(BaseAgent):
    """Concrete subclass exercising the ABC (test_base_agent.py:26 style)."""

    def get_agent_type(self):
        return "system"

    def get_capabilities(self):
        return ["monitor.read"]

    def get_tool_namespaces(self):
        return ["monitor"]

    def handle_task(self, task):
        if "explode" in task["description"]:
            raise RuntimeError("kaboom")
        return {"handled": task["description"]}


def _tool_ok(output=None):
    def call_tool(tool, args=None, reason=""):
        return {"success": True, "output": output or {"tool": tool},
                "error": "", "execution_id": "e1"}

    return call_tool


# ---------------------------------------------------------------------------
# BaseAgent
# ---------------------------------------------------------------------------


def test_execute_task_bookkeeping():
    a = HarnessAgent(name="t-1")
    ok = a.execute_task({"id": "x", "description": "do a thing",
                         "input": {}})
    assert ok["success"] and ok["output"] == {"handled": "do a thing"}
    assert a.tasks_completed == 1 and a.status == "idle"

    bad = a.execute_task({"id": "y", "description": "explode now",
                          "input": {}})
    assert not bad["success"] and "kaboom" in bad["error"]
    assert a.tasks_failed == 1


def test_agent_ids_and_types():
    for atype in AGENT_TYPES:
        cls = agent_class(atype)
        agent = cls()
        assert agent.get_agent_type() == atype
        assert agent.agent_id.startswith(f"{atype}_agent-")
        assert agent.get_tool_namespaces()
        agent_class(atype)  # idempotent resolution


def test_all_ten_agent_types_exist():
    assert len(AGENT_TYPES) == 10  # reference has 10 (not the README's 8)


# ---------------------------------------------------------------------------
# Individual agents (mocked tool layer)
# ---------------------------------------------------------------------------


def test_system_agent_restart_flow():
    a = SystemAgent(name="sys-t")
    calls = []

    def call_tool(tool, args=None, reason=""):
        calls.append(tool)
        return {"success": True, "output": {"state": "active"}, "error": ""}

    a.call_tool = call_tool
    out = a.handle_task({"id": "t", "description": "restart the nginx service",
                         "input": {}})
    assert calls == ["service.status", "service.restart", "service.status"]
    assert out["service"] == "nginx"


def test_system_agent_restart_failure_raises():
    a = SystemAgent(name="sys-t")

    def call_tool(tool, args=None, reason=""):
        ok = tool != "service.restart"
        return {"success": ok, "output": {}, "error": "unit not found"}

    a.call_tool = call_tool
    with pytest.raises(RuntimeError):
        a.handle_task({"id": "t", "description": "restart the ghost service",
                       "input": {}})


def test_network_agent_connectivity_probe():
    a = NetworkAgent(name="net-t")
    a.call_tool = _tool_ok({"reachable": True})
    out = a.handle_task({"id": "t", "description": "check connectivity",
                         "input": {}})
    assert set(out["probes"]) == set(NetworkAgent.PROBE_HOSTS)


def test_security_agent_full_sweep():
    a = SecurityAgent(name="sec-t")
    seen = []

    def call_tool(tool, args=None, reason=""):
        seen.append(tool)
        return {"success": True, "output": {}, "error": ""}

    a.call_tool = call_tool
    a.handle_task({"id": "t", "description": "run a security sweep",
                   "input": {}})
    assert "sec.scan" in seen and "sec.scan_rootkits" in seen


def test_package_agent_install_checks_search_first():
    a = PackageAgent(name="pkg-t")
    calls = []

    def call_tool(tool, args=None, reason=""):
        calls.append((tool, args))
        if tool == "pkg.search":
            return {"success": True, "output": {"results": ["htop - viewer"]},
                    "error": ""}
        return {"success": True, "output": {"installed": args["name"]},
                "error": ""}

    a.call_tool = call_tool
    out = a.handle_task({"id": "t", "description": "install htop",
                         "input": {}})
    assert calls[0][0] == "pkg.search"
    assert out["installed"] == "htop"


def test_package_agent_install_missing_package():
    a = PackageAgent(name="pkg-t")
    a.call_tool = lambda tool, args=None, reason="": {
        "success": True, "output": {"results": []}, "error": ""}
    with pytest.raises(RuntimeError):
        a.handle_task({"id": "t", "description": "install doesnotexist",
                       "input": {}})


def test_monitoring_agent_anomaly_detection():
    a = MonitoringAgent(name="mon-t")
    for _ in range(50):
        assert not a.observe("cpu", 20.0)
    # flat baseline then a huge spike -> anomaly
    assert a.observe("cpu", 99.0)
    assert not a.observe("cpu", 20.5)


def test_monitoring_agent_scrapes_runtime_serving_stats():
    """The serving counters in the runtime HealthCheck details land in the
    metric store under runtime.<model>.*, with anomaly events on pool
    exhaustion."""
    from aios_tpu.proto_gen import common_pb2

    a = MonitoringAgent(name="mon-t2")
    health = common_pb2.HealthStatus(healthy=True, service="runtime")
    health.details["tiny"] = "ready"
    health.details["tiny.serving"] = (
        "decode_steps=42,kv_pages_free=0,spec_tokens_per_round=3.5"
    )
    stub = MagicMock()
    stub.HealthCheck.return_value = health
    a._stubs = {"runtime": stub}
    metrics, events = {}, []
    a.update_metric = lambda k, v: metrics.__setitem__(k, v)
    a.push_event = lambda cat, data, critical=False: events.append(
        (cat, data, critical)
    )
    # observe() needs a baseline before flagging; prime kv_pages_free high
    for _ in range(20):
        a.observe("runtime.tiny.kv_pages_free", 50.0)
    a.collect_serving_metrics()
    assert metrics["runtime.tiny.decode_steps"] == 42.0
    assert metrics["runtime.tiny.spec_tokens_per_round"] == 3.5
    # pool hit zero against a healthy baseline -> critical anomaly
    assert any(
        data["metric"] == "runtime.tiny.kv_pages_free" and critical
        for _, data, critical in events
    )


def test_monitoring_agent_serving_scrape_survives_runtime_down():
    a = MonitoringAgent(name="mon-t3")
    stub = MagicMock()
    stub.HealthCheck.side_effect = RuntimeError("unavailable")
    a._stubs = {"runtime": stub}
    a.collect_serving_metrics()  # must not raise


def test_learning_agent_stores_recurring_patterns():
    a = agent_class("learning")(name="learn-t")
    a.get_recent_events = lambda count=100: (
        [{"category": "disk.full", "source": "x", "data": {}, "timestamp": 0}] * 6
        + [{"category": "rare.event", "source": "x", "data": {}, "timestamp": 0}]
    )
    stored = []
    a.store_pattern = lambda trigger, action, success_rate=1.0: stored.append(trigger)
    a.update_metric = lambda k, v: None
    out = a.learn_cycle()
    assert stored == ["disk.full"]
    assert out["recurring"]["disk.full"] == 6


def test_storage_agent_backup():
    a = StorageAgent(name="sto-t")
    a.call_tool = _tool_ok()
    out = a.handle_task({"id": "t", "description": "backup the config",
                         "input": {"src": "/etc/x", "dst": "/tmp/y"}})
    assert out["backed_up"] == "/etc/x"


def test_task_agent_plans_with_think():
    a = TaskAgent(name="task-t")
    a.assemble_context = lambda d, max_tokens=512: "ctx"
    a.think = lambda prompt, level="operational", max_tokens=512: (
        '[{"tool": "monitor.cpu", "args": {}}, {"tool": "fs.list", "args": {"path": "/tmp"}}]'
    )
    executed = []

    def call_tool(tool, args=None, reason=""):
        executed.append(tool)
        return {"success": True, "output": {}, "error": ""}

    a.call_tool = call_tool
    out = a.handle_task({"id": "t", "description": "summarize the system",
                         "input": {}, "intelligence_level": "tactical"})
    assert executed == ["monitor.cpu", "fs.list"]
    assert len(out["steps"]) == 2


def test_web_agent_scrape_requires_url():
    a = WebAgent(name="web-t")
    a.call_tool = _tool_ok({"text": "page text"})
    out = a.handle_task({
        "id": "t",
        "description": "scrape https://example.com/docs please",
        "input": {},
    })
    assert out["text"] == "page text"
    with pytest.raises(ValueError):
        a.handle_task({"id": "t", "description": "scrape the page",
                       "input": {}})


def test_creator_agent_scaffold_and_git():
    a = CreatorAgent(name="cre-t")
    calls = []

    def call_tool(tool, args=None, reason=""):
        calls.append(tool)
        return {"success": True,
                "output": {"files": ["/tmp/aios/projects/p/main.py"]},
                "error": ""}

    a.call_tool = call_tool
    out = a.handle_task({"id": "t", "description": "create a new project",
                         "input": {"name": "p"}})
    assert calls == ["code.scaffold", "git.init"]
    assert out["git"] == "initialized"


# ---------------------------------------------------------------------------
# Spawner configs
# ---------------------------------------------------------------------------


def test_spawner_default_configs(tmp_path):
    configs = load_agent_configs(str(tmp_path / "missing"))
    assert [c.agent_type for c in configs] == ["system", "network", "security"]


def test_spawner_toml_configs(tmp_path):
    (tmp_path / "monitoring.toml").write_text(
        '[agent]\nname = "mon-main"\ntype = "monitoring"\nenabled = true\n'
    )
    (tmp_path / "web.toml").write_text(
        '[agent]\ntype = "web"\nenabled = false\n'
    )
    (tmp_path / "bogus.toml").write_text('[agent]\ntype = "nonexistent"\n')
    configs = load_agent_configs(str(tmp_path))
    assert len(configs) == 1
    assert configs[0].name == "mon-main"
    assert configs[0].agent_type == "monitoring"
