"""Gossiped prefix index: score remote cache overlap off heartbeats.

Each host piggybacks a bounded digest of its prefix caches on the PR 16
``/fleet/announce`` heartbeat (obs/fleet.py ``gprefix`` descriptor
field): per model, the page size and up to ``AIOS_TPU_FLEET_GPREFIX_MAX``
chain-hash *tails* — the first 16 hex chars (64 bits) of the sha256
chain hash — mapped to the chain depth in blocks where the index knows
it (0 = resident, depth unknown; the host spill tier advertises this
way). Chain hashes commit to the whole prefix, so tail membership is
enough to score overlap: for a request's hash chain h1..hn, the deepest
k with ``tail(h_k)`` advertised means the peer holds >= k full blocks
of exactly this prompt's prefix.

The digest is advisory by construction: it ages one heartbeat interval,
truncates at the cap, and 64-bit tails can collide. Every way it can be
wrong is safe — a misroute means the transfer fetches nothing (the
``empty`` kvx cause) and the request falls back to local prefill. No
extra RPC, no extra lock: building the digest takes only the index and
host-store locks (``engine.prefix_digest``), and scoring peers reads
the membership table snapshot.
"""

from __future__ import annotations

import os
from typing import Dict, List, Sequence

__all__ = [
    "digest_max", "tail", "build_digest", "provider", "score_tails",
    "best_peer",
]


def digest_max() -> int:
    """Per-model tail cap on the heartbeat digest
    (AIOS_TPU_FLEET_GPREFIX_MAX). Bounds heartbeat growth: 512 tails is
    ~20 KB of JSON against the announce body cap of 4 MB."""
    try:
        return int(os.environ.get("AIOS_TPU_FLEET_GPREFIX_MAX", "") or 512)
    except ValueError:
        return 512


def tail(h: bytes) -> str:
    """The gossiped form of one chain hash: first 64 bits, hex."""
    return h.hex()[:16]


def build_digest(manager) -> Dict[str, dict]:
    """The ``gprefix`` heartbeat field for every ready model:
    ``{model: {"page": page_size, "tails": {tail: depth_blocks}}}``.
    Models without a paged prefix cache are omitted — nothing to
    advertise, nothing to transfer."""
    cap = digest_max()
    out: Dict[str, dict] = {}
    for m in manager.ready_models():
        engine = m.engine
        if engine is None or getattr(engine, "prefix_index", None) is None:
            continue
        tails = engine.prefix_digest(cap)
        if tails:
            out[m.name] = {
                "page": int(engine.allocator.page_size), "tails": tails,
            }
    return out


def provider(manager):
    """A closure for :func:`aios_tpu.obs.fleet.add_digest_provider` —
    bound to the manager, built fresh at each heartbeat."""
    return lambda: build_digest(manager)


def score_tails(digest: dict, hashes: Sequence[bytes]) -> int:
    """Overlap rows a peer's advertised digest promises for a request's
    chain ``hashes``: the longest advertised *prefix* of the chain,
    in rows (depth-in-blocks x page size). Prefix, not membership count:
    a transfer restores a contiguous chain from block 1, so an
    advertised deep block behind a hole is unreachable."""
    if not digest or not hashes:
        return 0
    tails: dict = digest.get("tails") or {}
    page = int(digest.get("page") or 0)
    if not tails or page <= 0:
        return 0
    k = 0
    for h in hashes:
        if tail(h) not in tails:
            break
        k += 1
    return k * page


def best_peer(peers: List[dict], model: str,
              hashes: Sequence[bytes]) -> tuple:
    """``(peer, rows)`` for the peer whose digest promises the deepest
    chain prefix for ``model`` — ``(None, 0)`` when nobody advertises
    overlap. ``peers`` are membership rows (obs/fleet.py ``members()``
    shape); only live, serving, non-quarantined ones with a transfer
    endpoint compete — a gray host's promised chain is a trap (the
    fetch would crawl or fail), so the breaker overlay hides it."""
    from . import breaker

    best, best_rows = None, 0
    for p in peers:
        if p.get("state") != "up" or p.get("self") or not p.get("kvx_addr"):
            continue
        if (p.get("phase") or "serving") != "serving":
            continue
        if breaker.BOARD.quarantined(p.get("host") or ""):
            continue
        rows = score_tails((p.get("gprefix") or {}).get(model) or {}, hashes)
        if rows > best_rows:
            best, best_rows = p, rows
    return best, best_rows
