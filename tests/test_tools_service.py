"""Tool registry: pipeline semantics, audit chain, plugins, RPC surface.

Covers the reference's executor pipeline (validate -> caps -> rate ->
backup -> execute -> audit, executor.rs:503-633), the hash-chain verifier
(audit.rs:107-150), capability denial, rollback, plugin self-evolution and
chaining — using only hermetic tools (fs.*, monitor.*, plugin.*).
"""

import json

import pytest

from aios_tpu import rpc, services
from aios_tpu.proto_gen import tools_pb2 as pb
from aios_tpu.tools.audit import AuditLog
from aios_tpu.tools.capabilities import CapabilityChecker, requirements_for
from aios_tpu.tools.executor import ToolExecutor
from aios_tpu.tools.ratelimit import RateLimiter


@pytest.fixture()
def executor(tmp_path):
    return ToolExecutor(
        audit_path=str(tmp_path / "audit.db"),
        backup_dir=str(tmp_path / "backups"),
        plugin_dir=str(tmp_path / "plugins"),
        secrets_path=str(tmp_path / "secrets.toml"),
    )


def _run(executor, tool, args, agent="autonomy-loop"):
    return executor.execute(agent, tool, json.dumps(args).encode())


# ---------------------------------------------------------------------------
# Registry surface
# ---------------------------------------------------------------------------


def test_registry_has_reference_tool_surface(executor):
    names = set(executor.registry)
    # the 62+ handlers of executor.rs:111-501, spot-checked per namespace
    for tool in [
        "fs.read", "fs.write", "fs.delete", "fs.list", "fs.stat", "fs.mkdir",
        "fs.move", "fs.copy", "fs.chmod", "fs.chown", "fs.symlink",
        "fs.search", "fs.disk_usage",
        "process.list", "process.spawn", "process.kill", "process.info",
        "process.signal", "process.cgroup",
        "service.list", "service.start", "service.stop", "service.restart",
        "service.status",
        "net.interfaces", "net.ping", "net.dns", "net.http_get",
        "net.port_scan",
        "firewall.rules", "firewall.add_rule", "firewall.delete_rule",
        "pkg.install", "pkg.remove", "pkg.search", "pkg.update",
        "pkg.list_installed",
        "sec.check_perms", "sec.audit_query", "sec.grant", "sec.revoke",
        "sec.audit", "sec.scan", "sec.cert_generate", "sec.cert_rotate",
        "sec.file_integrity", "sec.scan_rootkits",
        "monitor.cpu", "monitor.memory", "monitor.disk", "monitor.network",
        "monitor.logs", "monitor.ebpf_trace", "monitor.fs_watch",
        "hw.info",
        "web.http_request", "web.scrape", "web.webhook", "web.download",
        "web.api_call",
        "git.init", "git.clone", "git.add", "git.commit", "git.push",
        "git.pull", "git.branch", "git.status", "git.log", "git.diff",
        "code.scaffold", "code.generate",
        "self.inspect", "self.update", "self.rebuild", "self.health",
        "plugin.create", "plugin.list", "plugin.delete", "plugin.install_deps",
        "plugin.from_template",
        "container.create", "container.start", "container.stop",
        "container.list", "container.exec", "container.logs",
        "email.send",
    ]:
        assert tool in names, f"missing tool {tool}"
    assert len(names) >= 62


# ---------------------------------------------------------------------------
# Pipeline
# ---------------------------------------------------------------------------


def test_fs_roundtrip_with_audit(executor, tmp_path):
    f = tmp_path / "hello.txt"
    r = _run(executor, "fs.write", {"path": str(f), "content": "hi"})
    assert r.success
    r2 = _run(executor, "fs.read", {"path": str(f)})
    assert r2.success and r2.output["content"] == "hi"
    ok, bad = executor.audit.verify_chain()
    assert ok and bad is None
    assert executor.audit.count() == 2


def test_unknown_tool_fails_and_audits(executor):
    r = _run(executor, "fs.teleport", {})
    assert not r.success and "unknown tool" in r.error
    assert executor.audit.count() == 1


def test_capability_denied(executor):
    # monitoring_agent has no fs.write capability
    r = _run(executor, "fs.write", {"path": "/tmp/x", "content": "x"},
             agent="monitoring_agent")
    assert not r.success
    assert "lacks capabilities" in r.error


def test_capability_grant_via_sec_tool(executor, tmp_path):
    target = tmp_path / "g.txt"
    denied = _run(executor, "fs.write", {"path": str(target), "content": "x"},
                  agent="monitoring_agent")
    assert not denied.success
    granted = _run(executor, "sec.grant",
                   {"agent_id": "monitoring_agent", "capabilities": ["fs.write"]})
    assert granted.success
    allowed = _run(executor, "fs.write", {"path": str(target), "content": "x"},
                   agent="monitoring_agent")
    assert allowed.success
    _run(executor, "sec.revoke",
         {"agent_id": "monitoring_agent", "capabilities": ["fs.write"]})
    again = _run(executor, "fs.write", {"path": str(target), "content": "y"},
                 agent="monitoring_agent")
    assert not again.success


def test_rate_limit_blocks_floods():
    rl = RateLimiter(agent_rps=3, tool_rps=50)
    allowed = sum(1 for _ in range(10) if rl.check("a1", "fs.read")[0])
    assert allowed <= 4  # capacity burst only


def test_backup_and_rollback(executor, tmp_path):
    f = tmp_path / "cfg.txt"
    f.write_text("original")
    r = _run(executor, "fs.write", {"path": str(f), "content": "modified"})
    assert r.success and r.backup_id
    assert f.read_text() == "modified"
    ok, msg = executor.rollback(r.execution_id)
    assert ok, msg
    assert f.read_text() == "original"


def test_rollback_of_created_file_deletes_it(executor, tmp_path):
    f = tmp_path / "new.txt"
    r = _run(executor, "fs.write", {"path": str(f), "content": "x"})
    assert f.exists()
    ok, _ = executor.rollback(r.execution_id)
    assert ok
    assert not f.exists()


def test_handler_error_becomes_result_error(executor):
    r = _run(executor, "fs.read", {"path": "/nonexistent/deeply/missing"})
    assert not r.success and "not a file" in r.error


# ---------------------------------------------------------------------------
# Audit chain
# ---------------------------------------------------------------------------


def test_audit_chain_detects_tampering(tmp_path):
    log = AuditLog(str(tmp_path / "a.db"))
    for i in range(5):
        log.record("agent", f"tool{i}", b"{}", b"{}", True)
    ok, _ = log.verify_chain()
    assert ok
    log.tamper_for_test(seq=3)
    ok, bad = log.verify_chain()
    assert not ok and bad == 3


def test_sec_audit_tool_reports_chain(executor):
    _run(executor, "monitor.cpu", {})
    r = _run(executor, "sec.audit", {})
    assert r.success and r.output["chain_valid"]
    r2 = _run(executor, "sec.audit_query", {"tool_name": "monitor.cpu"})
    assert r2.success and len(r2.output["records"]) == 1


# ---------------------------------------------------------------------------
# Capabilities metadata
# ---------------------------------------------------------------------------


def test_risk_levels():
    assert requirements_for("fs.read")[1] == "low"
    assert requirements_for("fs.delete")[1] == "high"
    assert requirements_for("firewall.add_rule")[1] == "critical"
    assert requirements_for("sec.grant")[1] == "critical"


def test_agent_type_prefix_matching():
    c = CapabilityChecker()
    assert "net.diagnose" in c.grants_for("network_agent-x42")
    assert c.grants_for("unknown-agent") == set()


# ---------------------------------------------------------------------------
# Plugins (self-evolution)
# ---------------------------------------------------------------------------


def test_plugin_create_execute_chain(executor):
    r1 = _run(executor, "plugin.create", {
        "name": "adder",
        "code": "def main(input_data):\n"
                "    return {'sum': input_data.get('a', 0) + input_data.get('b', 0)}\n",
        "description": "adds a and b",
    })
    assert r1.success, r1.error
    assert "plugin.x.adder" in executor.registry

    r2 = _run(executor, "plugin.x.adder", {"a": 2, "b": 40})
    assert r2.success, r2.error
    assert r2.output["sum"] == 42

    # chain: doubler pipes into adder? build second plugin chained to adder
    r3 = _run(executor, "plugin.create", {
        "name": "doubler",
        "code": "def main(input_data):\n"
                "    return {'a': input_data.get('x', 0) * 2, 'b': 1}\n",
        "next_plugins": ["adder"],
        "output_mode": "pipe",
    })
    assert r3.success
    r4 = _run(executor, "plugin.x.doubler", {"x": 5})
    assert r4.success and r4.output["sum"] == 11  # 5*2 + 1


def test_plugin_rejects_bad_code(executor):
    r = _run(executor, "plugin.create",
             {"name": "broken", "code": "this is not python"})
    assert not r.success
    r2 = _run(executor, "plugin.create",
              {"name": "nomain", "code": "x = 1"})
    assert not r2.success and "main" in r2.error


def test_plugin_from_template_and_delete(executor):
    r = _run(executor, "plugin.from_template",
             {"name": "echoer", "template": "basic"})
    assert r.success
    assert _run(executor, "plugin.x.echoer", {"k": 1}).output == {"echo": {"k": 1}}
    r2 = _run(executor, "plugin.delete", {"name": "echoer"})
    assert r2.success and r2.output["deleted"]
    assert "plugin.x.echoer" not in executor.registry


# ---------------------------------------------------------------------------
# gRPC surface
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tools_stub(tmp_path_factory):
    from aios_tpu.tools.service import serve

    tmp = tmp_path_factory.mktemp("tools")
    ex = ToolExecutor(
        audit_path=str(tmp / "audit.db"),
        backup_dir=str(tmp / "backups"),
        plugin_dir=str(tmp / "plugins"),
    )
    server, service, port = serve(address="127.0.0.1:0", executor=ex, block=False)
    channel = rpc.insecure_channel(f"127.0.0.1:{port}")
    yield services.ToolRegistryStub(channel)
    channel.close()
    server.stop(grace=None)


def test_rpc_list_and_get(tools_stub):
    resp = tools_stub.ListTools(pb.ListToolsRequest())
    assert len(resp.tools) >= 62
    fs_only = tools_stub.ListTools(pb.ListToolsRequest(namespace="fs"))
    assert all(t.namespace == "fs" for t in fs_only.tools)
    one = tools_stub.GetTool(pb.GetToolRequest(name="fs.delete"))
    assert one.risk_level == "high" and one.reversible


def test_rpc_execute_and_rollback(tools_stub, tmp_path):
    f = tmp_path / "rpc.txt"
    f.write_text("before")
    resp = tools_stub.Execute(
        pb.ExecuteRequest(
            tool_name="fs.write",
            agent_id="autonomy-loop",
            input_json=json.dumps({"path": str(f), "content": "after"}).encode(),
            reason="test",
        )
    )
    assert resp.success
    assert f.read_text() == "after"
    rb = tools_stub.Rollback(pb.RollbackRequest(execution_id=resp.execution_id))
    assert rb.success
    assert f.read_text() == "before"


def test_rpc_register_deregister(tools_stub):
    resp = tools_stub.Register(
        pb.RegisterToolRequest(
            tool=pb.ToolDefinition(name="custom.thing", namespace="custom",
                                   description="external"),
            handler_address="127.0.0.1:7777",
        )
    )
    assert resp.accepted
    got = tools_stub.GetTool(pb.GetToolRequest(name="custom.thing"))
    assert got.description == "external"
    out = tools_stub.Deregister(pb.DeregisterToolRequest(tool_name="custom.thing"))
    assert out.success
