"""Structured-agent fast path (ISSUE 7): compressed-FSM jump-ahead
decoding + radix-tree prefix cache.

Four guarantees under test:
  * forced-run collapse: chains of singleton automaton states (the mask
    admits exactly one token) emit in ONE multi-token jump dispatch, and
    greedy constrained streams are token-identical jump-ahead ON vs OFF
    while the dispatch count drops >= 2x on schema-forced workloads;
  * no compile after warmup: the jump graphs are AOT-built behind the
    readiness gate (run-length buckets), extending the PR 6 invariant to
    the constrained path;
  * radix-index invariants: no page is ever simultaneously free-listed
    and tree-referenced — across leaf-LRU eviction, pool-pressure
    reclaim, host-tier spill, and restore re-insertion — and a prompt
    diverging MID-CHAIN from a cached prompt still hits the shared
    prefix (partial-node overlap, node splitting);
  * spec auto-disable: a collapsed EWMA draft-acceptance ratio suspends
    speculation (plain decode serves) and re-probes after the window.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aios_tpu.engine import jsonmode, jsonschema
from aios_tpu.engine import model as M
from aios_tpu.engine import paged
from aios_tpu.engine.batching import ContinuousBatcher, Request
from aios_tpu.engine.config import TINY_TEST
from aios_tpu.engine.engine import TPUEngine
from aios_tpu.engine.tokenizer import ByteTokenizer


@pytest.fixture(scope="module")
def params():
    return M.init_params(TINY_TEST, jax.random.PRNGKey(0), dtype=jnp.float32)


# enum-heavy: almost every position is grammar-forced once the first byte
# of each enum/bool disambiguates — the orchestrator tool-call shape
TOOL_SCHEMA = {
    "type": "object",
    "properties": {
        "tool": {
            "type": "string",
            "enum": ["read_file", "write_file", "list_dir"],
        },
        "path": {"type": "string", "enum": ["slash_tmp", "slash_etc"]},
        "recursive": {"type": "boolean"},
    },
    "required": ["tool", "path", "recursive"],
}

# free-form string + nested subtree: forced runs interleave with sampled
# content, exercising the mixed run/step cadence
MIXED_SCHEMA = {
    "type": "object",
    "properties": {
        "name": {"type": "string"},
        "count": {"type": "integer"},
    },
    "required": ["name", "count"],
}


def _token_table():
    tok = ByteTokenizer()
    return tok, jsonmode.token_bytes_table(tok, TINY_TEST.vocab_size)


# -- forced-run detection (host-side, no engine) ----------------------------


def test_forced_run_detection_schema_key_literal():
    """After '{"r' the key trie has one candidate ('recursive'), so the
    whole remaining literal + '":' is a singleton chain; the run stops at
    the boolean value (two admissible openers)."""
    tok, table = _token_table()
    cache = jsonschema.SchemaMaskCache(
        table, tok.eos_id, TOOL_SCHEMA, compact=True
    )
    c = jsonmode.JsonConstraint(cache)
    for b in b'{"r':
        tok_id = b  # ByteTokenizer: token id == byte value
        c.advance(tok_id)
    assert not c.failed
    run = c.forced_run(32)
    assert bytes(run) == b'ecursive":'
    # every run token really is the unique admissible one
    probe = jsonmode.JsonConstraint(cache)
    for b in b'{"r':
        probe.advance(b)
    for t in run:
        row = probe.mask_row()
        assert np.flatnonzero(row == 0.0).tolist() == [t]
        probe.advance(t)


def test_forced_run_respects_budget_gate():
    """When the budget-feasibility gate would alter the dispatched row,
    run detection stops — the per-step path owns the closing walk."""
    tok, table = _token_table()
    cache = jsonschema.SchemaMaskCache(
        table, tok.eos_id, TOOL_SCHEMA, compact=True
    )
    c = jsonmode.JsonConstraint(cache)
    for b in b'{"r':
        c.advance(b)
    assert c.forced_run(32, remaining=3) == []
    long_enough = c.forced_run(32, remaining=256)
    assert bytes(long_enough) == b'ecursive":'


def test_compact_mode_rejects_structural_whitespace_only():
    """compact=True outlaws inter-element whitespace but keeps spaces
    inside string content (enum values / keys may contain them)."""
    st = jsonmode.start_state()
    assert jsonmode.run_bytes(st, b'{ "a": 1 }') is not None
    assert jsonmode.run_bytes(st, b'{ "a":1}', compact=True) is None
    assert jsonmode.run_bytes(st, b'{"a":1}', compact=True) is not None
    assert jsonmode.run_bytes(st, b'{"a":"x y"}', compact=True) is not None


# -- jump-ahead through the continuous batcher ------------------------------


def _run_constrained(params, jump, reqs, *, engine_kw=None):
    tok = ByteTokenizer()
    kw = dict(num_slots=4, max_context=128, cache_dtype=jnp.float32)
    kw.update(engine_kw or {})
    eng = TPUEngine(TINY_TEST, params, **kw)
    eng.warmup(step_sizes=(2, 4), prefill_chunk=0, masked_step=True)
    b = ContinuousBatcher(
        eng, chunk_steps=4, admit_chunk_steps=2, tokenizer=tok,
        jump_ahead=jump,
    )
    try:
        handles = [b.submit(Request(**r)) for r in reqs]
        outs = [h.tokens() for h in handles]
        return outs, dict(eng.stats())
    finally:
        b.shutdown()
        eng.close()


def _schema_req(i, schema=TOOL_SCHEMA, **kw):
    tok = ByteTokenizer()
    req = dict(
        prompt_ids=tok.encode(f"emit json {i}"), max_tokens=64,
        temperature=0.0, stop_ids=(tok.eos_id,), json_schema=schema,
    )
    req.update(kw)
    return req


def test_jump_ahead_greedy_identity_and_dispatch_reduction(params):
    """Two waves through ONE off/on arm pair (warmup is the expensive
    part on this container):

    * wave 1 — greedy constrained decode with jump-ahead ON emits
      token-identical streams to OFF: schema-forced, generic json_mode,
      and a co-resident unconstrained stream;
    * wave 2 — the acceptance bar: >= 2x fewer engine dispatches on a
      schema-forced workload (dispatch counters, deterministic on CPU).
    """
    tok = ByteTokenizer()
    arms = {}
    try:
        for jump in (False, True):
            eng = TPUEngine(TINY_TEST, params, num_slots=4,
                            max_context=128, cache_dtype=jnp.float32)
            eng.warmup(step_sizes=(2, 4), prefill_chunk=0,
                       masked_step=True)
            arms[jump] = (eng, ContinuousBatcher(
                eng, chunk_steps=4, admit_chunk_steps=2, tokenizer=tok,
                jump_ahead=jump,
            ))
        # -- wave 1: mixed-batch token identity
        reqs = [
            _schema_req(0),
            _schema_req(1, schema=MIXED_SCHEMA),
            dict(prompt_ids=tok.encode("emit json 2"), max_tokens=48,
                 temperature=0.0, stop_ids=(tok.eos_id,), json_mode=True),
            dict(prompt_ids=tok.encode("plain"), max_tokens=20,
                 temperature=0.0),
        ]
        outs = {}
        for jump, (eng, b) in arms.items():
            handles = [b.submit(Request(**dict(r))) for r in reqs]
            outs[jump] = [h.tokens() for h in handles]
        assert outs[True] == outs[False]
        assert arms[True][0].jump_dispatches > 0
        for out in outs[True][:2]:
            parsed = json.loads(
                tok.decode([t for t in out if t != tok.eos_id])
            )
            assert isinstance(parsed, dict)
        # -- wave 2: schema-forced dispatch reduction
        steps, waves = {}, {}
        for jump, (eng, b) in arms.items():
            before = eng.decode_steps
            handles = [
                b.submit(Request(**_schema_req(10 + i))) for i in range(2)
            ]
            waves[jump] = [h.tokens() for h in handles]
            steps[jump] = eng.decode_steps - before
        assert waves[True] == waves[False]
        assert steps[False] >= 2 * steps[True], steps
        s_on = arms[True][0].stats()
        # the jump path emitted the bulk of the forced tokens
        assert s_on["jump_tokens"] >= s_on["jump_dispatches"] * 2
    finally:
        for eng, b in arms.values():
            b.shutdown()
            eng.close()


@pytest.mark.slow
def test_jump_ahead_sampled_schema_still_conforms(params):
    """Sampled constrained streams under jump-ahead stay schema-exact
    (forced tokens are sampler-independent; the sampled remainder draws
    a shifted key chain — the documented unified_step-style caveat)."""
    reqs = [_schema_req(0, temperature=0.9, top_p=0.9)]
    on, s_on = _run_constrained(params, True, reqs)
    tok = ByteTokenizer()
    parsed = json.loads(
        tok.decode([t for t in on[0] if t != tok.eos_id])
    )
    assert parsed["tool"] in TOOL_SCHEMA["properties"]["tool"]["enum"]
    assert parsed["path"] in TOOL_SCHEMA["properties"]["path"]["enum"]
    assert isinstance(parsed["recursive"], bool)
    assert s_on.get("jump_dispatches", 0) > 0


@pytest.mark.slow
def test_jump_no_compile_after_warmup(params):
    """PR 6 invariant extended to the jump path: warmup(masked_step=True)
    AOT-builds the run-length-bucketed jump graphs, so a full constrained
    generation — including prefix-hit resubmission — compiles nothing."""
    tok = ByteTokenizer()
    eng = TPUEngine(
        TINY_TEST.scaled(max_context=512), params, num_slots=2,
        max_context=512, cache_dtype=jnp.float32,
        paged_pool_rows=512, page_size=32, prefix_host_bytes=32 << 20,
    )
    b = None
    try:
        eng.warmup(step_sizes=(1, 2, 8, 16), masked_step=True)
        b = ContinuousBatcher(
            eng, chunk_steps=4, admit_chunk_steps=2, tokenizer=tok,
            jump_ahead=True,
        )
        before = eng.stats()["xla_compiles"]
        prompt = tok.encode("the same long preamble " * 12)
        for _ in range(2):  # second pass rides the radix prefix hit
            h = b.submit(Request(
                prompt_ids=prompt, max_tokens=64, temperature=0.0,
                stop_ids=(tok.eos_id,), json_schema=TOOL_SCHEMA,
            ))
            out = h.tokens()
            assert json.loads(
                tok.decode([t for t in out if t != tok.eos_id])
            )
        stats = eng.stats()
        assert stats["jump_dispatches"] > 0
        assert stats["prefix_rows_reused"] > 0
        assert stats["xla_compiles"] == before, (
            "constrained serving compiled a graph warmup should cover"
        )
    finally:
        if b is not None:
            b.shutdown()
        eng.close()


# -- radix prefix index -----------------------------------------------------


def _chains(alloc, n_tokens, seed, page_size=4):
    rng = np.random.default_rng(seed)
    ids = [int(t) for t in rng.integers(1, 500, n_tokens)]
    hashes = paged.chain_hashes(ids, page_size, n_tokens // page_size)
    return ids, hashes


def test_radix_partial_node_overlap_and_split():
    """A chain diverging MID-NODE still scores (peek) and maps (match)
    its shared prefix; the node splits at the divergence point and both
    branches stay reachable."""
    alloc = paged.PageAllocator(32, 4, 2, 16)
    ix = paged.RadixPrefixIndex(alloc, max_pages=31)
    ids_a, hashes_a = _chains(alloc, 24, seed=1)  # 6 blocks
    pages_a = alloc.alloc_pages(6)
    ix.put(hashes_a, pages_a)
    # B shares 3 blocks (12 tokens) then diverges
    ids_b = ids_a[:12] + [int(t) + 1 for t in ids_a[12:]]
    hashes_b = paged.chain_hashes(ids_b, 4, 6)
    assert hashes_b[:3] == hashes_a[:3] and hashes_b[3] != hashes_a[3]
    assert ix.peek(hashes_b) == 3  # partial-node overlap credited
    assert ix.peek(hashes_a) == 6
    got = ix.match(hashes_b)
    assert got == pages_a[:3]
    # graft B's divergent tail; both chains fully resolvable afterwards
    pages_b = pages_a[:3] + alloc.alloc_pages(3)
    ix.put(hashes_b, pages_b)
    assert ix.peek(hashes_a) == 6
    assert ix.peek(hashes_b) == 6
    snap = ix.snapshot()
    assert len(snap) == 9
    assert set(snap.values()) == set(pages_a) | set(pages_b[3:])


def test_radix_leaf_lru_evicts_deepest_blocks_first():
    """Eviction past max_pages pops leaf TAILS of the coldest chain —
    the shared preamble survives while divergent tails age out — and the
    evicted pairs reach the spill hook before their references drop."""
    alloc = paged.PageAllocator(32, 4, 2, 16)
    ix = paged.RadixPrefixIndex(alloc, max_pages=8)
    spilled = []
    ix.spill = spilled.extend
    ids_a, hashes_a = _chains(alloc, 24, seed=2)  # 6 blocks
    pages_a = alloc.alloc_pages(6)
    ix.put(hashes_a, pages_a)
    for p in pages_a:
        alloc.decref(p)  # the tree holds the only reference now
    ids_b = ids_a[:8] + [int(t) + 1 for t in ids_a[8:]]
    hashes_b = paged.chain_hashes(ids_b, 4, 6)
    pages_b_tail = alloc.alloc_pages(4)
    ix.put(hashes_b, pages_a[:2] + pages_b_tail)
    for p in pages_b_tail:
        alloc.decref(p)
    # 6 + 4 = 10 entries > 8: two of chain A's DEEPEST blocks evicted
    # (B's tail was touched more recently)
    assert [h for h, _ in spilled] == [hashes_a[5], hashes_a[4]]
    snap = ix.snapshot()
    assert hashes_a[3] in snap and hashes_a[5] not in snap
    assert ix.peek(hashes_b) == 6  # B untouched
    # invariant: no page simultaneously free-listed and tree-referenced
    assert not set(alloc._free[0]) & set(snap.values())


def test_radix_reclaim_skips_shared_pages_bottom_up():
    """Pool-pressure reclaim only frees pages held ONLY by the tree, and
    only as tree suffixes — a live slot's mapped prefix pins its chain."""
    alloc = paged.PageAllocator(32, 4, 2, 16)
    ix = paged.RadixPrefixIndex(alloc, max_pages=31)
    _, hashes = _chains(alloc, 24, seed=3)
    pages = alloc.alloc_pages(6)
    ix.put(hashes, pages)
    for p in pages:
        alloc.decref(p)
    # a slot maps the first 4 blocks (refcount 2 there)
    alloc.map_shared(0, pages[:4])
    assert ix.reclaimable() == 2
    assert ix.reclaim(6) == 2  # only the unshared tail freed
    snap = ix.snapshot()
    assert set(snap.values()) == set(pages[:4])
    assert not set(alloc._free[0]) & set(snap.values())
    alloc.free_slot(0)
    assert ix.reclaim(6) == 4  # now poppable bottom-up
    assert ix.snapshot() == {}


def test_radix_engine_mid_chain_divergence_gets_prefix_hit(params):
    """Acceptance: two sequential requests sharing a long system prefix —
    the second hits the radix cache (prefix_rows_reused > 0) even though
    its prompt diverges mid-chain (inside the first prompt's cached
    run)."""
    eng = TPUEngine(
        TINY_TEST.scaled(max_context=512), params, num_slots=2,
        max_context=512, cache_dtype=jnp.float32,
        paged_pool_rows=512, page_size=32,
    )
    try:
        assert isinstance(eng.prefix_index, paged.RadixPrefixIndex)
        rng = np.random.default_rng(5)
        a = [int(t) for t in rng.integers(1, 500, 300)]
        eng.prefill(0, a, temperature=0.0)
        eng.release(0)
        before = eng.prefix_rows_reused
        b = a[:270] + [int(t) for t in rng.integers(1, 500, 40)]
        eng.prefill(0, b, temperature=0.0)
        eng.release(0)
        # blocks 0..7 (256 rows) are shared; divergence at row 270 is
        # inside block 8 — the radix walk still maps the shared run
        assert eng.prefix_rows_reused - before == 256
    finally:
        eng.close()


def test_radix_spill_restore_interleaving_invariants(params):
    """Pool-pressure reclaim spills tree entries to the host tier; a
    later resubmission restores them into FRESH pages and re-inserts
    them into the tree at the right position. At every checkpoint no
    page is simultaneously free-listed and (tree-referenced or mapped)
    — the test_host_tier reclaim/restore invariant, radix edition."""
    eng = TPUEngine(
        TINY_TEST.scaled(max_context=512), params, num_slots=2,
        max_context=512, cache_dtype=jnp.float32,
        paged_pool_rows=512, page_size=32, prefix_host_bytes=32 << 20,
    )

    def check_invariant():
        alloc = eng.allocator
        free = set(alloc._free[0])
        referenced = set(eng.prefix_index.snapshot().values())
        for s in range(eng.num_slots):
            used = int(alloc._blocks_used[s])
            referenced.update(int(p) for p in alloc.tables[s, :used])
        assert not free & referenced, (free, referenced)

    try:
        rng = np.random.default_rng(6)
        preamble = [int(t) for t in rng.integers(1, 500, 321)]  # 10 blocks
        eng.prefill(0, preamble, temperature=0.0)
        eng.release(0)
        check_invariant()
        pressure = [int(t) for t in rng.integers(1, 500, 480)]  # 15 blocks
        eng.prefill(0, pressure, temperature=0.0)  # reclaim -> spill
        check_invariant()
        eng.release(0)
        deadline = time.time() + 10
        while eng.host_store.spills < 2 and time.time() < deadline:
            time.sleep(0.02)
        eng.prefill(0, preamble, temperature=0.0)  # host-tier restore
        check_invariant()
        eng.release(0)
        stats = eng.stats()
        assert stats.get("host_tier_restores", 0) >= 1
        assert stats.get("prefix_rows_restored", 0) > 0
        # the restored segment is back in the TREE: a third submission
        # maps it straight from HBM (no further host-tier restores)
        restores = stats["host_tier_restores"]
        reused = eng.prefix_rows_reused
        eng.prefill(0, preamble, temperature=0.0)
        eng.release(0)
        check_invariant()
        assert eng.prefix_rows_reused > reused
        assert eng.stats()["host_tier_restores"] == restores
    finally:
        eng.close()


def test_radix_escape_hatch_selects_flat_index(params):
    eng = TPUEngine(
        TINY_TEST.scaled(max_context=512), params, num_slots=2,
        max_context=512, cache_dtype=jnp.float32,
        paged_pool_rows=512, page_size=32, prefix_radix=False,
    )
    try:
        assert type(eng.prefix_index) is paged.PrefixIndex
    finally:
        eng.close()


# -- speculative auto-disable -----------------------------------------------


def test_spec_ewma_autodisable_and_reprobe(params):
    """Deterministic unit drive of the EWMA machinery: zero acceptance
    under a positive floor suspends the proposer; an expired window
    grants a PROBE-COUNT-SEEDED re-probe — the floor re-judges only
    after SPEC_PROBE_DISPATCHES probe dispatches accumulate into a
    fresh cumulative average, so one unlucky probe can no longer
    re-disable instantly (the old zeroed-EWMA behavior)."""
    from aios_tpu.engine.batching import SPEC_PROBE_DISPATCHES

    eng = TPUEngine(TINY_TEST, params, num_slots=4, max_context=128,
                    cache_dtype=jnp.float32)
    b = ContinuousBatcher(eng, speculative=True, spec_min_accept=0.5)
    try:
        assert b.spec_proposers == ("ngram",)
        assert b._spec_active() and b._spec_proposer() == "ngram"
        # a dispatch where every live slot emitted exactly 1 token/round
        counts = np.ones((2, 4), np.int64)
        b._spec_measure("ngram", counts, {0: 2, 1: 2})
        assert b.spec_ewma["ngram"] == 0.0
        assert b.spec_autodisables == 1
        assert not b._spec_active()
        # window expiry -> fresh evidence, judged over the probe budget
        b._spec_off_until["ngram"] = time.monotonic() - 1
        assert b._spec_active()
        assert b.spec_ewma["ngram"] is None
        assert b._spec_probe_left["ngram"] == SPEC_PROBE_DISPATCHES
        # one BAD probe (the fix this knob exists for): verdict deferred
        b._spec_measure("ngram", counts, {0: 2, 1: 2})
        assert b._spec_active(), "one bad probe must not re-disable"
        full = np.full((2, 4), b.spec_draft_len + 1, np.int64)
        b._spec_measure("ngram", full, {0: 2, 1: 2})
        b._spec_measure("ngram", full, {0: 2, 1: 2})
        # cumulative probe average (0 + 1 + 1) / 3 clears the floor
        assert b._spec_active()
        assert abs(b.spec_ewma["ngram"] - 2.0 / 3.0) < 1e-9
        # rounds past a slot's retirement are EXCLUDED: slot 0 retired
        # after round 1, its round-2 zero-acceptance column must not
        # drag the (perfect) served acceptance down
        b.spec_ewma["ngram"] = None
        mixed = np.full((2, 4), b.spec_draft_len + 1, np.int64)
        mixed[1, 0] = 1  # unserved continuation round, nothing accepted
        b._spec_measure("ngram", mixed, {0: 1, 1: 2})
        assert b.spec_ewma["ngram"] == 1.0 and b._spec_active()
    finally:
        b.shutdown()
        eng.close()


def test_spec_autodisable_end_to_end_sampled(params):
    """Sampled slots never speculate, so their acceptance ratio is 0 by
    construction: with a floor set, the first spec dispatch suspends
    speculation and the stream finishes on the plain path."""
    eng = TPUEngine(TINY_TEST, params, num_slots=4, max_context=128,
                    cache_dtype=jnp.float32)
    b = ContinuousBatcher(
        eng, chunk_steps=4, admit_chunk_steps=2, speculative=True,
        spec_min_accept=0.25,
    )
    try:
        out = b.submit(Request(
            prompt_ids=[7, 2, 55], max_tokens=24, temperature=0.9,
        )).tokens()
        assert len(out) == 24  # the stream completed on the plain path
        assert b.spec_autodisables >= 1
        # re-arm the window so a slow container can't expire it (and
        # trigger a legitimate re-probe) before the next request drains
        b._spec_off_until["ngram"] = time.monotonic() + 300
        rounds = eng.spec_rounds
        out2 = b.submit(Request(
            prompt_ids=[9, 4, 33], max_tokens=12, temperature=0.9,
        )).tokens()
        assert len(out2) == 12
        assert eng.spec_rounds == rounds  # suspended: no spec dispatches
    finally:
        b.shutdown()
        eng.close()
