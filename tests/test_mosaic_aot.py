"""Chipless Mosaic compilation tests for every Pallas kernel.

Interpret-mode parity (test_ops.py etc.) validates kernel MATH but not what
the real Mosaic compiler accepts — r3 proof: the int8-KV ragged kernel
family passed interpret mode yet failed on hardware, because Mosaic rejects
DMA-slicing a <128 lane extent (the per-(row, kv-head) scale arrays had the
tiny head count on lanes). These tests close that gap without needing a
chip: libtpu's AOT compiler builds each kernel against a v5e topology
description, so a Mosaic-invalid layout fails in CI the way it would fail
in serving.

Skips cleanly when no libtpu is importable (non-TPU dev machines).
"""

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# compile-heavy tier: excluded from the fast commit gate (pytest -m fast)
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def rep_sharding(request):
    # skip ONLY when libtpu itself is absent (non-TPU dev machine); any
    # other failure to build the topology is a real regression of this
    # module's CI gate and must fail loudly
    try:
        import libtpu  # noqa: F401
    except ImportError:
        pytest.skip("libtpu not installed — no Mosaic AOT compiler here")

    # libtpu wants these before its first init; restore after the module
    # so the fake 4-chip topology can't leak into later tests that might
    # initialize a real TPU backend in this process
    mp = pytest.MonkeyPatch()
    request.addfinalizer(mp.undo)
    if "TPU_ACCELERATOR_TYPE" not in os.environ:
        mp.setenv("TPU_ACCELERATOR_TYPE", "v5litepod-4")
    if "TPU_WORKER_HOSTNAMES" not in os.environ:
        mp.setenv("TPU_WORKER_HOSTNAMES", "localhost")

    from jax.experimental import topologies
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    topo = topologies.get_topology_desc(
        platform="tpu", topology_name="v5e:2x2x1"
    )
    mesh = Mesh(np.array(topo.devices[:1]).reshape(1), ("x",))
    return NamedSharding(mesh, PartitionSpec())


def aot_compile(rep, fn, *args, **static):
    f = jax.jit(
        functools.partial(fn, **static) if static else fn,
        in_shardings=(rep,) * len(args),
        out_shardings=rep,
    )
    f.trace(*args).lower().compile()  # raises on Mosaic rejection


# TinyLlama-shaped decode geometry (the shapes that caught the r3 bug)
B, H, KH, D, C = 8, 32, 4, 64, 4096


def test_aot_flash_attention(rep_sharding):
    from aios_tpu import ops

    T = 512
    q = jnp.ones((2, T, H, D), jnp.bfloat16)
    kv = jnp.ones((2, T, KH, D), jnp.bfloat16)
    aot_compile(rep_sharding, ops.flash_attention, q, kv, kv, causal=True)


def test_aot_quantized_matmul(rep_sharding):
    from aios_tpu import ops

    x = jnp.ones((8, 2048), jnp.bfloat16)
    w = jnp.ones((2048, 5632), jnp.int8)
    s = jnp.ones((1, 5632), jnp.float32)
    aot_compile(rep_sharding, ops.quantized_matmul, x, w, s)


@pytest.mark.parametrize(
    "K,N",
    [
        (4096, 6144), (14336, 4096), (4096, 32000),
        # Mistral-7B TP-4 shard geometries (ShardingPlan.int4_matmul_impl
        # runs the kernel per device on these): col shards [K, N/4] for
        # wq / wk+wv / w_gate+w_up, row shards [K/4, N] for wo / w_down.
        # (lm_head's 32000/4 = 8000 is not 128-aligned — quantize_params'
        # tp-aware eligibility keeps that leaf int8, so no AOT case.)
        (4096, 1024), (4096, 256), (4096, 3584),
        (1024, 4096), (3584, 4096),
    ],
)
def test_aot_int4_matmul(rep_sharding, K, N):
    from aios_tpu.ops.int4_matmul import GROUP, int4_matmul

    x = jnp.ones((8, K), jnp.bfloat16)
    p = jnp.ones((K // 2, N), jnp.uint8)
    s = jnp.ones((K // GROUP, 1, N), jnp.float32)
    aot_compile(rep_sharding, int4_matmul, x, p, s)


def test_aot_ragged_decode_bf16(rep_sharding):
    from aios_tpu import ops

    q = jnp.ones((B, H, D), jnp.bfloat16)
    kc = jnp.ones((B, C, KH, D), jnp.bfloat16)
    lens = jnp.ones((B,), jnp.int32)
    aot_compile(rep_sharding, ops.decode_attention, q, kc, kc, lens)


def test_aot_ragged_decode_int8(rep_sharding):
    """The kernel that failed real Mosaic in r3 (scale lane layout)."""
    from aios_tpu import ops

    q = jnp.ones((B, H, D), jnp.bfloat16)
    kq = jnp.ones((B, C, KH, D), jnp.int8)
    ks = jnp.ones((B, C, KH), jnp.float32)
    lens = jnp.ones((B,), jnp.int32)
    aot_compile(
        rep_sharding, ops.decode_attention_int8, q, kq, kq, ks, ks, lens
    )


def test_aot_paged_decode_both_dtypes(rep_sharding):
    from aios_tpu import ops

    N_, P = 64, 128
    q = jnp.ones((B, H, D), jnp.bfloat16)
    tbl = jnp.zeros((B, 32), jnp.int32)
    lens = jnp.ones((B,), jnp.int32)
    kp = jnp.ones((N_, P, KH, D), jnp.bfloat16)
    aot_compile(rep_sharding, ops.paged_decode_attention, q, kp, kp, tbl, lens)
    kq = jnp.ones((N_, P, KH, D), jnp.int8)
    ps = jnp.ones((N_, P, KH), jnp.float32)
    aot_compile(
        rep_sharding, ops.paged_decode_attention_int8,
        q, kq, kq, ps, ps, tbl, lens,
    )


def test_aot_multiquery_verify_both_dtypes(rep_sharding):
    from aios_tpu import ops

    T = 4
    qt = jnp.ones((B, T, H, D), jnp.bfloat16)
    lens = jnp.ones((B,), jnp.int32)
    strides = jnp.ones((B,), jnp.int32)
    kc = jnp.ones((B, C, KH, D), jnp.bfloat16)
    aot_compile(
        rep_sharding, ops.multiquery_decode_attention,
        qt, kc, kc, lens, strides,
    )
    kq = jnp.ones((B, C, KH, D), jnp.int8)
    ks = jnp.ones((B, C, KH), jnp.float32)
    aot_compile(
        rep_sharding, ops.multiquery_decode_attention_int8,
        qt, kq, kq, ks, ks, lens, strides,
    )


# ---------------------------------------------------------------------------
# Composed serving graphs — the exact jit units bench.py dispatches
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_aot_decode_step_int8_kv_ragged(rep_sharding, monkeypatch):
    """TinyLlama decode step with int8 KV + the ragged kernel family —
    the A/B arm that failed on hardware in r3."""
    monkeypatch.setenv("AIOS_TPU_INT8_RAGGED", "1")
    from aios_tpu.engine import model as M
    from aios_tpu.engine.config import TINYLLAMA_1_1B

    cfg = TINYLLAMA_1_1B
    params = M.init_quantized_params(cfg, jax.random.PRNGKey(0))
    k, v = M.init_kv_cache(cfg, 8, 4096, jnp.int8)
    ks, vs = M.init_kv_scales(cfg, 8, 4096)
    toks = jnp.ones((8,), jnp.int32)
    lens = jnp.ones((8,), jnp.int32)

    def step(params, toks, lens, k, v, ks, vs):
        return M.decode_step(params, cfg, toks, lens, k, v, kernels=True,
                             cache_scales=(ks, vs))

    args = (params, toks, lens, k, v, ks, vs)
    sh = jax.tree.map(lambda a: rep_sharding, args)
    jax.jit(step, in_shardings=sh).trace(*args).lower().compile()


@pytest.mark.slow
def test_aot_decode_step_int4_weights(rep_sharding):
    """Mistral-7B decode step on int4 serving weights (headline bench)."""
    from aios_tpu.engine import model as M
    from aios_tpu.engine.config import MISTRAL_7B

    cfg = MISTRAL_7B
    params = M.init_quantized_params(cfg, jax.random.PRNGKey(0), mode="int4")
    k, v = M.init_kv_cache(cfg, 8, 1024, jnp.bfloat16)
    toks = jnp.ones((8,), jnp.int32)
    lens = jnp.ones((8,), jnp.int32)

    def step(params, toks, lens, k, v):
        return M.decode_step(params, cfg, toks, lens, k, v, kernels=True)

    args = (params, toks, lens, k, v)
    sh = jax.tree.map(lambda a: rep_sharding, args)
    jax.jit(step, in_shardings=sh).trace(*args).lower().compile()
