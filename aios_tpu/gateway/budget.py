"""Monthly budget tracking for cloud providers.

Reference parity (api-gateway/src/budget.rs:18-114): $100/month Claude,
$50/month OpenAI (env-overridable); cost model $3/$15 per Mtok in/out for
Claude, $2.50/$10 for OpenAI; free local/qwen3 paths; 80% spend warning;
automatic reset on month rollover. Usage records are queryable per provider
and day window (GetUsage RPC).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List

from ..obs import instruments as obs

# (input $/Mtok, output $/Mtok)
COST_MODEL: Dict[str, tuple] = {
    "claude": (3.0, 15.0),
    "openai": (2.5, 10.0),
    "qwen3": (0.0, 0.0),
    "local": (0.0, 0.0),
}

WARN_FRACTION = 0.8


@dataclass
class UsageRecord:
    provider: str
    model: str
    input_tokens: int
    output_tokens: int
    cost_usd: float
    timestamp: int
    requesting_agent: str = ""
    task_id: str = ""


@dataclass
class BudgetManager:
    claude_budget: float = field(
        default_factory=lambda: float(os.environ.get("CLAUDE_MONTHLY_BUDGET", "100"))
    )
    openai_budget: float = field(
        default_factory=lambda: float(os.environ.get("OPENAI_MONTHLY_BUDGET", "50"))
    )

    def __post_init__(self):
        self._records: List[UsageRecord] = []
        self._month_key = self._current_month()
        self._lock = threading.Lock()

    @staticmethod
    def _current_month() -> str:
        return time.strftime("%Y-%m")

    def _maybe_reset(self) -> None:
        month = self._current_month()
        if month != self._month_key:
            self._records = [r for r in self._records if False]  # clear
            self._month_key = month

    def budget_for(self, provider: str) -> float:
        return {"claude": self.claude_budget, "openai": self.openai_budget}.get(
            provider, float("inf")
        )

    def used(self, provider: str) -> float:
        with self._lock:
            self._maybe_reset()
            return sum(r.cost_usd for r in self._records if r.provider == provider)

    def cost_of(self, provider: str, input_tokens: int, output_tokens: int) -> float:
        cin, cout = COST_MODEL.get(provider, (0.0, 0.0))
        return input_tokens / 1e6 * cin + output_tokens / 1e6 * cout

    def can_afford(self, provider: str, est_tokens: int = 2048) -> bool:
        budget = self.budget_for(provider)
        if budget == float("inf"):
            return True
        est_cost = self.cost_of(provider, est_tokens, est_tokens)
        return self.used(provider) + est_cost <= budget

    def record(
        self,
        provider: str,
        model: str,
        input_tokens: int,
        output_tokens: int,
        agent: str = "",
        task_id: str = "",
    ) -> UsageRecord:
        rec = UsageRecord(
            provider=provider,
            model=model,
            input_tokens=input_tokens,
            output_tokens=output_tokens,
            cost_usd=self.cost_of(provider, input_tokens, output_tokens),
            timestamp=int(time.time()),
            requesting_agent=agent,
            task_id=task_id,
        )
        with self._lock:
            self._maybe_reset()
            self._records.append(rec)
        # registry counters do NOT reset on month rollover (Prometheus
        # counters are monotonic; dashboards take increase() over windows)
        if rec.cost_usd:
            obs.GATEWAY_SPEND.labels(provider=provider).inc(rec.cost_usd)
        obs.GATEWAY_TOKENS.labels(
            provider=provider, direction="input"
        ).inc(input_tokens)
        obs.GATEWAY_TOKENS.labels(
            provider=provider, direction="output"
        ).inc(output_tokens)
        return rec

    def warning(self, provider: str) -> str:
        budget = self.budget_for(provider)
        if budget == float("inf"):
            return ""
        used = self.used(provider)
        if used >= budget:
            return f"{provider} monthly budget exhausted (${used:.2f}/${budget:.0f})"
        if used >= WARN_FRACTION * budget:
            return f"{provider} at {used / budget:.0%} of monthly budget"
        return ""

    def status(self) -> dict:
        now = time.localtime()
        import calendar

        days_in_month = calendar.monthrange(now.tm_year, now.tm_mon)[1]
        days_remaining = days_in_month - now.tm_mday
        claude_used = self.used("claude")
        openai_used = self.used("openai")
        total_used = claude_used + openai_used
        daily_rate = total_used / max(now.tm_mday, 1)
        return {
            "claude_monthly_budget_usd": self.claude_budget,
            "claude_used_usd": claude_used,
            "openai_monthly_budget_usd": self.openai_budget,
            "openai_used_usd": openai_used,
            "days_remaining": days_remaining,
            "daily_rate_usd": daily_rate,
            "budget_exceeded": (
                claude_used >= self.claude_budget or openai_used >= self.openai_budget
            ),
        }

    def usage(self, provider: str = "", days: int = 30) -> List[UsageRecord]:
        cutoff = int(time.time()) - days * 86400
        with self._lock:
            return [
                r
                for r in self._records
                if r.timestamp >= cutoff and (not provider or r.provider == provider)
            ]
