"""Multi-query ragged decode attention (ops/verify_attention.py).

The speculative verify step's kernel: T queries per slot over that slot's
valid cache rows, causal staircase per query. Interpret mode on CPU, like
the other kernel parity tests.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aios_tpu.engine import model
from aios_tpu.engine.config import TINY_TEST
from aios_tpu.ops import (
    multiquery_decode_attention,
    multiquery_decode_attention_reference,
)

# compile-heavy tier: excluded from the fast commit gate (pytest -m fast)
pytestmark = pytest.mark.slow


def _setup(rng, B, C, KH, D, H, T):
    q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, C, KH, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, C, KH, D)), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("window", [None, 24])
@pytest.mark.parametrize("T", [1, 4, 8])
def test_multiquery_kernel_matches_reference(window, T):
    rng = np.random.default_rng(0)
    B, C, KH, D, H = 3, 128, 2, 8, 4
    q, k, v = _setup(rng, B, C, KH, D, H, T)
    lengths = jnp.asarray([0, 37, 100], jnp.int32)
    strides = jnp.ones((B,), jnp.int32)
    ref = multiquery_decode_attention_reference(
        q, k, v, lengths, strides, window=window
    )
    got = multiquery_decode_attention(
        q, k, v, lengths, strides, window=window, block_kv=32, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_multiquery_matches_single_query_kernel():
    """T=1 must agree with the single-query ragged decode kernel."""
    from aios_tpu.ops import decode_attention

    rng = np.random.default_rng(1)
    B, C, KH, D, H = 2, 64, 2, 8, 4
    q, k, v = _setup(rng, B, C, KH, D, H, 1)
    lengths = jnp.asarray([5, 60], jnp.int32)
    strides = jnp.ones((B,), jnp.int32)
    mq = multiquery_decode_attention(
        q, k, v, lengths, strides, block_kv=32, interpret=True
    )
    sq = decode_attention(q[:, 0], k, v, lengths, block_kv=32, interpret=True)
    np.testing.assert_allclose(
        np.asarray(mq[:, 0]), np.asarray(sq), rtol=2e-5, atol=2e-5
    )


def test_multiquery_inactive_stride_zero():
    """stride 0 (inactive slot): every query sees only col 0, matching the
    verify_step inactive convention."""
    rng = np.random.default_rng(2)
    B, C, KH, D, H, T = 2, 64, 2, 8, 4, 4
    q, k, v = _setup(rng, B, C, KH, D, H, T)
    lengths = jnp.asarray([10, 0], jnp.int32)
    strides = jnp.asarray([1, 0], jnp.int32)
    ref = multiquery_decode_attention_reference(q, k, v, lengths, strides)
    got = multiquery_decode_attention(
        q, k, v, lengths, strides, block_kv=32, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_multiquery_ignores_rows_beyond_staircase():
    """Poisoning rows above each query's visibility must not change
    anything — the proof the kernel honors the ragged bound."""
    rng = np.random.default_rng(3)
    B, C, KH, D, H, T = 1, 128, 2, 8, 4, 4
    q, k, v = _setup(rng, B, C, KH, D, H, T)
    lengths = jnp.asarray([20], jnp.int32)
    strides = jnp.ones((B,), jnp.int32)
    base = multiquery_decode_attention(
        q, k, v, lengths, strides, block_kv=32, interpret=True
    )
    k = k.at[:, 24:].set(1e9)  # beyond the last query's row (20+3)
    v = v.at[:, 24:].set(1e9)
    got = multiquery_decode_attention(
        q, k, v, lengths, strides, block_kv=32, interpret=True
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(base), rtol=1e-6,
                               atol=1e-6)


def test_verify_step_kernel_branch_matches_masked(monkeypatch):
    """Drive verify_step's ACTUAL kernel branch on CPU: force crossover
    eligibility (AIOS_TPU_RAGGED_MIN_C=1, read at trace time) and wrap the
    op in interpret mode — a wiring bug (wrong read base, dropped window,
    bad stride gating) would diverge from the masked path here instead of
    first surfacing as wrong accepted-token counts on real TPU serving."""
    import functools

    import aios_tpu.engine.model as M
    from aios_tpu import ops as ops_pkg

    monkeypatch.setenv("AIOS_TPU_RAGGED_MIN_C", "1")
    monkeypatch.setattr(
        M.ops,
        "multiquery_decode_attention",
        functools.partial(ops_pkg.multiquery_decode_attention, interpret=True),
    )
    cfg = TINY_TEST.scaled(sliding_window=24)  # window wiring covered too
    params = model.init_params(cfg, jax.random.PRNGKey(1), jnp.float32)
    S, C, T = 3, 64, 3
    k, v = model.init_kv_cache(cfg, S, C, jnp.float32)
    feed = jnp.asarray([[3, 9, 4], [8, 1, 6], [2, 2, 2]], jnp.int32)
    lengths = jnp.asarray([0, 30, 5], jnp.int32)
    active = jnp.asarray([True, True, False])  # inactive stride-0 path

    ref, rk, rv = model.verify_step(
        params, cfg, feed, lengths, k, v, kernels=False, active=active
    )
    got, gk, gv = model.verify_step(
        params, cfg, feed, lengths, k, v, kernels=True, active=active
    )
    # inactive slot's outputs are garbage on both paths; compare active
    np.testing.assert_allclose(
        np.asarray(got[:2]), np.asarray(ref[:2]), rtol=2e-4, atol=2e-4
    )
    np.testing.assert_allclose(np.asarray(gk), np.asarray(rk), rtol=1e-5,
                               atol=1e-5)


# ---------------------------------------------------------------------------
# int8-KV multi-query kernel
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("window", [None, 24])
def test_multiquery_int8_parity(window):
    from aios_tpu.ops import (
        multiquery_decode_attention_int8,
        multiquery_decode_attention_int8_reference,
    )

    rng = np.random.default_rng(11)
    B, T, H, KH, D, C = 3, 4, 8, 2, 16, 64
    q = jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.integers(-127, 128, (B, C, KH, D)), jnp.int8)
    v = jnp.asarray(rng.integers(-127, 128, (B, C, KH, D)), jnp.int8)
    ks = jnp.asarray(rng.uniform(0.005, 0.02, (B, C, KH)), jnp.float32)
    vs = jnp.asarray(rng.uniform(0.005, 0.02, (B, C, KH)), jnp.float32)
    lens = jnp.asarray([0, 31, 57], jnp.int32)
    strides = jnp.asarray([1, 1, 0], jnp.int32)
    got = multiquery_decode_attention_int8(
        q, k, v, ks, vs, lens, strides, window=window, block_kv=16,
        interpret=True,
    )
    ref = multiquery_decode_attention_int8_reference(
        q, k, v, ks, vs, lens, strides, window=window
    )
    np.testing.assert_allclose(got, ref, atol=2e-5, rtol=2e-5)


def test_verify_step_int8_kernel_wiring(monkeypatch):
    """AIOS_TPU_INT8_RAGGED=1 routes int8-KV verify through the mq kernel
    (reference body on CPU); outputs match the dequantizing XLA path."""
    import aios_tpu.ops as ops_mod
    from aios_tpu.engine import model as M
    from aios_tpu.engine.config import TINY_TEST

    cfg = TINY_TEST
    params = M.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    toks = jnp.asarray([[1, 2, 3], [4, 5, 6]], jnp.int32)
    lens = jnp.asarray([5, 9], jnp.int32)
    k, v = M.init_kv_cache(cfg, 2, 128, jnp.int8)
    scales = M.init_kv_scales(cfg, 2, 128)

    ref = M.verify_step(
        params, cfg, toks, lens, k, v, kernels=False, cache_scales=scales,
    )[0]

    called = {}

    def fake(q, k_l, v_l, k_s, v_s, base, strides, window=None):
        called["hit"] = True
        return ops_mod.multiquery_decode_attention_int8_reference(
            q, k_l, v_l, k_s, v_s, base, strides, window=window
        )

    monkeypatch.setenv("AIOS_TPU_INT8_RAGGED", "1")
    monkeypatch.setenv("AIOS_TPU_RAGGED_MIN_C", "1")
    monkeypatch.setattr(ops_mod, "multiquery_decode_attention_int8", fake)
    got = M.verify_step(
        params, cfg, toks, lens, k, v, kernels=True, cache_scales=scales,
    )[0]
    assert called.get("hit")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)
