"""Memory service: tiers, embeddings, migration, context assembly, RPCs.

Mirrors the reference's model-based memory tests (tests/integration/
test_memory.rs exercises lifecycle semantics in-process) plus a live-socket
pass over the 24-RPC surface.
"""

import time

import numpy as np
import pytest

from aios_tpu import rpc, services
from aios_tpu.memory import embeddings
from aios_tpu.memory.migration import MigrationPipeline
from aios_tpu.memory.service import MemoryService
from aios_tpu.memory.tiers import LongTermMemory, OperationalMemory, WorkingMemory
from aios_tpu.proto_gen import memory_pb2 as pb


# ---------------------------------------------------------------------------
# Embeddings
# ---------------------------------------------------------------------------


def test_embedding_is_normalized_and_deterministic():
    v1 = embeddings.embed("restart the nginx service")
    v2 = embeddings.embed("restart the nginx service")
    np.testing.assert_array_equal(v1, v2)
    assert v1.shape == (64,)
    assert abs(float(np.linalg.norm(v1)) - 1.0) < 1e-5


def test_similar_texts_score_higher():
    q = "disk usage is high"
    related = "alert: disk usage exceeded 90 percent"
    unrelated = "the weather in paris is sunny"
    qv = embeddings.embed(q)
    s_rel = embeddings.hybrid_score(q, qv, related, embeddings.embed(related))
    s_unrel = embeddings.hybrid_score(q, qv, unrelated, embeddings.embed(unrelated))
    assert s_rel > s_unrel


# ---------------------------------------------------------------------------
# Tiers
# ---------------------------------------------------------------------------


def test_operational_ring_and_metrics():
    op = OperationalMemory(capacity=5)
    for i in range(8):
        op.push_event({"category": "test", "source": "t", "data_json": str(i)})
    events = op.recent_events(count=10)
    assert len(events) == 5  # ring capacity enforced
    assert events[0]["data_json"] == "7"  # newest first

    t0 = time.perf_counter()
    op.update_metric("cpu", 42.0)
    got = op.get_metric("cpu")
    assert got[0] == 42.0
    assert time.perf_counter() - t0 < 0.001  # <1 ms operational target


def test_working_goal_task_lifecycle(tmp_db_path):
    w = WorkingMemory(tmp_db_path)
    w.store_goal({"id": "g1", "description": "fix disk", "status": "in_progress"})
    w.store_task({"id": "t1", "goal_id": "g1", "description": "check df"})
    assert [g["id"] for g in w.active_goals()] == ["g1"]
    assert len(w.tasks_for_goal("g1")) == 1
    w.update_goal("g1", "completed", result="done")
    assert w.active_goals() == []


def test_pattern_stats_update():
    w = WorkingMemory()
    w.store_pattern({"id": "p1", "trigger": "high cpu", "action": "restart",
                     "success_rate": 1.0, "uses": 1})
    w.update_pattern_stats("p1", success=False)
    p = w.find_pattern("high cpu")
    assert p["uses"] == 2
    assert p["success_rate"] == pytest.approx(0.5)
    assert w.find_pattern("high cpu", min_success_rate=0.9) is None


def test_pattern_pruning_keeps_best():
    w = WorkingMemory()
    for i in range(20):
        w.store_pattern({"id": f"p{i}", "trigger": f"t{i}", "action": "a",
                         "success_rate": i / 20.0, "uses": i})
    removed = w.prune_patterns(cap=5)
    assert removed == 15
    assert w.find_pattern("t19") is not None
    assert w.find_pattern("t0") is None


def test_longterm_hybrid_search_ranks_relevant_first():
    lt = LongTermMemory()
    lt.store_memory("procedure for restarting nginx after config change",
                    collection="procedures")
    lt.store_memory("notes about TPU mesh topology", collection="general")
    lt.store_memory("incident: nginx crashed due to OOM", collection="incidents")
    got = lt.search("nginx restart", n_results=2)
    assert len(got) == 2
    assert "nginx" in got[0]["content"]


def test_longterm_collection_filter():
    lt = LongTermMemory()
    lt.store_memory("alpha fact", collection="a")
    lt.store_memory("alpha other", collection="b")
    got = lt.search("alpha", collections=["a"], n_results=5)
    assert len(got) == 1
    assert got[0]["collection"] == "a"


def test_knowledge_base_roundtrip():
    lt = LongTermMemory()
    lt.add_knowledge("Mesh sharding", "use pjit with NamedSharding over a Mesh",
                     source="docs")
    got = lt.search_knowledge("pjit sharding mesh")
    assert got and "NamedSharding" in got[0]["content"]


# ---------------------------------------------------------------------------
# Migration
# ---------------------------------------------------------------------------


def test_migration_moves_finished_goals_and_extracts_procedures():
    op, w, lt = OperationalMemory(), WorkingMemory(), LongTermMemory()
    m = MigrationPipeline(op, w, lt)
    old = int(time.time()) - 7200
    w.store_goal({"id": "g1", "description": "rotate tls certs",
                  "status": "completed", "completed_at": old})
    # force completed_at into the past (update_goal stamps now)
    w._exec("UPDATE goals SET completed_at=? WHERE id='g1'", (old,))
    w.store_task({"id": "t1", "goal_id": "g1", "description": "issue new cert",
                  "agent": "security_agent"})
    op.push_event({"category": "old", "source": "x", "data_json": "{}",
                   "timestamp": int(time.time()) - 90000})
    op.push_event({"category": "new", "source": "x", "data_json": "{}"})

    stats = m.run_once()
    assert stats["goals"] == 1
    assert stats["procedures"] == 1
    assert stats["events"] == 1
    # migrated out of working
    assert w.tasks_for_goal("g1") == [] or w.active_goals() == []
    got = lt.search("rotate tls certs", collections=["goal_history"])
    assert got
    # recent event is still in operational
    assert len(op.recent_events()) == 1


# ---------------------------------------------------------------------------
# Full RPC surface over a socket
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def memory_stub():
    from aios_tpu.memory.service import serve

    server, service, port = serve(address="127.0.0.1:0", block=False)
    channel = rpc.insecure_channel(f"127.0.0.1:{port}")
    yield services.MemoryServiceStub(channel)
    channel.close()
    server.stop(grace=None)


def test_rpc_events_and_metrics(memory_stub):
    memory_stub.PushEvent(
        pb.Event(category="sys", source="test", data_json=b'{"x":1}')
    )
    events = memory_stub.GetRecentEvents(pb.RecentEventsRequest(count=5))
    assert len(events.events) == 1
    memory_stub.UpdateMetric(pb.MetricUpdate(key="cpu", value=55.5))
    got = memory_stub.GetMetric(pb.MetricRequest(key="cpu"))
    assert got.value == 55.5
    snap = memory_stub.GetSystemSnapshot(pb.Empty())
    assert snap.memory_total_mb > 0


def test_rpc_goals_tasks_patterns(memory_stub):
    memory_stub.StoreGoal(
        pb.GoalRecord(id="g9", description="test goal", status="pending")
    )
    goals = memory_stub.GetActiveGoals(pb.Empty())
    assert any(g.id == "g9" for g in goals.goals)
    memory_stub.StoreTask(pb.TaskRecord(id="t9", goal_id="g9", description="step"))
    tasks = memory_stub.GetTasksForGoal(pb.GoalIdRequest(goal_id="g9"))
    assert len(tasks.tasks) == 1
    memory_stub.StorePattern(
        pb.Pattern(id="pp", trigger="disk full", action="clean /tmp",
                   success_rate=0.9, uses=3)
    )
    found = memory_stub.FindPattern(pb.PatternQuery(trigger="disk"))
    assert found.found and found.pattern.action == "clean /tmp"
    memory_stub.UpdatePatternStats(pb.PatternStatsUpdate(id="pp", success=True))


def test_rpc_agent_state(memory_stub):
    memory_stub.StoreAgentState(
        pb.AgentState(agent_name="system_agent", state_json=b'{"n":1}')
    )
    got = memory_stub.GetAgentState(pb.AgentStateRequest(agent_name="system_agent"))
    assert got.state_json == b'{"n":1}'
    missing = memory_stub.GetAgentState(pb.AgentStateRequest(agent_name="nope"))
    assert missing.state_json == b""


def test_rpc_semantic_search_and_knowledge(memory_stub):
    memory_stub.StoreProcedure(
        pb.Procedure(name="restart service", description="systemctl restart",
                     steps_json=b"[]")
    )
    memory_stub.StoreIncident(
        pb.Incident(description="OOM on nginx", root_cause="memory leak")
    )
    memory_stub.StoreConfigChange(
        pb.ConfigChange(file_path="/etc/nginx.conf", content="worker=4",
                        changed_by="test")
    )
    memory_stub.AddKnowledge(
        pb.KnowledgeEntry(title="nginx tuning", content="raise worker count",
                          source="docs")
    )
    hits = memory_stub.SearchKnowledge(
        pb.SemanticSearchRequest(query="nginx workers", n_results=3)
    )
    assert hits.results


def test_rpc_assemble_context_budget(memory_stub):
    # stuff long-term with enough content to overflow a small budget
    for i in range(10):
        memory_stub.PushEvent(
            pb.Event(category="load", source="t", data_json=b"x" * 200)
        )
    ctx = memory_stub.AssembleContext(
        pb.ContextRequest(task_description="anything", max_tokens=50)
    )
    assert ctx.total_tokens <= 50
    assert sum(c.tokens for c in ctx.chunks) == ctx.total_tokens
