"""SLO engine: windowed TTFT/TPOT/availability objectives per model.

Consumes finished flight-recorder timelines (it registers itself as a
``FlightRecorder`` finish listener) and maintains a sliding window of
per-request samples per (model, tenant). From the window it computes,
per model and objective:

  * **attainment** — the fraction of requests in the window meeting the
    objective's target (TTFT <= ``ttft_ms``, TPOT <= ``tpot_ms``, and
    for availability: retired normally rather than shed/aborted);
  * **burn rate** — error-budget consumption speed,
    ``(1 - attainment) / (1 - target)`` (1.0 = burning exactly at
    budget; >1 = the window is eating future budget);
  * **breach** — attainment below target with at least ``min_samples``
    requests observed (small windows never page anyone).

Exposed as the ``aios_tpu_slo_*`` metric family (attainment + burn-rate
gauges and a breach counter, labeled (model, objective) — the objective
label is the closed ``OBJECTIVES`` enum, and the per-tenant breakdown
stays in ``/debug/slo`` / ``health()`` JSON so no metric carries the
unbounded tenant x model product). A breach flipping ON increments the
counter, freezes a flight-recorder anomaly snapshot, and flips every
service's ``/healthz`` to 503 via :func:`annotate_health`
(obs/http.py calls it on each probe).

Targets come from env (read once at engine construction):
``AIOS_TPU_SLO_TTFT_MS`` / ``AIOS_TPU_SLO_TPOT_MS`` /
``AIOS_TPU_SLO_TARGET`` / ``AIOS_TPU_SLO_WINDOW_SECS`` /
``AIOS_TPU_SLO_MIN_SAMPLES`` — docs/OBSERVABILITY.md has the table.
"""

from __future__ import annotations

import logging
import os
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from . import flightrec
from . import instruments as obs
from ..analysis.locks import make_lock

log = logging.getLogger("aios.obs")

# The closed objective enum — the only values the ``objective`` label of
# the aios_tpu_slo_* family may carry (linted by tests/test_obs_lint.py).
OBJECTIVES = ("ttft", "tpot", "availability")

_MAX_SAMPLES_PER_MODEL = 8192  # hard cap under the time window
_MAX_TENANT_ROWS = 64  # per-tenant breakdown rows in health()/debug JSON
_EVAL_TTL_SECS = 1.0  # evaluation cache: scrapes hit 3 gauges x N models


def _env_float(name: str, default: float, lo: float, hi: float) -> float:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        v = float(raw)
        if not lo <= v <= hi:
            raise ValueError(f"must be in [{lo}, {hi}]")
        return v
    except ValueError as exc:
        log.warning("%s=%r ignored (%s); using %s", name, raw, exc, default)
        return default


@dataclass(frozen=True)
class SLOConfig:
    """Per-process objective targets (one policy for every model — the
    serving plane's floor; per-model targets can layer on later without
    changing the sample plumbing)."""

    ttft_ms: float = 2000.0  # time to first token
    tpot_ms: float = 250.0  # time per output token after the first
    target: float = 0.99  # attainment target per objective
    window_secs: float = 300.0
    min_samples: int = 20  # below this the window never breaches

    @classmethod
    def from_env(cls) -> "SLOConfig":
        return cls(
            ttft_ms=_env_float("AIOS_TPU_SLO_TTFT_MS", 2000.0, 1.0, 1e7),
            tpot_ms=_env_float("AIOS_TPU_SLO_TPOT_MS", 250.0, 0.1, 1e6),
            target=_env_float("AIOS_TPU_SLO_TARGET", 0.99, 0.5, 1.0),
            window_secs=_env_float(
                "AIOS_TPU_SLO_WINDOW_SECS", 300.0, 1.0, 86400.0
            ),
            min_samples=int(_env_float(
                "AIOS_TPU_SLO_MIN_SAMPLES", 20, 1, 1e6
            )),
        )


# One sample per finished request: (t_monotonic, tenant, ttft_ms|None,
# tpot_ms|None, ok). ttft/tpot are None when the request never produced
# a first token (shed, aborted pre-prefill) — those count against
# availability but not against the latency objectives.
_Sample = Tuple[float, str, Optional[float], Optional[float], bool]


class SLOEngine:
    def __init__(self, cfg: Optional[SLOConfig] = None) -> None:
        self.cfg = cfg or SLOConfig.from_env()
        self._lock = make_lock("slo")
        self._samples: Dict[str, deque] = {}  #: guarded_by _lock
        self._breached: Dict[Tuple[str, str], bool] = {}  #: guarded_by _lock
        self.breaches = 0  # total breach EDGES (monotonic)
        self._eval_cache: Dict[str, Tuple[float, dict]] = {}
        self._registered: set = set()

    # -- ingest --------------------------------------------------------------

    def observe(self, tl) -> None:
        """FlightRecorder finish listener: fold one timeline into the
        window. Cancelled requests are the client's choice, not the
        plane's failure — they don't sample. Neither do QUOTA sheds:
        they are the tenant's own policy violation doing exactly what
        the bucket promised, and counting them would let one abusive
        tenant breach availability and eject healthy replicas from the
        load balancer. Saturation sheds (deadline/queue_full/draining)
        and aborts DO count — those are the plane failing admitted or
        admissible work."""
        if tl.state == "cancelled":
            return
        if tl.state == "shed" and tl.shed_cause == "quota":
            return
        ok = tl.state == "retired"
        ttft = tl.ttft_ms if tl.ttft_ms > 0 else None
        tpot = tl.tpot_ms if tl.tokens_out > 1 and tl.ttft_ms > 0 else None
        self.record(tl.model, tl.tenant, ttft_ms=ttft, tpot_ms=tpot, ok=ok)

    def record(self, model: str, tenant: str = "anonymous", *,
               ttft_ms: Optional[float] = None,
               tpot_ms: Optional[float] = None, ok: bool = True,
               now: Optional[float] = None) -> None:
        """Add one request sample (``now`` injectable for window tests)."""
        t = time.monotonic() if now is None else now
        with self._lock:
            dq = self._samples.get(model)
            if dq is None:
                dq = self._samples.setdefault(
                    model, deque(maxlen=_MAX_SAMPLES_PER_MODEL)
                )
                first = model not in self._registered
                self._registered.add(model)
            else:
                first = False
            dq.append((t, tenant, ttft_ms, tpot_ms, ok))
        if first:
            self._register_gauges(model)
        # breach edges are detected at record time (the natural moment to
        # freeze evidence — the breaching requests are still in the
        # recorder ring), not only lazily at scrape; the 1 s evaluation
        # cache keeps this amortized O(window)/sec, not O(window)/request
        self.evaluate(model, now=now)

    def _register_gauges(self, model: str) -> None:
        for objective in OBJECTIVES:
            obs.SLO_ATTAINMENT.labels(
                model=model, objective=objective
            ).set_function(
                lambda m=model, o=objective:
                    self.evaluate(m)[o]["attainment"]
            )
            obs.SLO_BURN_RATE.labels(
                model=model, objective=objective
            ).set_function(
                lambda m=model, o=objective:
                    self.evaluate(m)[o]["burn_rate"]
            )

    # -- evaluation ----------------------------------------------------------

    def _window(self, model: str, now: float) -> List[_Sample]:
        dq = self._samples.get(model)
        if not dq:
            return []
        horizon = now - self.cfg.window_secs
        while dq and dq[0][0] < horizon:
            dq.popleft()
        return list(dq)

    def evaluate(self, model: str, now: Optional[float] = None) -> dict:
        """Windowed objective evaluation for one model:
        ``{objective: {attainment, burn_rate, breached, samples,
        target_value}}``. Breach EDGES (ok -> breached) increment the
        ``aios_tpu_slo_breaches_total`` counter and freeze a
        flight-recorder snapshot."""
        t = time.monotonic() if now is None else now
        with self._lock:
            cached = self._eval_cache.get(model)
            if now is None and cached is not None \
                    and t - cached[0] < _EVAL_TTL_SECS:
                return cached[1]
            window = self._window(model, t)
        cfg = self.cfg
        out: dict = {}
        for objective in OBJECTIVES:
            if objective == "ttft":
                vals = [s for s in window if s[2] is not None]
                met = sum(1 for s in vals if s[2] <= cfg.ttft_ms)
                target_value: float = cfg.ttft_ms
            elif objective == "tpot":
                vals = [s for s in window if s[3] is not None]
                met = sum(1 for s in vals if s[3] <= cfg.tpot_ms)
                target_value = cfg.tpot_ms
            else:  # availability
                vals = window
                met = sum(1 for s in vals if s[4])
                target_value = cfg.target
            n = len(vals)
            attainment = met / n if n else 1.0
            burn = (1.0 - attainment) / max(1.0 - cfg.target, 1e-9)
            breached = n >= cfg.min_samples and attainment < cfg.target
            out[objective] = {
                "attainment": round(attainment, 6),
                "burn_rate": round(burn, 4),
                "breached": breached,
                "samples": n,
                "target_value": target_value,
                "target": cfg.target,
            }
            self._note_breach(model, objective, breached)
        if now is None:  # injected clocks (tests) must not poison the cache
            with self._lock:
                self._eval_cache[model] = (t, out)
        return out

    def _note_breach(self, model: str, objective: str,
                     breached: bool) -> None:
        key = (model, objective)
        # edge detection under the lock: a scrape-thread evaluate() and a
        # record-path evaluate() crossing the threshold together must
        # count ONE breach, not one each (counter + snapshot follow
        # outside the lock — the recorder takes its own)
        with self._lock:
            was = self._breached.get(key, False)
            self._breached[key] = breached
            edge = breached and not was
            if edge:
                self.breaches += 1
        if edge:
            obs.SLO_BREACHES.labels(model=model, objective=objective).inc()
            log.warning("SLO breach: %s/%s fell below target", model,
                        objective)
            # async: breach edges fire on the request-finish path
            flightrec.RECORDER.snapshot(model, "slo_breach", sync=False)

    # -- surfaces ------------------------------------------------------------

    def tenants(self, model: str, now: Optional[float] = None) -> dict:
        """Per-tenant window breakdown (bounded row count; JSON surfaces
        only — tenant never becomes a metric label next to model)."""
        t = time.monotonic() if now is None else now
        with self._lock:
            window = self._window(model, t)
        by_tenant: Dict[str, List[_Sample]] = {}
        for s in window:
            by_tenant.setdefault(s[1], []).append(s)
        out = {}
        for tenant, rows in sorted(by_tenant.items())[:_MAX_TENANT_ROWS]:
            with_ttft = [s for s in rows if s[2] is not None]
            out[tenant] = {
                "samples": len(rows),
                "ok_ratio": round(
                    sum(1 for s in rows if s[4]) / len(rows), 4
                ),
                "ttft_attainment": round(
                    sum(1 for s in with_ttft if s[2] <= self.cfg.ttft_ms)
                    / len(with_ttft), 4
                ) if with_ttft else 1.0,
            }
        return out

    def models(self) -> List[str]:
        with self._lock:
            return sorted(self._samples)

    def health(self) -> dict:
        """/healthz fragment: evaluation per model + degraded status when
        any objective is in breach."""
        models = self.models()
        if not models:
            return {}
        slo = {m: self.evaluate(m) for m in models}
        breached = [
            m for m, objectives in slo.items()
            if any(o["breached"] for o in objectives.values())
        ]
        out: dict = {"slo": slo}
        if breached:
            out["status"] = "degraded"
            out["slo_breached"] = breached
        return out

    def clear(self) -> None:
        """Test isolation (metric children persist; values re-resolve)."""
        with self._lock:
            self._samples.clear()
            self._breached.clear()
            self._eval_cache.clear()


ENGINE = SLOEngine()
flightrec.RECORDER.add_listener(ENGINE.observe)


def annotate_health(payload: dict) -> dict:
    """Fold the SLO view into a /healthz payload (obs/http.py calls this
    on every probe): adds the ``slo`` section when samples exist and
    downgrades ``status`` to ``degraded`` on any active breach. When the
    fleet telemetry plane is armed (obs/fleet.py), the fleet rollup —
    member counts by state, worst-burn host, per-objective fleet
    attainment — rides the same probe as a ``fleet`` section."""
    from . import fleet

    if fleet.FLEET is not None:
        payload.setdefault("fleet", fleet.FLEET.health_summary())
    h = ENGINE.health()
    if not h:
        return payload
    slo = h.pop("slo")
    payload.setdefault("slo", slo)
    if h.get("status") == "degraded" and payload.get("status") == "ok":
        payload["status"] = "degraded"
        payload["slo_breached"] = h["slo_breached"]
    return payload
