"""Weight / train-state checkpointing (orbax-backed, sharding-aware).

The reference has no model checkpoints at all — it is inference-only over
externally-downloaded GGUF files, and its notion of "resume" is goal/task
state in SQLite (SURVEY.md section 5 "Checkpoint/resume"). The TPU build
adds the missing half:

  * serving weights: params saved once after load/quantize-prep, restored
    directly to device (sharded restore when a mesh plan is given) — a
    LoadModel from a checkpoint skips GGUF parse + dequant entirely;
  * training: the full {params, opt_state, step} pytree checkpoints
    atomically with retention, and `latest_step` powers crash resume, the
    same pattern the reference applies to goals (goal_engine.rs:493-518)
    lifted to model state.

Orbax handles atomicity (tmp dir + rename), async-free single-controller
writes, and per-leaf sharding metadata, so multi-chip restores place shards
without a host-side gather.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp

PARAMS_NAME = "params"


def _abs(path: str) -> str:
    return os.path.abspath(os.path.expanduser(path))


class CheckpointManager:
    """Step-indexed checkpoints of an arbitrary pytree (train state)."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = _abs(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, step: int, tree: Any, wait: bool = True) -> None:
        self._mgr.save(step, args=ocp.args.StandardSave(tree))
        if wait:
            self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, step: Optional[int] = None, like: Any = None) -> Any:
        """Restore a checkpoint; ``like`` provides dtypes/shardings to
        restore onto (abstract pytree of jax.ShapeDtypeStruct or arrays)."""
        step = self._mgr.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        if like is not None:
            abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, like)
            return self._mgr.restore(
                step, args=ocp.args.StandardRestore(abstract)
            )
        return self._mgr.restore(step)

    def close(self) -> None:
        self._mgr.close()


def save_params(directory: str, params: Any) -> None:
    """One-shot serving-weight checkpoint (no step indexing)."""
    path = os.path.join(_abs(directory), PARAMS_NAME)
    ckpt = ocp.StandardCheckpointer()
    ckpt.save(path, params, force=True)
    ckpt.wait_until_finished()
    ckpt.close()


def load_params(directory: str, like: Any = None) -> Any:
    """Restore serving weights; ``like`` carries target dtype/sharding."""
    path = os.path.join(_abs(directory), PARAMS_NAME)
    if not os.path.isdir(path):
        raise FileNotFoundError(path)
    ckpt = ocp.StandardCheckpointer()
    try:
        if like is not None:
            abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, like)
            return ckpt.restore(path, abstract)
        return ckpt.restore(path)
    finally:
        ckpt.close()


def is_checkpoint_dir(path: str) -> bool:
    return os.path.isdir(os.path.join(_abs(path), PARAMS_NAME))


# ---------------------------------------------------------------------------
# Full model checkpoints: params + config + tokenizer in one directory.
# This is the TPU analog of a prepared GGUF file — `scripts/prepare_model.py`
# converts GGUF/HF sources into this format once, and LoadModel restores it
# straight to device (no dequantization pass on the serving path).
# ---------------------------------------------------------------------------

MODEL_META_NAME = "aios_model.json"


def save_model_checkpoint(directory: str, cfg, params, tokenizer,
                          tp: int = 1) -> None:
    import dataclasses
    import json

    from .tokenizer import HFTokenizer, tokenizer_to_dict

    directory = _abs(directory)
    os.makedirs(directory, exist_ok=True)
    save_params(directory, params)
    if isinstance(tokenizer, HFTokenizer):
        # self-contained: copy the HF tokenizer files into the checkpoint so
        # it deploys without the original model directory
        tokenizer._tok.save_pretrained(os.path.join(directory, "tokenizer"))
        tok_meta = {"type": "hf", "path": "tokenizer"}
    else:
        tok_meta = tokenizer_to_dict(tokenizer)
    # record the stored serving-quantization mode so load can skip the
    # host-staging hop (prequantized leaves restore straight to device —
    # no quantize pass will follow); single source of truth for the
    # detection lives in engine.py
    from .engine import _is_prequantized, _prequantized_mode

    quantized = _prequantized_mode(params) if _is_prequantized(params) else None
    if quantized == "int4":
        # Persisted int4 leaves must satisfy the STRICT kernel rule
        # (target="tpu" in quantize_params): a storage-only q4 leaf baked
        # on a CPU box would serve through the dequantize-in-HBM path on
        # TPU — strictly worse than int8. Engine-load quantization uses
        # target="auto", so re-check here, at the persistence boundary.
        if tp > 1:
            # tp-prepared artifacts run the kernel per device on shard-
            # local dims — validate against those, not the global shapes
            from .engine import _validate_prequantized_tp

            _validate_prequantized_tp(params, tp)
        else:
            from ..ops.int4_matmul import kernel_supported

            # the leaf's ACTUAL stored group is K / G where s4 is
            # [..., G, 1, N] — pick_group(K) may differ when the leaf was
            # quantized with an explicit smaller group
            bad = [
                key
                for key, v in {**params["layers"], "lm_head": params.get("lm_head")}.items()
                if isinstance(v, dict) and "q4" in v
                for K, N in ((v["q4"].shape[-2] * 2, v["q4"].shape[-1]),)
                if not kernel_supported(K, N, K // v["s4"].shape[-3])
            ]
            if bad:
                raise ValueError(
                    "refusing to persist int4 leaves the TPU kernel cannot "
                    f"serve ({', '.join(bad)}): re-quantize with "
                    "quantize_params(..., target='tpu') (prepare_model does "
                    "this) so ineligible dims fall back to int8"
                )
    meta = {
        "format": "aios-tpu-model-v1",
        "config": dataclasses.asdict(cfg),
        "tokenizer": tok_meta,
        "serving_quantized": quantized,
        # tp degree the QUANTIZED layout was prepared for (1 = fused
        # single-chip); informative — the engine re-validates against the
        # actual plan at load
        "prepared_tp": tp if quantized else 1,
    }
    tmp = os.path.join(directory, MODEL_META_NAME + ".tmp")
    with open(tmp, "w") as fh:
        json.dump(meta, fh)
    os.replace(tmp, os.path.join(directory, MODEL_META_NAME))


def is_model_checkpoint(path: str) -> bool:
    return os.path.isfile(
        os.path.join(_abs(path), MODEL_META_NAME)
    ) and is_checkpoint_dir(path)


def cpu_device():
    """The host CPU jax device, or None if that backend is unregistered.
    (Shared with TPUEngine's host-quantize path — keep the probe single.)"""
    try:
        return jax.local_devices(backend="cpu")[0]
    # aios: waive(silent-except): capability probe — "no CPU backend" IS the answer (None), nothing failed
    except Exception:  # noqa: BLE001
        return None


def load_model_checkpoint(directory: str, host_stage: bool = False):
    """Returns (cfg, params, tokenizer) from a prepared model directory."""
    import json

    from .config import ModelConfig
    from .tokenizer import tokenizer_from_dict

    directory = _abs(directory)
    with open(os.path.join(directory, MODEL_META_NAME)) as fh:
        meta = json.load(fh)
    cfg = ModelConfig(**meta["config"])
    # host_stage (opt-in): restore onto the host CPU backend instead of
    # the default device. Callers that will quantize afterwards pass True
    # (ModelManager does: host_stage=bool(quantize)) — restoring a big
    # dense checkpoint straight to the accelerator and THEN quantizing
    # would hold dense + quantized HBM at once (7B OOM). Everyone else
    # restores straight to device: defaulting to the host hop would tax
    # every dense-bf16 restore with an extra copy + transfer. Prequantized
    # checkpoints (prepare_model --quantize) never need the hop: their
    # leaves are final. The engine does final placement either way.
    if meta.get("serving_quantized"):
        host_stage = False
    cpu = cpu_device() if host_stage else None
    if cpu is not None:
        with jax.default_device(cpu):
            params = load_params(directory)
    else:
        params = load_params(directory)
    tok_meta = dict(meta["tokenizer"])
    if tok_meta.get("type") == "hf" and not os.path.isabs(
        tok_meta.get("path", "")
    ):
        tok_meta["path"] = os.path.join(directory, tok_meta["path"])
    tokenizer = tokenizer_from_dict(tok_meta)
    return cfg, params, tokenizer
