"""Stdlib /metrics + /healthz + /debug endpoint for every service.

Each service's ``serve()`` can start one next to its gRPC port — either
by passing ``metrics_port`` explicitly or via the per-service env var
``AIOS_<SERVICE>_METRICS_PORT`` (0 = ephemeral port, useful in tests);
``AIOS_METRICS_HOST`` widens the bind beyond the 127.0.0.1 default for
external scrapers.

Routes:
  * ``/metrics``   — Prometheus text exposition of the process registry;
  * ``/livez``     — pure liveness: always 200 while the process
    answers (point restart-on-failure probes here);
  * ``/healthz``   — JSON readiness/health probe (service-supplied
    ``health_fn`` merged in; the runtime's health_fn folds the SLO view
    in via ``slo.annotate_health``). Returns **503** whenever the
    payload's status is not ``ok`` — a degraded service or an SLO
    breach takes the replica out of LB rotation, without the process
    kill a liveness probe would cause;
  * ``/metrics/fleet`` — federation: the union of every live fleet
    member's /metrics with a ``host`` label injected (404 until
    obs/fleet.py is armed);
  * ``/fleet/members`` — the fleet membership table + transition
    journal (JSON; what fleetctl renders);
  * ``/fleet/announce`` — POST: one member's heartbeat descriptor in,
    ours + known peers back (the membership gossip hop);
  * ``/fleet/drain`` — POST: start this host's graceful drain
    (fleet/drain.py; 202 + current phase, ``?timeout=S`` bounds the
    in-flight wait; ``fleetctl drain`` drives it);
  * ``/debug/requests``  — recent flight-recorder timelines (JSON;
    ``?model=&limit=&events=0&trace=<id>``);
  * ``/debug/trace``     — the same timelines as Chrome trace-event /
    Perfetto JSON (``?model=&limit=``, or ``?snapshot=<id>`` to render a
    frozen anomaly snapshot);
  * ``/debug/trace/fleet`` — one trace id stitched ACROSS the fleet:
    matching timelines fetched from every live peer's recorder, merged
    into per-host Chrome-trace lanes (``?trace=<id>``);
  * ``/debug/spans``     — the finished-span ring (``?name=&limit=``);
  * ``/debug/slo``       — per-model objective evaluation + per-tenant
    breakdown;
  * ``/debug/snapshots`` — frozen anomaly snapshots (``?id=`` for one,
    metadata list otherwise);
  * ``/debug/devprof``   — the device-time attribution ledgers (per
    model/graph dispatches, device-seconds, MFU/HBM utilization) +
    capture status (``?model=``);
  * ``/debug/profile``   — start a bounded on-demand ``jax.profiler``
    capture (``?secs=N``, capped, one at a time → 409 while busy,
    403 unless ``AIOS_TPU_DEVPROF_DUMP_DIR`` is set);
  * ``/debug/tsdb``      — the black-box time-series ring
    (``?name=&verb=&window=&match=k:v``; stats when no name; 404
    until ``AIOS_TPU_TSDB`` arms obs/tsdb.py);
  * ``/debug/tsdb/fleet`` — the same query answered by every live
    fleet member, keyed by host (404 until fleet is armed);
  * ``/debug/incidents`` — frozen incident bundles (``?id=`` for one
    full bundle, metadata list otherwise; 404 until obs/incidents.py
    is armed);
  * ``/debug``           — the machine-readable route index: every row
    of :data:`ROUTES` (tests pin the table complete).
"""

from __future__ import annotations

import json
import logging
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from .metrics import REGISTRY, MetricsRegistry

log = logging.getLogger("aios.obs")

# THE route index — every path the handler serves, one (method, route,
# one-line help) row per route. ``GET /debug`` renders this table, and
# tests/test_obs_lint.py pins it complete against the handler source: a
# new route without its row here fails CI, so the index can never rot
# into a partial map of the endpoint.
ROUTES = (
    ("GET", "/metrics",
     "Prometheus text exposition of the process registry"),
    ("GET", "/metrics/fleet",
     "federation: every live member's /metrics with a host label"),
    ("GET", "/livez",
     "pure liveness: 200 while the process answers"),
    ("GET", "/healthz",
     "JSON readiness probe; 503 when degraded or SLO-breached"),
    ("GET", "/fleet/members",
     "fleet membership table + transition journal"),
    ("POST", "/fleet/announce",
     "one member's heartbeat descriptor in, ours + known peers back"),
    ("POST", "/fleet/drain",
     "start this host's graceful drain (202 + phase, ?timeout=S)"),
    ("GET", "/debug",
     "this route index"),
    ("GET", "/debug/requests",
     "recent flight-recorder timelines (?model=&limit=&trace=)"),
    ("GET", "/debug/trace",
     "timelines as Chrome-trace JSON (?model=&limit=&snapshot=)"),
    ("GET", "/debug/trace/fleet",
     "one trace id stitched across the fleet (?trace=<id>)"),
    ("GET", "/debug/spans",
     "the finished-span ring (?name=&limit=)"),
    ("GET", "/debug/slo",
     "per-model objective evaluation + per-tenant breakdown"),
    ("GET", "/debug/snapshots",
     "frozen anomaly snapshots (?id= for one, metadata otherwise)"),
    ("GET", "/debug/devprof",
     "device-time attribution ledgers + capture status (?model=)"),
    ("GET", "/debug/profile",
     "bounded on-demand profiler capture (?secs=N; 403/409 gated)"),
    ("GET", "/debug/tsdb",
     "time-series query (?name=&verb=&window=&match=k:v; stats bare)"),
    ("GET", "/debug/tsdb/fleet",
     "the same tsdb query answered by every live member, per host"),
    ("GET", "/debug/incidents",
     "frozen incident bundles (?id= for one, metadata otherwise)"),
)


def _debug_response(
    path: str, query: dict,
) -> Optional[Tuple[bytes, str, int]]:
    """Render one /debug/* route -> (body, content_type, status), or
    None for an unknown path. flightrec/slo/devprof import at call time
    because the obs package __init__ imports THIS module before them
    (they are package-level imports everywhere else — every process
    importing aios_tpu.obs has them loaded)."""
    from . import devprof, fleet, flightrec, incidents, slo, tracing
    from . import tsdb as tsdb_mod

    def q(name: str, default: str = "") -> str:
        return query.get(name, [default])[0]

    def qint(name: str, default: int) -> int:
        try:
            return int(q(name, str(default)))
        except ValueError:
            return default

    status = 200
    if path == "/debug":
        # the machine-readable index — one row per served route, straight
        # from the ROUTES table the handler itself is pinned against
        body = json.dumps({
            "routes": [
                {"method": m, "route": r, "help": h} for m, r, h in ROUTES
            ],
        })
    elif path == "/debug/tsdb/fleet":
        if fleet.FLEET is None:
            body = json.dumps({"error": "fleet telemetry not armed"})
            status = 404
        else:
            body = json.dumps(fleet.FLEET.federate_tsdb(query))
    elif path == "/debug/tsdb":
        payload, status = tsdb_mod.handle_query(query)
        body = json.dumps(payload)
    elif path == "/debug/incidents":
        if incidents.STORE is None:
            body = json.dumps({
                "error": "incident store not armed "
                         "(set AIOS_TPU_INCIDENTS=1 or AIOS_TPU_TSDB=1)",
            })
            status = 404
        else:
            incs = incidents.STORE.incidents()
            inc_id = qint("id", 0)
            if inc_id:
                match = [b for b in incs if b["id"] == inc_id]
                if match:
                    body = json.dumps(match[0])
                else:
                    body = json.dumps({"error": "no such incident"})
                    status = 404
            else:
                body = json.dumps({
                    "incidents": [
                        {k: b[k] for k in
                         ("id", "model", "cause", "at", "fields")}
                        | {"tsdb_series": len(b["tsdb"]["series"]),
                           "snapshot_id":
                               b["flightrec"].get("snapshot_id")}
                        for b in incs
                    ],
                })
    elif path == "/debug/requests":
        trace = q("trace")
        limit = qint("limit", 64)
        tls = flightrec.RECORDER.recent(
            model=q("model"), limit=limit * 4 if trace else limit
        )
        if trace:
            # trace filter: the fleet stitcher (and humans chasing one
            # request) want exactly the timelines sharing a traceparent
            tls = [t for t in tls if t.trace_id == trace][-limit:]
        body = json.dumps({
            "requests": [
                t.to_dict(events=q("events", "1") not in ("0", "false"))
                for t in tls
            ],
        })
    elif path == "/debug/trace/fleet":
        if fleet.FLEET is None:
            body = json.dumps({"error": "fleet telemetry not armed"})
            status = 404
        elif not q("trace"):
            body = json.dumps({"error": "trace id required (?trace=<id>)"})
            status = 400
        else:
            body = json.dumps(fleet.FLEET.stitch(
                q("trace"), limit=qint("limit", 64)
            ))
    elif path == "/debug/trace":
        snap_id = qint("snapshot", 0)
        if snap_id:
            snaps = [
                s for s in flightrec.RECORDER.snapshots()
                if s["id"] == snap_id
            ]
            if not snaps:
                # 404, not a 200-with-error body: `curl -f` scripts must
                # not archive the miss as a valid trace capture
                body = json.dumps({"error": "no such snapshot"})
                status = 404
            else:
                # same renderer as the live path — a snapshot keeps its
                # durations and engine-lane events through the freeze
                body = json.dumps(flightrec.snapshot_trace(snaps[0]))
        else:
            model = q("model")
            body = json.dumps(flightrec.chrome_trace(
                flightrec.RECORDER.recent(
                    model=model, limit=qint("limit", 64)
                ),
                flightrec.RECORDER.model_events(model),
            ))
    elif path == "/debug/spans":
        spans = tracing.recent_spans(
            name=q("name"), limit=qint("limit", 200)
        )
        body = json.dumps({
            "spans": [
                {
                    "name": s.name, "trace_id": s.trace_id,
                    "span_id": s.span_id, "parent_id": s.parent_id,
                    "start": s.start, "duration_ms":
                        round(s.duration_s * 1e3, 3),
                    "status": s.status,
                    "attributes": {
                        k: repr(v) if not isinstance(
                            v, (str, int, float, bool, type(None))
                        ) else v
                        for k, v in s.attributes.items()
                    },
                }
                for s in spans
            ],
        })
    elif path == "/debug/slo":
        body = json.dumps({
            "config": vars(slo.ENGINE.cfg),
            "models": {
                m: {
                    "objectives": slo.ENGINE.evaluate(m),
                    "tenants": slo.ENGINE.tenants(m),
                }
                for m in slo.ENGINE.models()
            },
        })
    elif path == "/debug/snapshots":
        snap_id = qint("id", 0)
        snaps = flightrec.RECORDER.snapshots()
        if snap_id:
            match = [s for s in snaps if s["id"] == snap_id]
            if match:
                body = json.dumps(match[0])
            else:
                body = json.dumps({"error": "no such snapshot"})
                status = 404
        else:
            body = json.dumps({
                "snapshots": [
                    {k: s[k] for k in ("id", "model", "cause", "at")}
                    | {"timelines": len(s["timelines"])}
                    for s in snaps
                ],
            })
    elif path == "/debug/devprof":
        body = json.dumps(devprof.snapshot_all(model=q("model")))
    elif path == "/debug/profile":
        try:
            secs = float(q("secs", "2") or 2)
        except ValueError:
            secs = 2.0
        try:
            body = json.dumps(devprof.start_capture(secs))
        except devprof.CaptureDisabled as exc:
            # 403, not 404: the route exists, the deployment opted out
            # (no dump dir); a curl -f script reads the distinction
            body = json.dumps({"error": str(exc)})
            status = 403
        except devprof.CaptureBusy as exc:
            # one capture at a time — a second request must not stack a
            # profiler session on the live plane
            body = json.dumps({"error": str(exc)})
            status = 409
    else:
        return None
    return body.encode("utf-8"), "application/json", status


def start_metrics_server(
    port: int = 0,
    host: str = "127.0.0.1",
    registry: Optional[MetricsRegistry] = None,
    health_fn: Optional[Callable[[], dict]] = None,
) -> Tuple[ThreadingHTTPServer, int]:
    """Start the exposition endpoint on a daemon thread; returns
    (server, bound_port). ``server.shutdown()`` stops it."""
    reg = registry or REGISTRY

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
            parsed = urlparse(self.path)
            path = parsed.path
            status = 200
            if path == "/metrics":
                body = reg.render().encode("utf-8")
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif path == "/metrics/fleet":
                from . import fleet

                if fleet.FLEET is None:
                    body = b'{"error":"fleet telemetry not armed"}'
                    ctype = "application/json"
                    status = 404
                else:
                    body = fleet.FLEET.federate().encode("utf-8")
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif path == "/fleet/members":
                from . import fleet

                if fleet.FLEET is None:
                    body = b'{"error":"fleet telemetry not armed"}'
                    status = 404
                else:
                    body = json.dumps({
                        "self": fleet.FLEET.identity,
                        "members": fleet.FLEET.members(),
                        "journal": fleet.FLEET.journal(),
                        "summary": fleet.FLEET.health_summary(),
                    }).encode("utf-8")
                ctype = "application/json"
            elif path == "/livez":
                # pure liveness: always 200 while the process answers.
                # Point k8s livenessProbe HERE — /healthz 503s on SLO
                # breach, and a liveness probe acting on that would kill
                # the process (losing AOT warmup + KV caches) in a
                # restart loop exactly when the plane is overloaded;
                # /healthz is for readiness / LB rotation decisions.
                body = b'{"status":"alive"}'
                ctype = "application/json"
            elif path == "/healthz":
                # the ACTUAL bound port rides every probe: with
                # AIOS_<SVC>_METRICS_PORT=0 the ephemeral port was
                # otherwise only in serve()'s return value — fleet
                # peers and tests discover it here
                payload = {
                    "status": "ok",
                    "metrics_port": self.server.server_address[1],
                }
                if health_fn is not None:
                    try:
                        payload.update(health_fn())
                    except Exception as exc:  # noqa: BLE001
                        payload = {"status": "degraded",
                                   "error": repr(exc)[:200],
                                   "metrics_port":
                                       self.server.server_address[1]}
                # degraded/SLO-breach is a PROBE FAILURE, not prose: load
                # balancers and k8s probes act on the status code, so a
                # body saying "degraded" under HTTP 200 kept sick
                # replicas in rotation (the ISSUE 8 satellite fix). A
                # health_fn wanting SLO degradation folds it in via
                # slo.annotate_health (the runtime service does).
                if payload.get("status", "ok") != "ok":
                    status = 503
                body = json.dumps(payload).encode("utf-8")
                ctype = "application/json"
            elif path == "/debug" or path.startswith("/debug/"):
                try:
                    rendered = _debug_response(path, parse_qs(parsed.query))
                except Exception as exc:  # noqa: BLE001 - debug routes
                    # must never take down the exposition endpoint
                    rendered = (
                        json.dumps({"error": repr(exc)[:200]}).encode(
                            "utf-8"
                        ),
                        "application/json",
                        500,
                    )
                if rendered is None:
                    self.send_error(404)
                    return
                body, ctype, status = rendered
            else:
                self.send_error(404)
                return
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self):  # noqa: N802 - BaseHTTPRequestHandler API
            from . import fleet

            parsed = urlparse(self.path)
            if parsed.path == "/fleet/drain":
                # graceful drain trigger (fleet/drain.py; fleetctl drain
                # drives it): 202 — the protocol runs on a worker thread
                from ..fleet import drain

                if drain.COORD is None:
                    self.send_error(
                        404, "drain coordinator not armed on this host"
                    )
                    return
                q = parse_qs(parsed.query)
                try:
                    timeout_s = float(q["timeout"][0]) if "timeout" in q \
                        else None
                except ValueError:
                    timeout_s = None
                phase = drain.request_drain(timeout_s)
                body = json.dumps({
                    "phase": phase,
                    "host": fleet.FLEET.identity["host"]
                    if fleet.FLEET is not None else "",
                }).encode("utf-8")
                self.send_response(202)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if parsed.path != "/fleet/announce":
                self.send_error(404)
                return
            if fleet.FLEET is None:
                self.send_error(404, "fleet telemetry not armed")
                return
            try:
                n = min(int(self.headers.get("Content-Length", 0)),
                        4 << 20)
                desc = json.loads(self.rfile.read(n).decode("utf-8"))
                if not isinstance(desc, dict):
                    raise ValueError("announce body must be an object")
                # the server side of a seeded per-edge partition
                # (faults/net.py): the REPLY travels the self->announcer
                # edge — a fired one-way partition still folds the
                # peer's descriptor (their bytes reached us) but
                # withholds the reply; a full partition refuses both
                from ..faults import net

                fold, reply = net.gate_announce(str(desc.get("host", "")))
                if not fold:
                    self.send_error(503, "announce refused: partitioned")
                    return
                reply_body = fleet.FLEET.receive(desc)
                if not reply:
                    self.send_error(503, "announce reply withheld")
                    return
                body = json.dumps(reply_body).encode("utf-8")
                status = 200
            except Exception as exc:  # noqa: BLE001 - a malformed
                # announce must not take down the exposition endpoint
                body = json.dumps({"error": repr(exc)[:200]}).encode("utf-8")
                status = 400
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # scrapes must not spam stderr
            pass

    server = ThreadingHTTPServer((host, port), Handler)
    server.daemon_threads = True
    thread = threading.Thread(
        target=server.serve_forever, name="obs-metrics-http", daemon=True
    )
    thread.start()
    bound = server.server_address[1]
    log.info("metrics endpoint on http://%s:%d/metrics", host, bound)
    return server, bound


def maybe_start_metrics_server(
    service_name: str,
    metrics_port: Optional[int] = None,
    health_fn: Optional[Callable[[], dict]] = None,
) -> Tuple[Optional[ThreadingHTTPServer], Optional[int]]:
    """serve()-helper: start the endpoint when asked for explicitly or via
    ``AIOS_<SERVICE>_METRICS_PORT``; (None, None) otherwise."""
    host = os.environ.get("AIOS_METRICS_HOST", "127.0.0.1")
    if metrics_port is None:
        env = os.environ.get(f"AIOS_{service_name.upper()}_METRICS_PORT")
        if env is None or env == "":
            return None, None
        try:
            metrics_port = int(env)
        except ValueError:
            log.warning(
                "AIOS_%s_METRICS_PORT=%r is not an integer; metrics "
                "endpoint disabled", service_name.upper(), env,
            )
            return None, None
    try:
        server, bound = start_metrics_server(
            port=metrics_port, host=host, health_fn=health_fn
        )
        # the service name + ACTUAL port in one startup line: with
        # AIOS_<SVC>_METRICS_PORT=0 this log (plus /healthz and the
        # fleet announce) is how anything finds the endpoint
        log.info("%s metrics endpoint bound on port %d", service_name,
                 bound)
        from . import fleet, incidents, tsdb

        fleet.maybe_start(service_name, bound, host=host)
        # the history planes ride the same arming pass: every real
        # serving process comes through here, and both are env-gated
        # no-ops (module global stays None) unless asked for
        tsdb.maybe_start()
        incidents.maybe_start()
        return server, bound
    except (OSError, OverflowError) as exc:  # taken port / port > 65535
        # the endpoint is optional: a taken/invalid port must not crash a
        # serve() whose gRPC server is already up
        log.warning(
            "%s metrics endpoint on port %s failed (%s); continuing "
            "without it", service_name, metrics_port, exc,
        )
        return None, None
