"""Tool handler namespaces.

Reference parity: tools/src/{fs,process,service,net,firewall,pkg,sec,
monitor,hw,web,git,code,self_update,plugin,container,email}/ — the full
handler table at executor.rs:111-501. Each handler takes a JSON-dict input
and returns a JSON-dict output; failures raise ToolError.
"""

from __future__ import annotations

import shutil
import subprocess
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional


class ToolError(Exception):
    """Handler failure — becomes ExecuteResponse.error."""


@dataclass(frozen=True)
class ToolSpec:
    fn: Callable[[dict], dict]
    description: str
    reversible: bool = False
    idempotent: bool = False
    target_arg: Optional[str] = None  # which arg names the path to back up
    requires_confirmation: bool = False
    timeout_ms: int = 30_000
    version: str = "1.0.0"


def run_cmd(argv, timeout: float = 30.0, input_text: str | None = None) -> dict:
    """Run a host command; ToolError if the binary is missing or it fails."""
    if shutil.which(argv[0]) is None:
        raise ToolError(f"{argv[0]} is not available on this host")
    try:
        proc = subprocess.run(
            argv,
            capture_output=True,
            text=True,
            timeout=timeout,
            input=input_text,
        )
    except subprocess.TimeoutExpired as exc:
        raise ToolError(f"{argv[0]} timed out after {timeout}s") from exc
    out = {
        "stdout": proc.stdout[-20_000:],
        "stderr": proc.stderr[-5_000:],
        "exit_code": proc.returncode,
    }
    if proc.returncode != 0:
        raise ToolError(
            f"{' '.join(argv[:3])} exited {proc.returncode}: {proc.stderr[:500]}"
        )
    return out


def collect_all() -> Dict[str, ToolSpec]:
    """Aggregate every namespace's TOOLS table."""
    from . import dev, filesystem, netops, pkgsec, system

    table: Dict[str, ToolSpec] = {}
    for mod in (filesystem, system, netops, pkgsec, dev):
        overlap = table.keys() & mod.TOOLS.keys()
        assert not overlap, f"duplicate tool names: {overlap}"
        table.update(mod.TOOLS)
    return table
