#!/usr/bin/env bash
# Operator CLI for a running aiOS-TPU stack.
#
# The reference ships service management inside its initd + systemctl tool
# handlers (/root/reference/scripts/*, service.* tools); on a TPU VM the
# equivalents are this script's probes against the five gRPC services and
# the console's REST API.
#
# Usage: scripts/aiosctl.sh <command>
#   status    one line per service: port reachability
#   health    orchestrator + runtime health detail (console /api/*)
#   serving   per-model TPU serving counters (slots, pages, prefix, queue)
#   goals     recent goals through the console
#   submit "<text>"   submit a goal
#   logs [service]    tail the supervisor's per-service logs
#   start|stop|restart    systemd unit control (install --systemd first)
set -euo pipefail

CONSOLE=${AIOS_CONSOLE:-http://127.0.0.1:9090}
LOG_DIR=${AIOS_LOG_DIR:-/var/lib/aios/data/logs}

# console host:port derived from AIOS_CONSOLE so `status` probes the same
# endpoint the REST subcommands talk to
CONSOLE_HP=${CONSOLE#*://}; CONSOLE_HP=${CONSOLE_HP%%/*}
CONSOLE_HOST=${CONSOLE_HP%%:*}
CONSOLE_PORT=${CONSOLE_HP##*:}; [[ "$CONSOLE_PORT" == "$CONSOLE_HOST" ]] && CONSOLE_PORT=80

declare -A PORTS=(
  [orchestrator]=50051 [tools]=50052 [memory]=50053
  [gateway]=50054 [runtime]=50055 [console]=$CONSOLE_PORT
)

probe() {  # probe <host> <port> — the subshell opens and closes the socket
  (exec 3<>"/dev/tcp/$1/$2") 2>/dev/null
}

cmd=${1:-status}
case "$cmd" in
  status)
    rc=0
    for name in orchestrator tools memory gateway runtime console; do
      port=${PORTS[$name]}
      host=127.0.0.1
      [[ "$name" == console ]] && host=$CONSOLE_HOST
      if probe "$host" "$port"; then
        echo "$name :$port up"
      else
        echo "$name :$port DOWN"
        rc=1
      fi
    done
    exit $rc
    ;;
  health)
    curl -fsS "$CONSOLE/api/health" && echo
    curl -fsS "$CONSOLE/api/status" && echo
    ;;
  serving)
    curl -fsS "$CONSOLE/api/serving" && echo
    ;;
  goals)
    curl -fsS "$CONSOLE/api/goals" && echo
    ;;
  submit)
    [[ $# -ge 2 ]] || { echo "usage: aiosctl.sh submit \"<goal>\"" >&2; exit 2; }
    curl -fsS -X POST "$CONSOLE/api/goals" \
      -H 'Content-Type: application/json' \
      -d "{\"description\": $(python3 -c 'import json,sys; print(json.dumps(sys.argv[1]))' "$2")}" && echo
    ;;
  logs)
    svc=${2:-}
    if [[ -d "$LOG_DIR" ]]; then
      shopt -s nullglob
      logs=("$LOG_DIR"/*.log)
      shopt -u nullglob
      if [[ -n "$svc" ]]; then
        tail -n 100 -f "$LOG_DIR/$svc.log"
      elif [[ ${#logs[@]} -gt 0 ]]; then
        tail -n 20 "${logs[@]}"
      else
        echo "no logs yet in $LOG_DIR"
      fi
    elif command -v journalctl >/dev/null; then
      journalctl -u aios.service -n 100 ${svc:+-g "$svc"} --no-pager
    else
      echo "no $LOG_DIR and no journalctl" >&2; exit 1
    fi
    ;;
  start|stop|restart)
    sudo systemctl "$cmd" aios.service
    ;;
  *)
    echo "unknown command: $cmd (status|health|serving|goals|submit|logs|start|stop|restart)" >&2
    exit 2
    ;;
esac
