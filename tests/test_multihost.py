"""Multi-host data plane e2e: REAL processes, real TCP collectives.

Two `python tests/multihost_worker.py` children each play one host (4
virtual CPU devices apiece), join the process group through the
AIOS_TPU_COORDINATOR env contract, build the global ("dp","sp","tp") mesh
with dp spanning the hosts, and run (a) the cross-host all-reduce probe
and (b) one sharded train step whose gradient all-reduce crosses the
process boundary — both ranks must report the identical loss. This is the
TPU-native counterpart of the reference's multi-node story, which stops
at gRPC remote execution (cluster.rs / remote_exec.rs) and never shares
model state across nodes; here the collective data plane does
(SURVEY.md section 5 "Distributed communication backend").

CPU collectives run over TCP (gloo) — the same code rides DCN on real
pods, where `jax.distributed.initialize` auto-detects the topology.
"""

import os
import socket
import subprocess
import sys

import pytest

# compile-heavy tier: excluded from the fast commit gate (pytest -m fast)
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_group_allreduce_and_train():
    port = _free_port()
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        # the TPU-tunnel site hook must not register its PJRT plugin in
        # CPU-only children (a wedged tunnel would hang them at import)
        "PALLAS_AXON_POOL_IPS": "",
        "PYTHONPATH": REPO,
    }
    worker = os.path.join(REPO, "tests", "multihost_worker.py")
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(pid), "2", f"127.0.0.1:{port}"],
            env=env,
            cwd=REPO,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=540)
            outs.append(out)
    finally:
        # a rank that died early leaves its peer blocked in the coordinator
        # barrier — never leak it past the test
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-2000:]}"
    ok_lines = [
        line
        for out in outs
        for line in out.splitlines()
        if line.startswith("WORKER_OK")
    ]
    assert len(ok_lines) == 2, outs
    # both ranks must agree on the all-reduce AND the post-all-reduce loss
    results = {line.split(" ", 2)[2] for line in ok_lines}
    assert len(results) == 1, ok_lines
