"""aios.memory.MemoryService gRPC implementation (24 RPCs).

Reference parity: memory/src/main.rs — operational/working/long-term tiers,
knowledge base, and AssembleContext which merges tiers into token-budgeted
chunks (4-chars-per-token estimate, same as the reference's context
assembler, agent-core/src/context.rs:64-66,119-122).
"""

from __future__ import annotations

import logging
import time
from typing import Optional

from .. import rpc
from ..proto_gen import memory_pb2 as pb
from ..services import MEMORY, MemoryServiceServicer, service_address
from .migration import MigrationPipeline
from .tiers import LongTermMemory, OperationalMemory, WorkingMemory

log = logging.getLogger("aios.memory")

CHARS_PER_TOKEN = 4  # context.rs:64-66 token estimate


def _estimate_tokens(text: str) -> int:
    return max(1, len(text) // CHARS_PER_TOKEN)


class MemoryService(MemoryServiceServicer):
    def __init__(
        self,
        working_path: str = ":memory:",
        longterm_path: str = ":memory:",
        start_migration: bool = False,
    ):
        self.operational = OperationalMemory()
        self.working = WorkingMemory(working_path)
        self.longterm = LongTermMemory(longterm_path)
        self.migration = MigrationPipeline(
            self.operational, self.working, self.longterm
        )
        if start_migration:
            self.migration.start()
        self.started_at = time.time()

    # -- operational --------------------------------------------------------

    def PushEvent(self, request, context):
        self.operational.push_event(
            {
                "id": request.id,
                "timestamp": request.timestamp,
                "category": request.category,
                "source": request.source,
                "data_json": request.data_json.decode("utf-8", "replace"),
                "critical": request.critical,
            }
        )
        return pb.Empty()

    def GetRecentEvents(self, request, context):
        events = self.operational.recent_events(
            count=request.count or 50,
            category=request.category,
            source=request.source,
        )
        return pb.EventList(
            events=[
                pb.Event(
                    id=e.get("id", ""),
                    timestamp=e.get("timestamp", 0),
                    category=e.get("category", ""),
                    source=e.get("source", ""),
                    data_json=e.get("data_json", "").encode(),
                    critical=e.get("critical", False),
                )
                for e in events
            ]
        )

    def UpdateMetric(self, request, context):
        self.operational.update_metric(request.key, request.value, request.timestamp)
        return pb.Empty()

    def GetMetric(self, request, context):
        got = self.operational.get_metric(request.key)
        if got is None:
            return pb.MetricValue(key=request.key, value=0.0, timestamp=0)
        return pb.MetricValue(key=request.key, value=got[0], timestamp=got[1])

    def GetSystemSnapshot(self, request, context):
        try:
            import psutil

            vm = psutil.virtual_memory()
            disk = psutil.disk_usage("/")
            cpu = psutil.cpu_percent(interval=None)
            snap = pb.SystemSnapshot(
                cpu_percent=cpu,
                memory_used_mb=vm.used / 1e6,
                memory_total_mb=vm.total / 1e6,
                disk_used_gb=disk.used / 1e9,
                disk_total_gb=disk.total / 1e9,
            )
        except Exception:  # psutil unavailable -> zeros
            snap = pb.SystemSnapshot()
        active = self.operational.get_metric("tasks.active")
        agents = self.operational.get_metric("agents.active")
        snap.active_tasks = int(active[0]) if active else 0
        snap.active_agents = int(agents[0]) if agents else 0
        return snap

    # -- working ------------------------------------------------------------

    def StoreGoal(self, request, context):
        self.working.store_goal(
            {
                "id": request.id,
                "description": request.description,
                "status": request.status,
                "priority": request.priority,
                "created_at": request.created_at,
                "completed_at": request.completed_at,
                "result": request.result,
                "metadata_json": request.metadata_json.decode("utf-8", "replace"),
            }
        )
        return pb.Empty()

    def UpdateGoal(self, request, context):
        self.working.update_goal(request.id, request.status, request.result)
        return pb.Empty()

    def GetActiveGoals(self, request, context):
        return pb.GoalList(
            goals=[
                pb.GoalRecord(
                    id=g["id"],
                    description=g["description"],
                    status=g["status"],
                    priority=g["priority"],
                    created_at=g["created_at"],
                    completed_at=g["completed_at"],
                    result=g["result"],
                    metadata_json=g["metadata_json"].encode(),
                )
                for g in self.working.active_goals()
            ]
        )

    def StoreTask(self, request, context):
        self.working.store_task(
            {
                "id": request.id,
                "goal_id": request.goal_id,
                "description": request.description,
                "agent": request.agent,
                "status": request.status,
                "input_json": request.input_json.decode("utf-8", "replace"),
                "output_json": request.output_json.decode("utf-8", "replace"),
                "started_at": request.started_at,
                "completed_at": request.completed_at,
                "duration_ms": request.duration_ms,
                "error": request.error,
            }
        )
        return pb.Empty()

    def GetTasksForGoal(self, request, context):
        return pb.TaskList(
            tasks=[
                pb.TaskRecord(
                    id=t["id"],
                    goal_id=t["goal_id"],
                    description=t["description"],
                    agent=t["agent"],
                    status=t["status"],
                    input_json=t["input_json"].encode(),
                    output_json=t["output_json"].encode(),
                    started_at=t["started_at"],
                    completed_at=t["completed_at"],
                    duration_ms=t["duration_ms"],
                    error=t["error"],
                )
                for t in self.working.tasks_for_goal(request.goal_id)
            ]
        )

    def StoreToolCall(self, request, context):
        self.working.store_tool_call(
            {
                "id": request.id,
                "task_id": request.task_id,
                "tool_name": request.tool_name,
                "agent": request.agent,
                "input_json": request.input_json.decode("utf-8", "replace"),
                "output_json": request.output_json.decode("utf-8", "replace"),
                "success": request.success,
                "duration_ms": request.duration_ms,
                "reason": request.reason,
                "timestamp": request.timestamp,
            }
        )
        return pb.Empty()

    def StoreDecision(self, request, context):
        self.working.store_decision(
            {
                "id": request.id,
                "context": request.context,
                "options_json": request.options_json.decode("utf-8", "replace"),
                "chosen": request.chosen,
                "reasoning": request.reasoning,
                "intelligence_level": request.intelligence_level,
                "model_used": request.model_used,
                "outcome": request.outcome,
                "timestamp": request.timestamp,
            }
        )
        return pb.Empty()

    def StorePattern(self, request, context):
        self.working.store_pattern(
            {
                "id": request.id,
                "trigger": request.trigger,
                "action": request.action,
                "success_rate": request.success_rate,
                "uses": request.uses,
                "last_used": request.last_used,
                "created_from": request.created_from,
            }
        )
        return pb.Empty()

    def FindPattern(self, request, context):
        found = self.working.find_pattern(request.trigger, request.min_success_rate)
        if found is None:
            return pb.PatternResult(found=False)
        return pb.PatternResult(
            found=True,
            pattern=pb.Pattern(
                id=found["id"],
                trigger=found["trigger"],
                action=found["action"],
                success_rate=found["success_rate"],
                uses=found["uses"],
                last_used=found["last_used"],
                created_from=found["created_from"],
            ),
        )

    def UpdatePatternStats(self, request, context):
        self.working.update_pattern_stats(request.id, request.success)
        return pb.Empty()

    def StoreAgentState(self, request, context):
        self.working.store_agent_state(
            request.agent_name, request.state_json.decode("utf-8", "replace")
        )
        return pb.Empty()

    def GetAgentState(self, request, context):
        got = self.working.get_agent_state(request.agent_name)
        if got is None:
            return pb.AgentState(agent_name=request.agent_name)
        return pb.AgentState(
            agent_name=request.agent_name,
            state_json=got[0].encode(),
            updated_at=got[1],
        )

    # -- long-term ----------------------------------------------------------

    def SemanticSearch(self, request, context):
        results = self.longterm.search(
            request.query,
            collections=list(request.collections) or None,
            n_results=request.n_results or 5,
            min_relevance=request.min_relevance,
        )
        return self._search_results(results)

    def StoreProcedure(self, request, context):
        self.longterm.store_procedure(
            {
                "id": request.id,
                "name": request.name,
                "description": request.description,
                "steps_json": request.steps_json.decode("utf-8", "replace"),
                "success_count": request.success_count,
                "fail_count": request.fail_count,
                "avg_duration_ms": request.avg_duration_ms,
                "tags": list(request.tags),
                "created_at": request.created_at,
                "last_used": request.last_used,
            }
        )
        return pb.Empty()

    def StoreIncident(self, request, context):
        self.longterm.store_incident(
            {
                "id": request.id,
                "description": request.description,
                "symptoms_json": request.symptoms_json.decode("utf-8", "replace"),
                "root_cause": request.root_cause,
                "resolution": request.resolution,
                "resolved_by": request.resolved_by,
                "prevention": request.prevention,
                "timestamp": request.timestamp,
            }
        )
        return pb.Empty()

    def StoreConfigChange(self, request, context):
        self.longterm.store_config_change(
            {
                "id": request.id,
                "file_path": request.file_path,
                "content": request.content,
                "changed_by": request.changed_by,
                "reason": request.reason,
                "timestamp": request.timestamp,
            }
        )
        return pb.Empty()

    # -- knowledge ----------------------------------------------------------

    def SearchKnowledge(self, request, context):
        results = self.longterm.search_knowledge(
            request.query,
            n_results=request.n_results or 5,
            min_relevance=request.min_relevance,
        )
        return self._search_results(results)

    def AddKnowledge(self, request, context):
        self.longterm.add_knowledge(
            request.title, request.content, request.source, list(request.tags)
        )
        return pb.Empty()

    # -- context assembly ---------------------------------------------------

    def AssembleContext(self, request, context):
        """Merge tiers into token-budgeted chunks (memory.proto:255-259)."""
        budget = request.max_tokens or 1024
        tiers = set(request.memory_tiers) or {"operational", "working", "longterm"}
        query = request.task_description
        chunks = []
        used = 0

        def add(source: str, content: str, relevance: float) -> bool:
            nonlocal used
            tokens = _estimate_tokens(content)
            if used + tokens > budget:
                return False
            chunks.append(
                pb.ContextChunk(
                    source=source, content=content, relevance=relevance, tokens=tokens
                )
            )
            used += tokens
            return True

        if "longterm" in tiers:
            for r in self.longterm.search(query, n_results=5):
                if not add(f"longterm/{r['collection']}", r["content"], r["relevance"]):
                    break
            for r in self.longterm.search_knowledge(query, n_results=3):
                if not add("knowledge", r["content"], r["relevance"]):
                    break
        if "working" in tiers:
            pattern = self.working.find_pattern(query)
            if pattern is not None:
                add(
                    "working/pattern",
                    f"known pattern '{pattern['trigger']}' -> {pattern['action']}"
                    f" (success {pattern['success_rate']:.0%})",
                    pattern["success_rate"],
                )
            for g in self.working.active_goals()[:3]:
                add("working/goal", f"active goal: {g['description']}", 0.5)
        if "operational" in tiers:
            for ev in self.operational.recent_events(count=5):
                add(
                    "operational/event",
                    f"[{ev.get('category','')}] {ev.get('data_json','')}",
                    0.3,
                )

        return pb.ContextResponse(chunks=chunks, total_tokens=used)

    def _search_results(self, results) -> pb.SearchResults:
        return pb.SearchResults(
            results=[
                pb.SearchResult(
                    content=r["content"],
                    metadata_json=r["metadata_json"].encode(),
                    relevance=r["relevance"],
                    collection=r["collection"],
                    id=r["id"],
                )
                for r in results
            ]
        )


def serve(
    address: Optional[str] = None,
    data_dir: Optional[str] = None,
    block: bool = True,
    metrics_port: Optional[int] = None,
):
    """Start the memory service (reference binds 0.0.0.0:50053,
    memory/src/main.rs:511). ``metrics_port`` (or
    AIOS_MEMORY_METRICS_PORT) also starts /metrics + /healthz."""
    from ..obs.http import maybe_start_metrics_server

    address = address or service_address("memory")
    if data_dir:
        import os

        os.makedirs(data_dir, exist_ok=True)
        service = MemoryService(
            working_path=f"{data_dir}/working.db",
            longterm_path=f"{data_dir}/longterm.db",
            start_migration=True,
        )
    else:
        service = MemoryService(start_migration=True)
    server = rpc.create_server()
    rpc.add_to_server(MEMORY, service, server)
    port = server.add_insecure_port(address)
    server.start()
    service.metrics_server, service.metrics_port = maybe_start_metrics_server(
        "memory", metrics_port, health_fn=lambda: {"service": "memory"}
    )
    log.info("MemoryService listening on %s", address)
    if block:
        server.wait_for_termination()
    return server, service, port


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    import os

    serve(data_dir=os.environ.get("AIOS_DATA_DIR", "/tmp/aios/memory"))
