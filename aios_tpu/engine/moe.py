"""Mixture-of-experts FFN: router + expert computation, TPU-first.

Two implementations of the same math (top-k routed SwiGLU experts):

  * ``moe_ffn_dense`` — every expert processes every token; per-token gate
    weights (zero for unselected experts) scale the outputs. Exact and
    dropless. Decode steps are weight-bandwidth-bound, and at serving batch
    sizes the routed set spans most experts anyway, so streaming all expert
    weights is the honest cost — this is the serving path. The einsum
    contracts over the expert axis, so under expert parallelism (experts
    sharded on the mesh's ``ep`` axis) each device computes its local
    experts and XLA inserts one psum over ``ep`` — no hand-written
    collectives, same GSPMD recipe as the Megatron TP rules
    (parallel/sharding.py).
  * ``moe_ffn_dispatch`` — GShard-style capacity-based dispatch/combine
    one-hot einsums: tokens route to per-expert queues of ``capacity``
    slots, experts run a batched SwiGLU over their queues, outputs combine
    back weighted by the gates. FLOPs scale with k/num_experts instead of
    num_experts — the training/prefill path at large token counts. Tokens
    beyond an expert's capacity are dropped (their contribution from that
    expert is zero), the standard training trade; with generous capacity
    the result is bit-identical to the dense path (tested).

Replaces: nothing in the reference — its only MoE access is the cloud
qwen3:30b endpoint behind the api-gateway (api-gateway/src/main.rs:70-88).
Serving the Qwen3-30B-A3B tier locally is a TPU-build extension.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from .config import ModelConfig


def _expert_einsum(spec: str, x: jnp.ndarray, w) -> jnp.ndarray:
    """einsum where w may be a dense array or an int8 leaf {"q", "s"}.

    Quantized expert leaves keep per-output-channel scales on a size-1
    contraction axis (model.quantize_params, axis=-2), so scaling the
    einsum output by a broadcast of ``s`` reproduces the dequantized
    result — the expert-stacked twin of model.matmul. The spec's output
    must keep the expert axis leading (``x...``): the scales are
    per-(expert, out-channel), so they can only be applied before any
    reduction over experts.
    """
    if isinstance(w, dict):
        w_q, s = w["q"], w["s"]
        assert spec.split("->")[1][0] == "x", spec
        y = jnp.einsum(
            spec, x, w_q, preferred_element_type=jnp.float32
        )
        # s [X, 1, out] -> [X, 1, out] broadcasting over the token/queue axis
        return (y * jnp.squeeze(s, axis=-2)[:, None, :]).astype(x.dtype)
    return jnp.einsum(spec, x, w)


def route(
    h: jnp.ndarray,  # [N, E] normalized hidden states
    w_router,  # [E, X]
    cfg: ModelConfig,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Top-k routing. Returns (probs [N, X] fp32, weights [N, k] fp32,
    idx [N, k] int32). ``probs`` is the full softmax (for the
    load-balancing aux loss); ``weights`` are the selected gates,
    renormalized over the top-k set when cfg.norm_topk_prob (the
    Mixtral/Qwen3-MoE convention)."""
    if isinstance(w_router, dict):  # never quantized, but be safe
        w_router = w_router["q"].astype(jnp.float32) * w_router["s"]
    logits = (h.astype(jnp.float32) @ w_router.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)
    weights, idx = jax.lax.top_k(probs, cfg.num_experts_per_tok)
    if cfg.norm_topk_prob:
        weights = weights / jnp.sum(weights, axis=-1, keepdims=True)
    return probs, weights, idx.astype(jnp.int32)


def gate_matrix(
    weights: jnp.ndarray, idx: jnp.ndarray, num_experts: int
) -> jnp.ndarray:
    """Scatter top-k (weights, idx) into a full [N, X] gate matrix."""
    onehot = jax.nn.one_hot(idx, num_experts, dtype=weights.dtype)  # [N,k,X]
    return jnp.einsum("nk,nkx->nx", weights, onehot)


def load_balance_aux(
    probs: jnp.ndarray, idx: jnp.ndarray, num_experts: int
) -> jnp.ndarray:
    """Switch-transformer load-balancing loss for one layer:
    X * sum_x(fraction_of_tokens_routed_to_x * mean_router_prob_x).
    Equals 1.0 under perfect balance; minimized jointly with the LM loss
    (train.py weights it by moe_aux_coef)."""
    X = num_experts
    counts = jnp.sum(
        jax.nn.one_hot(idx, X, dtype=jnp.float32), axis=(0, 1)
    )  # [X] — how many (token, slot) picks landed on each expert
    frac = counts / jnp.maximum(jnp.sum(counts), 1.0)
    mean_prob = jnp.mean(probs, axis=0)
    return X * jnp.sum(frac * mean_prob)


def moe_ffn_dense(
    h: jnp.ndarray,  # [B, T, E] normalized hidden states
    lp,  # layer params holding w_router / we_gate / we_up / we_down
    cfg: ModelConfig,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Exact dropless MoE FFN; returns (out [B, T, E], aux scalar fp32)."""
    B, T, E = h.shape
    flat = h.reshape(B * T, E)
    probs, weights, idx = route(flat, lp["w_router"], cfg)
    gates = gate_matrix(weights, idx, cfg.num_experts).astype(h.dtype)  # [N,X]

    if "we_gateup" in lp:  # fused serving layout (model.quantize_params)
        F = cfg.expert_dim
        gu = _expert_einsum("ne,xef->xnf", flat, lp["we_gateup"])
        g, u = gu[..., :F], gu[..., F:]
    else:
        g = _expert_einsum("ne,xef->xnf", flat, lp["we_gate"])
        u = _expert_einsum("ne,xef->xnf", flat, lp["we_up"])
    z = jax.nn.silu(g.astype(jnp.float32)).astype(h.dtype) * u  # [X, N, F]
    z = z * gates.T[..., None]  # gate before down-proj: scales per (x, n)
    # Down-project then contract the expert axis — one psum over ep under
    # GSPMD. Quantized leaves need the per-expert scale applied before the
    # expert reduction, hence the explicit xne intermediate + sum.
    if isinstance(lp["we_down"], dict):
        y = _expert_einsum("xnf,xfe->xne", z, lp["we_down"])
        out = jnp.sum(y.astype(jnp.float32), axis=0).astype(h.dtype)
    else:
        out = jnp.einsum("xnf,xfe->ne", z, lp["we_down"])
    aux = load_balance_aux(probs, idx, cfg.num_experts)
    return out.reshape(B, T, E), aux


def moe_ffn_gather(
    h: jnp.ndarray,  # [B, T, E] normalized hidden states
    lp,
    cfg: ModelConfig,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Gathered-expert MoE FFN for SMALL token counts; returns (out, aux).

    Decode is weight-bandwidth-bound, and with N*k picks below the expert
    count most experts are idle — so instead of streaming every expert's
    weights (moe_ffn_dense), gather exactly the N*k routed experts' weight
    blocks and run one batched per-pick SwiGLU. HBM traffic drops from
    X * 3EF bytes to N*k * 3EF bytes per layer: ~16x less FFN traffic for
    a single request on a top-8-of-128 model (qwen3-30b-a3b), ~2x at batch
    8. Exact and dropless — identical math to the dense path, reordered.

    Single-device layouts only: the weight gather indexes the expert axis,
    which under expert parallelism is sharded (an ep-sharded gather would
    bounce picks across chips; the dense path's psum handles that case).
    """
    B, T, E = h.shape
    N = B * T
    k = cfg.num_experts_per_tok
    flat = h.reshape(N, E)
    probs, weights, idx = route(flat, lp["w_router"], cfg)
    picks = idx.reshape(N * k)  # [P] expert id per pick
    x_pick = jnp.repeat(flat, k, axis=0)  # [P, E] token repeated per pick

    def pick_einsum(x, w):  # x [P, E or F], w [X, in, out] -> [P, out]
        if isinstance(w, dict):
            w_q, s = w["q"], w["s"]  # s [X, 1, out]
            y = jnp.einsum(
                "pi,pio->po",
                x,
                w_q[picks],
                preferred_element_type=jnp.float32,
            )
            return (y * s[picks, 0, :]).astype(x.dtype)
        return jnp.einsum("pi,pio->po", x, w[picks])

    if "we_gateup" in lp:  # fused serving layout (quantize_params)
        F = cfg.expert_dim
        gu = pick_einsum(x_pick, lp["we_gateup"])
        g, u = gu[..., :F], gu[..., F:]
    else:
        g = pick_einsum(x_pick, lp["we_gate"])
        u = pick_einsum(x_pick, lp["we_up"])
    z = jax.nn.silu(g.astype(jnp.float32)).astype(h.dtype) * u  # [P, F]
    y_pick = pick_einsum(z, lp["we_down"])  # [P, E]
    out = jnp.sum(
        y_pick.reshape(N, k, E).astype(jnp.float32)
        * weights[..., None],
        axis=1,
    ).astype(h.dtype)
    aux = load_balance_aux(probs, idx, cfg.num_experts)
    return out.reshape(B, T, E), aux


def moe_ffn_dispatch(
    h: jnp.ndarray,  # [B, T, E] normalized hidden states
    lp,
    cfg: ModelConfig,
    capacity_factor: float = 1.25,
    capacity: Optional[int] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Capacity-based GShard dispatch MoE FFN; returns (out, aux).

    ``capacity`` (per-expert queue length) defaults to
    ceil(N * k / X * capacity_factor) rounded up to a multiple of 8 —
    static, so the jit graph is fixed-shape regardless of routing.
    """
    B, T, E = h.shape
    N = B * T
    X, k = cfg.num_experts, cfg.num_experts_per_tok
    flat = h.reshape(N, E)
    probs, weights, idx = route(flat, lp["w_router"], cfg)

    if capacity is None:
        capacity = max(8, int(-(-N * k * capacity_factor // X)))
        capacity = min(-(-capacity // 8) * 8, N * k)

    # Queue position of each (token, slot) pick within its expert, in
    # (token-major, slot-minor) priority order: a running count of prior
    # picks of the same expert.
    onehot = jax.nn.one_hot(idx, X, dtype=jnp.int32).reshape(N * k, X)
    pos = (jnp.cumsum(onehot, axis=0) - onehot)  # picks before this one
    pos = jnp.sum(pos * onehot, axis=-1).reshape(N, k)  # [N, k]
    keep = pos < capacity  # dropped picks contribute zero

    # dispatch [N, k, X, cap] collapses to bool [N, X, cap]; combine is the
    # same structure carrying the gate weights.
    slot_oh = jax.nn.one_hot(
        jnp.where(keep, pos, capacity), capacity, dtype=h.dtype
    )  # [N, k, cap] — overflow rows one-hot off the end -> all-zero
    exp_oh = jax.nn.one_hot(idx, X, dtype=h.dtype)  # [N, k, X]
    combine = jnp.einsum(
        "nk,nkx,nkc->nxc", weights.astype(h.dtype), exp_oh, slot_oh
    )
    dispatch = jnp.einsum("nkx,nkc->nxc", exp_oh, slot_oh)

    xe = jnp.einsum("nxc,ne->xce", dispatch, flat)  # [X, cap, E]
    if "we_gateup" in lp:
        F = cfg.expert_dim
        gu = _expert_einsum("xce,xef->xcf", xe, lp["we_gateup"])
        g, u = gu[..., :F], gu[..., F:]
    else:
        g = _expert_einsum("xce,xef->xcf", xe, lp["we_gate"])
        u = _expert_einsum("xce,xef->xcf", xe, lp["we_up"])
    z = jax.nn.silu(g.astype(jnp.float32)).astype(h.dtype) * u
    ye = _expert_einsum("xcf,xfe->xce", z, lp["we_down"])
    out = jnp.einsum("nxc,xce->ne", combine, ye)
    aux = load_balance_aux(probs, idx, X)
    return out.reshape(B, T, E), aux
