#!/usr/bin/env python3
"""Fault-domain smoke: three REAL processes survive a seeded asymmetric
partition, a mid-stream link sever, and a graceful drain — with the
survivor streams token-identical to a solo reference (the preflight.sh
gate 8; docs/TESTING.md, docs/FAULTS.md "Per-edge network faults").

The cast (each process carries its OWN seeded ``AIOS_TPU_FAULTS``
schedule — per-edge faults are client-side, so each host injects only
its own outbound edges plus its announce-reply gate):

  A  prefill host. Schedule: ``net.drop_after=nth:1,dst=hostB,
     surface=rpc,after_msgs=3`` — the FIRST A->B response stream (the
     first Handoff) severs after 3 messages. Breaker knobs tightened
     (threshold 1, 2 probes, short cooldown) so one sever quarantines
     and two federation scrapes heal.
  B  decode host. Schedule: ``net.partition_oneway=nth:4,until=60,
     dst=hostA,surface=http`` — after ~1 clean announce round, EVERY
     B->A http edge traversal in the hit window [4, 60] drops: B's
     outbound announces refuse at check_send AND B's replies to A's
     announces are withheld by the server-side gate (A's descriptor
     still folds — that direction is clean). Plus ``dispatch.delay=
     prob:1.0`` so decoded tokens trickle at a real cadence and the
     drain provably lands mid-stream.
  C  decode host, no faults — the control: it must finish the smoke
     with ZERO breaker transitions (healthy fleets never quarantine).

The acts:

  1. solo reference on A (``no_peer`` route — same weights as the
     fleet runs);
  2. spawn C, wait up; spawn B, wait up (B's hits 1-3 let the first
     announce fold B's full descriptor into A before the window slams);
  3. asymmetric-partition evidence: A walks B up->suspect->dead while
     B still sees A "up" (the reverse edge is clean); A counts
     announce failures to B; the window exhausts and A heals B to up;
  4. stream 1: A hands off to B (least-loaded lexicographic tie), the
     link severs after 3 chunks, the breaker opens (-> B quarantined),
     the resume ladder re-hands to C, and the text matches the
     reference exactly;
  5. quarantine heals: polling A's ``/metrics/fleet`` drives federation
     scrapes of B — the half-open probes — until the breaker gauge
     returns to closed; C's gauge never left 0;
  6. drain e2e: a live StreamInfer routes to B again, then ``fleetctl
     drain --host hostB`` walks B through draining->leaving: B aborts
     the relay per-token (A re-hands to C mid-stream), pushes its hot
     chains to C, announces ``phase=leaving``, exits 0 — and the
     joined stream text still matches the reference.

The whole round runs TWICE; the port-free verdicts must be identical
across runs (the seeded-determinism contract). Human progress goes to
stderr; ONE JSON verdict line goes to stdout. Exit 0 on pass.
"""

import json
import os
import re
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")

SCALE = float(os.environ.get("FLEET_SMOKE_TIME_SCALE", "1") or 1)
INTERVAL = 0.3 * SCALE
SUSPECT = 1.5 * SCALE
DEAD = 3.0 * SCALE
MODEL = "fleet-smoke"
# chosen for its generation shape on synthetic://tiny-test: 200
# char-level tokens (>= one full 128-token KV page, so chains export
# and the drain has hot pages to push) and a full 16-token generation
# whose streamed deltas concatenate to exactly the unary text
PROMPT = "0 1 2 3 4 5 6 7 8 9 " * 10
MAX_TOKENS = 16
# B's per-token decode delay: wide enough that spawning fleetctl (a
# stdlib-only CLI) provably lands the drain before the stream finishes
DELAY_MS = int(150 * SCALE)

FAULTS_A = (
    "seed=11;net.drop_after=nth:1,dst=hostB,surface=rpc,after_msgs=3"
)
FAULTS_B = (
    "seed=11;net.partition_oneway=nth:4,until=60,dst=hostA,surface=http"
    f";dispatch.delay=prob:1.0,delay_ms={DELAY_MS}"
)
# one sever opens the breaker; two clean federation scrapes close it
BREAKER_ENV_A = {
    "AIOS_TPU_FLEET_BREAKER_THRESHOLD": "1",
    "AIOS_TPU_FLEET_BREAKER_PROBES": "2",
    "AIOS_TPU_FLEET_BREAKER_COOLDOWN_SECS": str(0.4 * SCALE),
    "AIOS_TPU_FLEET_BREAKER_MAX_COOLDOWN_SECS": str(2.0 * SCALE),
}


def log(*args) -> None:
    print(*args, file=sys.stderr, flush=True)


def worker_env(host_id: str, fleet_role: str, peers: str = "",
               faults: str = "", extra: dict = None) -> dict:
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "PALLAS_AXON_POOL_IPS": "",
        "PYTHONPATH": REPO,
        "AIOS_TPU_FLEET": "1",
        "AIOS_TPU_FLEET_HOST": host_id,
        "AIOS_TPU_FLEET_ROLE": fleet_role,
        "AIOS_TPU_FLEET_PEERS": peers,
        "AIOS_TPU_FLEET_INTERVAL_SECS": str(INTERVAL),
        "AIOS_TPU_FLEET_SUSPECT_SECS": str(SUSPECT),
        "AIOS_TPU_FLEET_DEAD_SECS": str(DEAD),
        "AIOS_TPU_PAGED_KV": "auto",
        "AIOS_TPU_PREFIX_HOST_BYTES": str(32 << 20),
    }
    env.pop("AIOS_TPU_FAULTS", None)
    if faults:
        env["AIOS_TPU_FAULTS"] = faults
    if extra:
        env.update(extra)
    return env


def spawn_worker(host_id: str, fleet_role: str, peers: str = "",
                 faults: str = "", extra: dict = None,
                 stderr=subprocess.DEVNULL) -> tuple:
    """-> (Popen, grpc_port, metrics_port); waits for the ready line."""
    p = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "scripts", "fleet_worker.py")],
        env=worker_env(host_id, fleet_role, peers, faults, extra),
        cwd=REPO, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=stderr, text=True,
    )
    deadline = time.monotonic() + 180 * SCALE
    while True:
        line = p.stdout.readline()
        if line.startswith("FLEET_WORKER_READY "):
            ports = json.loads(line.split(" ", 1)[1])
            return p, ports["grpc_port"], ports["metrics_port"]
        if not line and p.poll() is not None:
            raise RuntimeError(f"worker {host_id} died before ready")
        if time.monotonic() > deadline:
            p.kill()
            raise RuntimeError(f"worker {host_id} never became ready")


def fetch_json(port: int, path: str) -> dict:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as r:
        return json.loads(r.read().decode("utf-8"))


def fetch_text(port: int, path: str) -> str:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as r:
        return r.read().decode("utf-8")


def poll(fn, what: str, timeout: float):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = fn()
        if out:
            return out
        time.sleep(0.1 * SCALE)
    raise RuntimeError(f"timed out waiting for {what}")


def member_row(port: int, host: str) -> dict:
    for m in fetch_json(port, "/fleet/members")["members"]:
        if m.get("host") == host:
            return m
    return {}


def infer(grpc_port: int, task_id: str) -> str:
    from aios_tpu import rpc, services
    from aios_tpu.proto_gen import runtime_pb2

    channel = rpc.insecure_channel(f"127.0.0.1:{grpc_port}")
    try:
        resp = services.AIRuntimeStub(channel).Infer(
            runtime_pb2.InferRequest(
                model=MODEL, prompt=PROMPT, max_tokens=MAX_TOKENS,
                temperature=5e-5, task_id=task_id,
            ),
            timeout=180,
        )
        return resp.text
    finally:
        channel.close()


def stream_infer(grpc_port: int, task_id: str) -> str:
    """StreamInfer the prompt with the incremental-delta client
    contract -> the joined text."""
    from aios_tpu import rpc, services
    from aios_tpu.proto_gen import runtime_pb2

    channel = rpc.insecure_channel(f"127.0.0.1:{grpc_port}")
    parts = []
    try:
        for chunk in services.AIRuntimeStub(channel).StreamInfer(
            runtime_pb2.InferRequest(
                model=MODEL, prompt=PROMPT, max_tokens=MAX_TOKENS,
                temperature=5e-5, task_id=task_id,
            ),
            timeout=180,
        ):
            if chunk.done:
                break
            parts.append(chunk.text)
        return "".join(parts)
    finally:
        channel.close()


def counter(metrics_text: str, name: str, **labels) -> float:
    """One sample's value out of the exposition text, 0.0 when the
    child was never touched (pre-registered children render as 0)."""
    want = {k: str(v) for k, v in labels.items()}
    for line in metrics_text.splitlines():
        m = re.match(rf"^{re.escape(name)}\{{([^}}]*)\}} (\S+)$", line)
        if m:
            got = dict(re.findall(r'(\w+)="([^"]*)"', m.group(1)))
            if got == want:
                return float(m.group(2))
    return 0.0


def counter_any(metrics_text: str, name: str, **labels) -> float:
    """Sum of every sample whose labels INCLUDE the given subset —
    for families keyed by ephemeral ports (the announce peer label)."""
    want = {k: str(v) for k, v in labels.items()}
    total = 0.0
    for line in metrics_text.splitlines():
        m = re.match(rf"^{re.escape(name)}\{{([^}}]*)\}} (\S+)$", line)
        if m:
            got = dict(re.findall(r'(\w+)="([^"]*)"', m.group(1)))
            if all(got.get(k) == v for k, v in want.items()):
                total += float(m.group(2))
    return total


def breaker_gauge(metrics_a: int, peer: str) -> float:
    return counter(
        fetch_text(metrics_a, "/metrics"),
        "aios_tpu_fleet_peer_breaker_state_total",
        host="hostA", peer=peer,
    )


def run_round(tag: str) -> dict:
    """One full smoke round -> the port-free verdict dict."""
    pa, grpc_a, metrics_a = spawn_worker(
        "hostA", "prefill", faults=FAULTS_A, extra=BREAKER_ENV_A,
    )
    pb = pc = None
    b_errlog = tempfile.NamedTemporaryFile(
        mode="w+", suffix=".hostB.stderr", delete=False,
    )
    try:
        # -- act 1: solo references (the no_peer route, twice). The
        # streamed reference is collected with the SAME incremental
        # client as the drain act — unary and streamed detokenization
        # may legitimately resegment differently ---------------------
        ref = infer(grpc_a, "partition-smoke-ref")
        ref_s = stream_infer(grpc_a, "partition-smoke-ref-stream")
        log(f"[{tag}] solo references: unary={len(ref)} chars, "
            f"streamed={len(ref_s)} chars")

        # -- act 2: C (control) first, then B (its fault window starts
        # counting the moment its announce loop does) --------------------
        pc, _, _ = spawn_worker(
            "hostC", "decode", peers=f"127.0.0.1:{metrics_a}",
        )
        poll(
            lambda: member_row(metrics_a, "hostC").get("state") == "up"
            and member_row(metrics_a, "hostC").get("kvx_addr"),
            "hostC up with kvx_addr on A", 30 * SCALE,
        )
        pb, _, metrics_b = spawn_worker(
            "hostB", "decode", peers=f"127.0.0.1:{metrics_a}",
            faults=FAULTS_B, stderr=b_errlog,
        )
        poll(
            lambda: member_row(metrics_a, "hostB").get("state") == "up"
            and member_row(metrics_a, "hostB").get("kvx_addr"),
            "hostB up with kvx_addr on A (the pre-window announce)",
            30 * SCALE,
        )
        log(f"[{tag}] both decode hosts folded into A's table")

        # -- act 3: the asymmetric partition ----------------------------
        poll(
            lambda: member_row(metrics_a, "hostB").get("state")
            == "suspect",
            "A suspecting hostB", 30 * SCALE,
        )
        poll(
            lambda: member_row(metrics_a, "hostB").get("state") == "dead",
            "A declaring hostB dead", 30 * SCALE,
        )
        # the reverse edge is clean: B still sees A up, mid-partition
        asym = member_row(metrics_b, "hostA").get("state") == "up"
        announce_fails = counter_any(
            fetch_text(metrics_a, "/metrics"),
            "aios_tpu_fleet_announce_failures_total",
        )
        poll(
            lambda: member_row(metrics_a, "hostB").get("state") == "up",
            "the window exhausting and A healing hostB", 60 * SCALE,
        )
        partition_fired = counter(
            fetch_text(metrics_b, "/metrics"),
            "aios_tpu_faults_injected_total",
            point="net.partition_oneway", mode="nth",
        )
        log(f"[{tag}] partition arc complete: asym={asym} "
            f"announce_fails={announce_fails} fired={partition_fired}")

        # -- act 4: the severed handoff + quarantine --------------------
        out1 = infer(grpc_a, "partition-smoke-sever")
        sever_fired = counter(
            fetch_text(metrics_a, "/metrics"),
            "aios_tpu_faults_injected_total",
            point="net.drop_after", mode="nth",
        )
        quarantined = breaker_gauge(metrics_a, "hostB")
        log(f"[{tag}] severed stream done: sever_fired={sever_fired} "
            f"breaker(hostB)={quarantined}")

        # -- act 5: federation scrapes are the half-open probes ---------
        def breaker_closed():
            fetch_text(metrics_a, "/metrics/fleet")  # drives the scrape
            return breaker_gauge(metrics_a, "hostB") == 0.0

        poll(breaker_closed, "the breaker healing through probes",
             30 * SCALE)
        control_gauge = breaker_gauge(metrics_a, "hostC")
        log(f"[{tag}] quarantine healed; control breaker(hostC)="
            f"{control_gauge}")

        # -- act 6: graceful drain under a LIVE stream. The watcher
        # thread fires fleetctl the moment A's route counter shows the
        # second handoff established (the stream is live ON hostB),
        # well inside the ~15-token decode window -----------------------
        fleetctl = {}

        def drain_watcher():
            deadline = time.monotonic() + 60 * SCALE
            while time.monotonic() < deadline:
                v = counter(
                    fetch_text(metrics_a, "/metrics"),
                    "aios_tpu_fleet_route_total",
                    model=MODEL, reason="handoff",
                )
                if v >= 2.0:
                    fleetctl["proc"] = subprocess.Popen(
                        [
                            sys.executable,
                            os.path.join(REPO, "scripts", "fleetctl.py"),
                            "drain", "--target",
                            f"127.0.0.1:{metrics_a}",
                            "--host", "hostB",
                            "--timeout", str(30 * SCALE), "--json",
                        ],
                        cwd=REPO, stdout=subprocess.PIPE,
                        stderr=subprocess.DEVNULL, text=True,
                    )
                    return
                time.sleep(0.05 * SCALE)

        watcher = threading.Thread(target=drain_watcher, daemon=True)
        watcher.start()
        out2 = stream_infer(grpc_a, "partition-smoke-drain")
        watcher.join(timeout=60 * SCALE)
        ctl = fleetctl.get("proc")
        if ctl is None:
            raise RuntimeError(
                "the drain never started: the second handoff was never "
                "observed on the route counter"
            )
        b_status = pb.wait(timeout=60 * SCALE)
        pb = None
        ctl_out, _ = ctl.communicate(timeout=60 * SCALE)
        ctl_verdict = json.loads(ctl_out.strip().splitlines()[-1])
        b_phase = member_row(metrics_a, "hostB").get("phase")
        b_errlog.flush()
        with open(b_errlog.name) as f:
            m = re.search(r"drain push moved (\d+)/(\d+)", f.read())
        drain_pushed = int(m.group(1)) if m else -1
        log(f"[{tag}] drain done: b_exit={b_status} "
            f"fleetctl_exit={ctl.returncode} phase={b_phase} "
            f"pushed={drain_pushed}")

        # -- the verdict ------------------------------------------------
        metrics = fetch_text(metrics_a, "/metrics")
        routes = {
            reason: counter(
                metrics, "aios_tpu_fleet_route_total",
                model=MODEL, reason=reason,
            )
            for reason in ("no_peer", "handoff", "handoff_resume",
                           "fallback_local")
        }
        verdict = {
            "text1_matches": out1 == ref,
            "text2_matches": out2 == ref_s,
            "text_len": len(ref),
            "stream_len": len(ref_s),
            "routes": routes,
            "asym_b_saw_a_up": asym,
            "announce_failures_counted": announce_fails > 0,
            "partition_fired": partition_fired > 0,
            "sever_fired": sever_fired,
            "quarantine_entered": quarantined == 1.0,
            "control_breaker_untouched": control_gauge == 0.0,
            "b_exit": b_status,
            "fleetctl_exit": ctl.returncode,
            "fleetctl_pass": bool(ctl_verdict.get("pass")),
            "b_phase_leaving": b_phase == "leaving",
            "drain_pushed_pages": drain_pushed,
        }
        verdict["pass"] = (
            verdict["text1_matches"] and verdict["text2_matches"]
            and routes["no_peer"] == 2.0
            and routes["handoff"] == 2.0
            and routes["handoff_resume"] == 2.0
            and routes["fallback_local"] == 0.0
            and verdict["asym_b_saw_a_up"]
            and verdict["announce_failures_counted"]
            and verdict["partition_fired"]
            and sever_fired == 1.0
            and verdict["quarantine_entered"]
            and verdict["control_breaker_untouched"]
            and b_status == 0
            and ctl.returncode == 0
            and verdict["fleetctl_pass"]
            and verdict["b_phase_leaving"]
            and drain_pushed > 0
        )
        if not verdict["pass"]:
            log(f"[{tag}] FAIL detail: ref={ref!r} out1={out1!r} "
                f"out2={out2!r}")
        return verdict
    finally:
        for p in (pa, pb, pc):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait()
        b_errlog.close()
        try:
            os.unlink(b_errlog.name)
        except OSError:
            pass


def main() -> int:
    rounds = [run_round("round1"), run_round("round2")]
    identical = rounds[0] == rounds[1]
    verdict = {
        "smoke": "partition",
        "round": rounds[0],
        "identical": identical,
        "pass": identical and all(r["pass"] for r in rounds),
    }
    print(json.dumps(verdict, sort_keys=True))
    if not identical:
        log("FAIL: verdicts diverged across seeded runs:")
        log(f"  round1: {rounds[0]}")
        log(f"  round2: {rounds[1]}")
    return 0 if verdict["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
