"""Per-peer circuit breakers — the fleet's gray-host quarantine.

The membership plane (obs/fleet.py) answers "is the peer's heartbeat
fresh?". That is the wrong question for a GRAY host: one that
heartbeats fine while every data-plane RPC to it crawls or fails.
Before this module, such a peer stayed "up" forever and every prefill
paid a full kvx timeout re-probing it — the retry-every-prefill
behavior ISSUE 18 retires.

Every cross-host call site (kvx push/fetch, Handoff, federation
scrapes, trace stitches) feeds one :class:`BreakerBoard` — a per-peer
EWMA of latency plus a cause-weighted failure score driving the closed
circuit-breaker state machine (:data:`BREAKER_STATES`, pinned by
test_obs_lint):

    closed     healthy: calls flow, failures accumulate score
    open       quarantined: calls refused locally until the cooldown
               elapses (exponential per-peer backoff, capped)
    half_open  probing: a bounded budget of real calls may pass; N
               consecutive successes close the breaker, one failure
               re-opens it with a doubled cooldown

``quarantined`` is an OVERLAY on up/suspect/dead, deliberately
orthogonal: heartbeats alone can never clear it — announce outcomes do
not feed this board — only successful data-plane probes can. Routers
(`FleetRouter`, ``pick_decode``, ``gprefix.best_peer``) treat a
quarantined peer as absent; the federation loop's scrapes double as the
half-open probes, so an idle fleet still heals.

Failure causes are weighted (``crc_mismatch`` > ``timeout``): a peer
returning *corrupt* payloads is actively poisoning callers and trips
the breaker faster than one that is merely slow.

State edges land on ``aios_tpu_fleet_peer_breaker_state_total{host,
peer}`` (value = BREAKER_STATES index) and the flight recorder's fleet
lane as ``quarantine`` events. Knobs: docs/CONFIG.md "Fleet fault
domain".
"""

from __future__ import annotations

import logging
import os
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..analysis.locks import make_lock

log = logging.getLogger("aios.fleet.breaker")

__all__ = [
    "BREAKER_STATES", "BreakerBoard", "BOARD", "reset",
]

# THE closed breaker enum (pinned by test_obs_lint; the gauge value is
# an index into it, so order is part of the contract).
BREAKER_STATES = ("closed", "open", "half_open")

# cause -> score weight: how hard one failure of that flavor pushes the
# peer toward quarantine. Corruption outweighs slowness — a peer
# shipping bad bytes burns caller work on every touch; an unknown cause
# weighs 1.0.
CAUSE_WEIGHTS: Dict[str, float] = {
    "crc_mismatch": 2.0,
    "timeout": 1.0,
    "unavailable": 1.0,
    "decode_error": 2.0,
}

# EWMA smoothing for the per-peer latency estimate (informational +
# the optional latency floor): ~10-call memory.
_LAT_ALPHA = 0.2

# how much one SUCCESS decays the failure score in the closed state —
# occasional blips on a busy edge never accumulate to a trip
_OK_DECAY = 0.5


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class BreakerConfig:
    """Knobs (docs/CONFIG.md "Fleet fault domain"), read at construction
    so worker processes and tests configure per process."""

    def __init__(self) -> None:
        # failure score at which a closed breaker opens
        self.threshold = _env_float("AIOS_TPU_FLEET_BREAKER_THRESHOLD", 3.0)
        # first-open cooldown; doubles per consecutive open, capped
        self.cooldown_secs = _env_float(
            "AIOS_TPU_FLEET_BREAKER_COOLDOWN_SECS", 5.0
        )
        self.max_cooldown_secs = _env_float(
            "AIOS_TPU_FLEET_BREAKER_MAX_COOLDOWN_SECS", 60.0
        )
        # consecutive half-open successes required to close; also the
        # probe budget one half-open window may spend
        self.probes = int(_env_float("AIOS_TPU_FLEET_BREAKER_PROBES", 3.0))
        # optional gray-latency floor (seconds): a latency EWMA past it
        # counts like a failure even when calls "succeed"; 0 disables
        self.lat_floor_secs = _env_float(
            "AIOS_TPU_FLEET_BREAKER_LAT_SECS", 0.0
        )


class _Peer:
    """One peer's breaker bookkeeping — all fields guarded by the
    board's lock."""

    __slots__ = ("state", "score", "lat_ewma", "opens", "opened_at",
                 "cooldown", "probes_left", "streak")

    def __init__(self) -> None:
        self.state = "closed"
        self.score = 0.0
        self.lat_ewma = 0.0
        self.opens = 0          # consecutive opens -> cooldown exponent
        self.opened_at = 0.0
        self.cooldown = 0.0
        self.probes_left = 0
        self.streak = 0         # consecutive half-open successes


class BreakerBoard:
    """The per-process board of per-peer breakers. ``clock`` is
    injectable for deterministic state-machine tests."""

    def __init__(self, cfg: Optional[BreakerConfig] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.cfg = cfg or BreakerConfig()
        self.clock = clock
        self._lock = make_lock("quarantine")
        self._peers: Dict[str, _Peer] = {}  #: guarded_by _lock

    # -- the feeding surface --------------------------------------------------

    def allow(self, peer: str) -> bool:
        """May a cross-host call to ``peer`` proceed? closed -> yes;
        open -> no until the cooldown elapses (then the breaker goes
        half-open and this call consumes one probe slot); half-open ->
        yes while the probe budget lasts."""
        if not peer:
            return True
        edges: List[Tuple[str, str, str, str]] = []
        with self._lock:
            p = self._peers.get(peer)
            if p is None or p.state == "closed":
                return True
            if p.state == "open":
                if self.clock() - p.opened_at < p.cooldown:
                    return False
                self._transition(p, peer, "half_open", "cooldown_elapsed",
                                 edges)
                p.probes_left = max(1, self.cfg.probes)
                p.streak = 0
            if p.probes_left <= 0:
                allowed = False
            else:
                p.probes_left -= 1
                allowed = True
        self._emit(edges)
        return allowed

    def record_ok(self, peer: str, latency_s: float = 0.0) -> None:
        """A cross-host call to ``peer`` succeeded (data plane or
        probe). NEVER called for heartbeat announces — heartbeats must
        not clear quarantine."""
        if not peer:
            return
        edges: List[Tuple[str, str, str, str]] = []
        with self._lock:
            p = self._ensure(peer)
            p.lat_ewma = (
                latency_s if p.lat_ewma == 0.0
                else (1 - _LAT_ALPHA) * p.lat_ewma + _LAT_ALPHA * latency_s
            )
            floor = self.cfg.lat_floor_secs
            if floor > 0 and p.lat_ewma > floor:
                # "success" past the gray floor IS the gray-host case
                self._score_failure(p, peer, "gray_latency", 1.0, edges)
            elif p.state == "half_open":
                p.streak += 1
                if p.streak >= max(1, self.cfg.probes):
                    self._transition(p, peer, "closed", "probes_ok", edges)
                    p.score = 0.0
                    p.opens = 0
            else:
                p.score *= _OK_DECAY
        self._emit(edges)

    def record_failure(self, peer: str, cause: str = "unavailable") -> None:
        """A cross-host call to ``peer`` failed; ``cause`` picks the
        score weight (kvx.KVX_FAIL_CAUSES vocabulary plus
        "gray_latency")."""
        if not peer:
            return
        edges: List[Tuple[str, str, str, str]] = []
        with self._lock:
            p = self._ensure(peer)
            self._score_failure(
                p, peer, cause, CAUSE_WEIGHTS.get(cause, 1.0), edges
            )
        self._emit(edges)

    # -- the routing surface --------------------------------------------------

    def quarantined(self, peer: str) -> bool:
        """True while the peer's breaker is anything but closed —
        routers treat such a peer as absent."""
        with self._lock:
            p = self._peers.get(peer)
            return p is not None and p.state != "closed"

    def state(self, peer: str) -> str:
        with self._lock:
            p = self._peers.get(peer)
            return p.state if p is not None else "closed"

    def snapshot(self) -> Dict[str, dict]:
        """Per-peer debug view (tests, /fleet/members overlays)."""
        with self._lock:
            return {
                peer: {
                    "state": p.state,
                    "score": round(p.score, 3),
                    "lat_ewma": round(p.lat_ewma, 6),
                    "opens": p.opens,
                    "cooldown": p.cooldown,
                    "probes_left": p.probes_left,
                }
                for peer, p in sorted(self._peers.items())
            }

    # -- internals ------------------------------------------------------------

    def _ensure(self, peer: str) -> _Peer:
        # caller holds _lock
        p = self._peers.get(peer)
        if p is None:
            # aios: waive(guarded-by): private helper invoked only from record_ok/record_failure with _lock already held — the with-block lives in the caller
            p = self._peers[peer] = _Peer()
        return p

    def _score_failure(self, p: _Peer, peer: str, cause: str,
                       weight: float, edges: List[Tuple[str, str, str, str]]
                       ) -> None:
        # caller holds _lock
        p.score += weight
        if p.state == "half_open":
            # one failed probe re-opens with a doubled cooldown
            self._open(p, peer, cause, edges)
        elif p.state == "closed" and p.score >= self.cfg.threshold:
            self._open(p, peer, cause, edges)

    def _open(self, p: _Peer, peer: str, cause: str,
              edges: List[Tuple[str, str, str, str]]) -> None:
        # caller holds _lock
        p.opens += 1
        p.opened_at = self.clock()
        p.cooldown = min(
            self.cfg.cooldown_secs * (2.0 ** (p.opens - 1)),
            self.cfg.max_cooldown_secs,
        )
        p.probes_left = 0
        p.streak = 0
        self._transition(p, peer, "open", cause, edges)

    def _transition(self, p: _Peer, peer: str, to: str, why: str,
                    edges: List[Tuple[str, str, str, str]]) -> None:
        # caller holds _lock; emission happens in _emit after release
        frm, p.state = p.state, to
        edges.append((peer, frm, to, why))

    def _emit(self, edges: List[Tuple[str, str, str, str]]) -> None:
        """Metric + recorder evidence for breaker edges — outside the
        quarantine lock (no quarantine->recorder/metrics edge)."""
        if not edges:
            return
        from ..faults import net
        from ..obs import flightrec, incidents, instruments

        host = net.self_host()
        for peer, frm, to, why in edges:
            # gauge value = index into the closed BREAKER_STATES enum —
            # registration and rendering iterate the same tuple
            instruments.FLEET_PEER_BREAKER.labels(
                host=host, peer=peer
            ).set(float(BREAKER_STATES.index(to)))
            flightrec.RECORDER.model_event(
                "fleet", "quarantine", peer=peer, frm=frm, to=to,
                cause=why,
            )
            if to == "open":
                # a quarantined peer is exactly the moment to freeze
                # the surrounding telemetry window (no-op when unarmed)
                incidents.notify("fleet", "breaker_open",
                                 peer=peer, why=why)
            log.warning("fleet peer breaker %s: %s -> %s (%s)",
                        peer, frm, to, why or "?")


# -- process-wide board ------------------------------------------------------

BOARD = BreakerBoard()


def reset(cfg: Optional[BreakerConfig] = None,
          clock: Callable[[], float] = time.monotonic) -> BreakerBoard:
    """Swap in a fresh board (tests / env re-reads); returns it."""
    global BOARD
    BOARD = BreakerBoard(cfg=cfg, clock=clock)
    return BOARD
