"""Parallelism: device meshes, TP/DP/SP sharding plans, ring attention.

The reference has no ML parallelism at all (SURVEY.md section 2.4 — each
model is one llama-server process); this package is where the TPU build
scales instead: jax.sharding meshes with XLA collectives over ICI/DCN.
"""
