"""aiOS-TPU — a TPU-native rebuild of the aiOS "AI Operating System".

Subpackages:
  engine       JAX/XLA TPU inference engine (model, KV cache, batching,
               sharding, sampling, GGUF loading) — replaces llama.cpp
  ops          Pallas TPU kernels for the hot paths
  runtime      aios.runtime.AIRuntime gRPC service over the engine
  memory       three-tier memory service (aios.memory.MemoryService)
  tools        capability-checked tool registry (aios.tools.ToolRegistry)
  gateway      cloud/local inference router (aios.api_gateway.ApiGateway)
  orchestrator goal engine, task planner, autonomy loop, scheduler, console
  agents       Python agent framework + the 10 system agents
  boot         topo-sorted service supervisor (initd equivalent)
  native       C++ components (ring buffer, token bucket, audit hash chain)
"""

__version__ = "0.1.0"
