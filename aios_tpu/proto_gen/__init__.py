"""Generated protobuf modules (see scripts/gen_protos.py)."""
from . import common_pb2
from . import runtime_pb2
from . import orchestrator_pb2
from . import agent_pb2
from . import tools_pb2
from . import api_gateway_pb2
from . import memory_pb2
from . import fleet_pb2

__all__ = [
    "common_pb2",
    "runtime_pb2",
    "orchestrator_pb2",
    "agent_pb2",
    "tools_pb2",
    "api_gateway_pb2",
    "memory_pb2",
    "fleet_pb2",
]
