#!/usr/bin/env bash
# ONE pre-merge gate chaining every cheap self-judging check the tree
# carries (docs/TESTING.md) — run it before pushing a serving-plane
# change and read the first failure:
#
#   1. scripts/analyze.sh        — static concurrency / dispatch /
#                                  knob-docs / metric-catalog analysis
#                                  (exit 1 on any unwaived finding);
#   2. the obs-lint subset       — metric naming, typed families, closed
#                                  enums (tests/test_obs_lint.py);
#   3. bench.py --chaos          — the seeded chaos storm, run twice,
#                                  deterministic or fail (scripts/chaos.sh
#                                  semantics, docs/FAULTS.md); arms cover
#                                  plain, draft-speculation, longctx
#                                  compression, and megagraph decode
#                                  (mega_ticks=8 + a seeded mid-window
#                                  pool.megatick_abort early exit);
#   4. the devprof sentinel      — bench.py --devprof captured fresh and
#                                  diffed against the committed
#                                  BASELINE_DEVPROF.json by
#                                  scripts/benchdiff.py: a per-graph
#                                  dispatch-count or device-time
#                                  regression past the budget fails the
#                                  gate (docs/OBSERVABILITY.md
#                                  "Device-time attribution");
#   5. the storm smoke           — bench.py --storm --smoke: the seeded
#                                  trace-driven tenant mix (streaming
#                                  chat + fork-shaped agent families +
#                                  a quota storm) drives the live gRPC
#                                  surface twice and the deterministic
#                                  verdict must be identical and PASS
#                                  (aios_tpu/loadgen/, docs/TESTING.md)
#                                  — every PR is gated under
#                                  contention-realistic load;
#   6. the fleet smoke           — scripts/fleet_smoke.py: two real
#                                  runtime processes on ephemeral ports
#                                  federate /metrics/fleet, stitch one
#                                  trace across the gRPC boundary, and
#                                  one is killed — the up -> suspect ->
#                                  dead journal must be identical across
#                                  two runs (aios_tpu/obs/fleet.py,
#                                  docs/RUNBOOK.md §9);
#   7. the disagg smoke          — scripts/disagg_smoke.py: one prefill
#                                  + two decode processes serve one
#                                  stream through the fleet data plane —
#                                  KV chain pushed over the wire, the
#                                  first decode host killed mid-stream
#                                  (exit 17), the survivor finishes the
#                                  stream token-identically to a solo
#                                  run, and the survivor gossips the
#                                  restored prefix digest; run twice,
#                                  verdicts identical (aios_tpu/fleet/,
#                                  docs/SERVING.md, docs/RUNBOOK.md §10);
#   8. the partition smoke        — scripts/partition_smoke.py: three
#                                  processes under seeded PER-EDGE
#                                  network faults — an asymmetric
#                                  partition walks one host to dead and
#                                  back while the reverse edge stays
#                                  clean, a handoff severs mid-stream
#                                  into quarantine + resume, federation
#                                  probes heal the breaker, and a
#                                  graceful drain re-hands a live stream
#                                  and exits 0 — token-identical to solo,
#                                  run twice, verdicts identical
#                                  (aios_tpu/faults/net.py,
#                                  aios_tpu/fleet/breaker.py,
#                                  aios_tpu/fleet/drain.py,
#                                  docs/FAULTS.md, docs/RUNBOOK.md §11);
#   9. the incident smoke         — scripts/incident_smoke.py: two
#                                  processes with the tsdb ring +
#                                  incident store armed, one seeded with
#                                  a fault storm — the fired crash must
#                                  freeze an incident bundle carrying
#                                  the fault journal AND a non-empty
#                                  tsdb window, /debug/tsdb/fleet must
#                                  federate both hosts, and fleetctl
#                                  history must exit 0; run twice,
#                                  verdicts identical
#                                  (aios_tpu/obs/tsdb.py,
#                                  aios_tpu/obs/incidents.py,
#                                  docs/OBSERVABILITY.md,
#                                  docs/RUNBOOK.md §12).
#
# The devprof threshold here is looser than benchdiff's default: the
# committed baseline was captured on a different run of a noisy shared-
# CPU container, so only gross per-graph timing regressions (and ANY
# deterministic dispatch-count inflation past the same budget) fail.
# Same-machine A/Bs should diff two fresh captures at the default 0.15.
#
# Usage:
#   scripts/preflight.sh                # full gate
#   PREFLIGHT_DEVPROF_THRESHOLD=0.25 scripts/preflight.sh
set -euo pipefail
cd "$(dirname "$0")/.."

threshold="${PREFLIGHT_DEVPROF_THRESHOLD:-0.75}"
workdir="$(mktemp -d)"
trap 'rm -rf "$workdir"' EXIT

echo "[preflight 1/9] static analysis (scripts/analyze.sh)" >&2
scripts/analyze.sh

echo "[preflight 2/9] obs-lint subset (tests/test_obs_lint.py)" >&2
python -m pytest tests/test_obs_lint.py -q -p no:cacheprovider

echo "[preflight 3/9] seeded chaos storm (bench.py --chaos; plain/draft/longctx/mega arms)" >&2
python bench.py --chaos > "$workdir/chaos.json"

echo "[preflight 4/9] devprof sentinel (bench.py --devprof vs" \
     "BASELINE_DEVPROF.json, threshold +${threshold})" >&2
python bench.py --devprof > "$workdir/devprof.json"
python scripts/benchdiff.py BASELINE_DEVPROF.json \
    "$workdir/devprof.json" --threshold "$threshold"

echo "[preflight 5/9] storm smoke (bench.py --storm --smoke," \
     "seeded, run twice, deterministic verdict)" >&2
python bench.py --storm --smoke > "$workdir/storm.json"

echo "[preflight 6/9] fleet smoke (scripts/fleet_smoke.py: two" \
     "processes federate + stitch, one dies, journals identical)" >&2
python scripts/fleet_smoke.py > "$workdir/fleet.json"

echo "[preflight 7/9] disagg smoke (scripts/disagg_smoke.py: prefill" \
     "+ 2 decode processes, kill + resume, token-identical twice)" >&2
python scripts/disagg_smoke.py > "$workdir/disagg.json"

echo "[preflight 8/9] partition smoke (scripts/partition_smoke.py:" \
     "per-edge faults, quarantine, graceful drain, identical twice)" >&2
python scripts/partition_smoke.py > "$workdir/partition.json"

echo "[preflight 9/9] incident smoke (scripts/incident_smoke.py: seeded" \
     "fault storm -> replayable incident bundles, identical twice)" >&2
python scripts/incident_smoke.py > "$workdir/incidents.json"

echo "[preflight] PASS" >&2
