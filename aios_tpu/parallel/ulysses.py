"""Ulysses (all-to-all) sequence parallelism over the `sp` mesh axis.

The second of the two standard long-context shardings (the task brief's
"ring attention OR all-to-all sequence/context parallelism"); ring
(`ring_attention.py`) rotates K/V around the chips and is
bandwidth-optimal at very long T, Ulysses re-shards once per attention:
two `all_to_all`s swap the sharded axis from sequence to heads, every
device then runs plain causal attention over the FULL sequence for its
head group, and a final all_to_all swaps back. Communication is
O(T·H·D / sp) per a2a regardless of ring hops, which wins when sp is
modest and heads are plentiful; the attention math itself stays the
single-device kind, so it inherits any local attention optimizations for
free.

Constraints: both `num_heads` and `num_kv_heads` must divide by the sp
axis (the head split must respect GQA group boundaries). The reference
has no counterpart (fixed 2048-8192 contexts, SURVEY.md section 5
"long-context: absent").
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from .ring_attention import fold_tile, visibility


def ulysses_attention(
    q: jnp.ndarray,  # [B, T, H, D]   T sharded over `axis`
    k: jnp.ndarray,  # [B, T, KH, D]
    v: jnp.ndarray,  # [B, T, KH, D]
    mesh: Mesh,
    axis: str = "sp",
    window: Optional[int] = None,
) -> jnp.ndarray:
    """Causal GQA attention, sequence-parallel via two all-to-alls.

    Returns [B, T, H, D] sharded like q. Inside the shard_map the local
    attention runs a blockwise online softmax over KV tiles so the
    [T, T] score matrix never materializes (same recurrence as ring /
    flash).
    """
    B, T, H, D = q.shape
    KH = k.shape[2]
    G = H // KH
    sp = mesh.shape[axis]
    if H % sp or KH % sp:
        raise ValueError(
            f"num_heads {H} and num_kv_heads {KH} must divide the sp axis "
            f"({sp}) — the all-to-all splits heads across it"
        )
    scale = 1.0 / np.sqrt(D)

    spec = P(None, axis, None, None)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_rep=False,
    )
    def _uly(q_blk, k_blk, v_blk):
        # [B, T/sp, H, D] -> [B, T, H/sp, D]: gather sequence, split heads
        qh = jax.lax.all_to_all(
            q_blk, axis, split_axis=2, concat_axis=1, tiled=True
        )
        kh = jax.lax.all_to_all(
            k_blk, axis, split_axis=2, concat_axis=1, tiled=True
        )
        vh = jax.lax.all_to_all(
            v_blk, axis, split_axis=2, concat_axis=1, tiled=True
        )
        Hl, KHl = H // sp, KH // sp
        qg = qh.reshape(B, T, KHl, G, D).astype(jnp.float32) * scale
        tile = T // sp  # reuse the natural shard size as the KV tile
        kb = kh.astype(jnp.float32).reshape(B, sp, tile, KHl, D)
        vb = vh.astype(jnp.float32).reshape(B, sp, tile, KHl, D)
        rows = jnp.arange(T)

        def fold(carry, xs):
            k_t, v_t, cols = xs  # [B, tile, KHl, D], [tile]
            s = jnp.einsum("btkgd,bskd->bkgts", qg, k_t)
            vis = visibility(rows, cols, window)
            return fold_tile(carry, s, vis, v_t), None

        cols = rows.reshape(sp, tile)
        init = (
            jnp.full((B, KHl, G, T), -1e30, jnp.float32),
            jnp.zeros((B, KHl, G, T), jnp.float32),
            jnp.zeros((B, KHl, G, T, D), jnp.float32),
        )
        (m, l, acc), _ = jax.lax.scan(
            fold, init, (kb.transpose(1, 0, 2, 3, 4),
                         vb.transpose(1, 0, 2, 3, 4), cols)
        )
        out = acc / jnp.maximum(l, 1e-30)[..., None]  # [B,KHl,G,T,D]
        out = out.transpose(0, 3, 1, 2, 4).reshape(B, T, Hl, D)
        # [B, T, H/sp, D] -> [B, T/sp, H, D]: split sequence, gather heads
        out = jax.lax.all_to_all(
            out, axis, split_axis=1, concat_axis=2, tiled=True
        )
        return out.astype(q_blk.dtype)

    return _uly(q, k, v)


def make_ulysses_attn_fn(mesh: Mesh, axis: str = "sp",
                         window: Optional[int] = None):
    """Adapter matching model.py's attention signature (the causal /
    sliding-window mask is applied internally from GLOBAL positions; the
    passed local mask is ignored — callers must forward the model's
    window, as make_train_step does)."""

    def attn(q, k, v, mask):  # noqa: ARG001
        return ulysses_attention(q, k, v, mesh, axis, window=window)

    return attn
