"""Transparent in-flight failover: a crashed replica must not cost the
client its stream.

Before this module, a replica scheduler crash aborted every outstanding
request (``batching._abort_all``) and the client got a truncated stream
plus an error status — correct, but the recovery the spawner-style
respawn makes possible was left to the client. Production engines treat
fault tolerance as a serving feature (RTP-LLM, PAPERS.md): here the pool
wraps every eligible request in a :class:`FailoverHandle` that, when the
stream dies with a RETRYABLE abort (``scheduler_failed`` always;
``evicted`` only when a sibling replica exists to re-route to):

  1. waits out a bounded exponential backoff with jitter;
  2. resubmits ``prompt + already-emitted tokens`` through the pool's
     router — the radix PrefixIndex and host KV tier make the re-prefill
     a cache hit (page-table update / memcpy), not a recompute;
  3. resumes the client stream at the exact next token (prefill of the
     grown prompt samples precisely the token the dead replica would
     have produced next — greedy streams are token-identical to a
     fault-free run).

One flight-recorder timeline spans every attempt: the batcher's
``_rec_close`` defers the terminal event to this controller for claimed
aborts (see :meth:`FailoverHandle.claims`), each resubmission lands a
``failover`` event, and TTFT/TPOT accumulate across attempts — failover
latency counts against the SLOs, by design. A retry budget that
exhausts surfaces as an aborted handle whose ``retry_after_ms`` the
runtime service returns as ``UNAVAILABLE`` + ``retry-after-ms`` trailing
metadata (the admission-shed convention) — never a silent truncation.

Grammar-constrained requests (``json_mode`` / ``json_schema``) are NOT
wrapped: their first post-prefill token is sampled unmasked and then
grammar-forced, which a mid-stream resume cannot reproduce without
masked prefill; they keep the pre-failover abort behavior (retryable
status + retry-after, so clients resubmit). docs/FAULTS.md documents
the limitation.
"""

from __future__ import annotations

import logging
import random
import time
from typing import List, Optional

from ..analysis.locks import make_lock
from ..engine.batching import Request, RequestHandle
from ..obs import flightrec
from ..obs import instruments as obs

log = logging.getLogger("aios.serving")

# ceiling on one backoff sleep: a deep retry chain must not park the
# client's stream for longer than its deadline could plausibly cover
MAX_BACKOFF_S = 5.0

FAILOVER_OUTCOMES = ("resumed", "exhausted")


def build_resume_request(pool, req: Request, emitted: List[int],
                         failover=None) -> Request:
    """The resume-from-emitted contract, shared by in-pool failover and
    the fleet handoff plane (fleet/disagg.py): rebuild ``req`` as
    ``prompt + already-emitted tokens`` with the remaining token budget.

    Resumes from the ADMISSION-TRUNCATED prompt, not the raw one: the
    engine kept only the last max_context-1 prompt ids, and appending
    emitted tokens to the RAW prompt would shift the truncation window
    by ``len(emitted)`` — a different conditioning context than the
    fault-free run's KV. From the truncated base, base + emitted <=
    max_context-1 always holds (a stream at the cap retires instead of
    aborting), so the resubmit is never re-truncated and greedy identity
    is preserved."""
    base, _ = pool._route_ids(req)
    return Request(
        prompt_ids=list(base) + list(emitted),
        max_tokens=max(req.max_tokens - len(emitted), 1),
        temperature=req.temperature,
        top_p=req.top_p,
        stop_ids=req.stop_ids,
        request_id=req.request_id,
        priority=req.priority,
        rec=req.rec,  # ONE timeline spans every attempt
        failover=failover,
    )


class FailoverHandle:
    """Caller-side view of a failover-protected request: iterates like
    :class:`~aios_tpu.engine.batching.RequestHandle`, transparently
    splicing resumed attempts into one token stream."""

    def __init__(self, pool, req: Request, tenant: str,
                 retries: int, backoff_ms: float) -> None:
        self._pool = pool
        self._req = req
        self._tenant = tenant
        self.retries = retries
        self.backoff_ms = backoff_ms
        #: guarded_by _lock
        self._inner: Optional[RequestHandle] = None  # set by the pool
        self._emitted: List[int] = []
        self._attempts = 0
        self._t0 = time.monotonic()
        self._ttft_at = 0.0
        self._lock = make_lock("failover")
        #: guarded_by _lock
        self._terminal_abort = ""
        #: guarded_by _lock
        self._cancelled = False
        # evicted re-routes only when a SIBLING can host the request —
        # retrying on the same starved replica would just evict another
        # victim (and possibly this request again, in a loop the budget
        # pays for without progress)
        self._retryable = ("scheduler_failed",) + (
            ("evicted",) if len(pool.replicas) > 1 else ()
        )

    # -- scheduler-side contract (called by batching._rec_close) ------------

    def claims(self, abort_reason: str) -> bool:
        """Whether this controller will own the aborted request's
        terminal event (the batcher then skips finishing the timeline).
        Conservative: claiming and then NOT retrying is handled (the
        controller finishes the timeline itself); finishing here and
        then retrying would freeze the record mid-recovery."""
        with self._lock:
            if self._cancelled:
                return False
        return (
            flightrec.abort_cause(abort_reason) in self._retryable
            and self._attempts < self.retries
            and not (self._pool._draining or self._pool._closed)
        )

    # -- RequestHandle surface ----------------------------------------------

    def __iter__(self):
        while True:
            with self._lock:
                inner = self._inner
            for tok in inner:
                if not self._ttft_at:
                    self._ttft_at = time.monotonic()
                self._emitted.append(tok)
                yield tok
            reason = inner._live.abort_reason
            if not reason:
                return  # retired / cancelled: a normal end of stream
            if not self._resume(reason):
                return  # terminal abort: self.aborted reflects it

    def tokens(self) -> List[int]:
        return list(self)

    def cancel(self) -> None:
        with self._lock:
            self._cancelled = True
            inner = self._inner
        if inner is None:
            return
        inner.cancel()
        # a crash and a client disconnect are correlated (the stalled
        # stream is WHY the client gave up): if the inner attempt is
        # already dead with an abort this controller claimed (the
        # batcher deferred the terminal event to us) and the consumer
        # will never drive _resume, the timeline must not be left
        # unfinished — no ring entry, no SLO sample, no snapshot
        live = inner._live
        if live.done and live.abort_reason and not self._terminal_abort:
            self._terminal(
                live.abort_reason, flightrec.abort_cause(live.abort_reason)
            )

    @property
    def aborted(self) -> bool:
        return bool(self._terminal_abort)

    @property
    def abort_reason(self) -> str:
        return self._terminal_abort

    @property
    def retry_after_ms(self) -> int:
        """Client backoff hint once the in-pool budget is spent: the
        next backoff step this controller WOULD have taken — the client
        inherits the retry chain where the pool left off."""
        if not self._terminal_abort:
            return 0
        cause = flightrec.abort_cause(self._terminal_abort)
        if cause not in flightrec.RETRYABLE_ABORT_CAUSES:
            return 0
        return int(min(
            self.backoff_ms * (2 ** self._attempts), MAX_BACKOFF_S * 1e3
        ))

    @property
    def ttft_ms(self) -> float:
        if not self._ttft_at:
            return 0.0
        return (self._ttft_at - self._t0) * 1000.0

    # -- the failover core ---------------------------------------------------

    def _resume(self, reason: str) -> bool:
        """Attempt one failover resubmission. Runs on the CONSUMER's
        thread (the stream is already stalled on the dead attempt, and
        the backoff sleep must not block any scheduler). Returns True
        when a new attempt is live; False finishes the timeline as
        aborted and surfaces the terminal state."""
        cause = flightrec.abort_cause(reason)
        with self._lock:
            cancelled = self._cancelled
        if (
            cancelled
            or cause not in self._retryable
            or self._attempts >= self.retries
            or self._pool._draining
            or self._pool._closed
        ):
            return self._terminal(reason, cause)
        self._attempts += 1
        # exponential backoff + jitter: a crash that killed N in-flight
        # requests wakes N consumers at once — the jitter de-synchronizes
        # their re-prefill storm on the surviving replicas
        delay_s = min(
            self.backoff_ms / 1e3 * (2 ** (self._attempts - 1)),
            MAX_BACKOFF_S,
        ) * (0.5 + random.random())
        time.sleep(delay_s)
        resumed = build_resume_request(
            self._pool, self._req, self._emitted, failover=self
        )
        try:
            handle = self._pool.submit_failover(
                resumed, cause=cause, attempt=self._attempts,
                backoff_ms=round(delay_s * 1e3, 1),
            )
        except Exception as exc:  # noqa: BLE001 - the pool may be mid-teardown
            log.warning(
                "%s: failover attempt %d for %s failed to resubmit (%s)",
                self._pool.name, self._attempts,
                self._req.request_id or "<anon>", exc,
            )
            return self._terminal(reason, cause)
        with self._lock:
            self._inner = handle
            cancelled = self._cancelled
        if cancelled:
            handle.cancel()
        obs.SERVING_FAILOVERS.labels(
            model=self._pool.name, outcome="resumed"
        ).inc()
        log.warning(
            "%s: request %s failed over (attempt %d/%d, cause %s, "
            "%d tokens already streamed)",
            self._pool.name, self._req.request_id or "<anon>",
            self._attempts, self.retries, cause, len(self._emitted),
        )
        return True

    def _terminal(self, reason: str, cause: str) -> bool:
        """No further attempt will run: finish the timeline this
        controller claimed and surface the abort. Idempotent — cancel()
        and a racing _resume may both arrive here for one request."""
        with self._lock:
            if self._terminal_abort:
                return False
            self._terminal_abort = reason
        # "exhausted" means the RETRY BUDGET was the blocker — a client
        # cancel mid-crash or a draining pool terminates retryable
        # causes too, and counting those would false-alarm the RUNBOOK's
        # "exhausted flat = no client saw the crash" drill verdict
        if cause in self._retryable and self._attempts >= self.retries:
            obs.SERVING_FAILOVERS.labels(
                model=self._pool.name, outcome="exhausted"
            ).inc()
        # finish() is itself idempotent for the case where the batcher
        # already closed the timeline (unclaimed causes, e.g.
        # prompt_too_large on a resumed attempt)
        flightrec.RECORDER.finish(
            self._req.rec, "aborted", abort_reason=reason
        )
        return False
