"""Draft-model speculative decoding (engine/spec.py DraftModel,
engine.spec_step_draft, the batcher's proposer ladder).

The acceptance rule is exact for greedy requests, so the key contract is
the same as n-gram speculation's: token IDENTITY with plain greedy
decoding — a draft model (however good or bad) may only change how many
dispatches a sequence takes, never the tokens. The identical-weights
draft exercises the accept path (acceptance ~1.0) and a mismatched
random draft exercises the reject/sync path (acceptance ~0.0); both must
stream the exact plain-greedy sequence.

Wall-clock discipline: the accept-path tests share ONE warmed
module-scoped engine (the fused draft graphs compile once for the whole
file); every test releases the slots it prefills.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aios_tpu.engine import model, spec
from aios_tpu.engine.batching import ContinuousBatcher, Request
from aios_tpu.engine.config import TINY_TEST
from aios_tpu.engine.engine import TPUEngine


@pytest.fixture(scope="module")
def params():
    return model.init_params(
        TINY_TEST, jax.random.PRNGKey(1), dtype=jnp.float32
    )


@pytest.fixture(scope="module")
def self_draft(params):
    # identical weights, unquantized: greedy argmax agreement ~1.0, the
    # deterministic accept-path fixture
    return spec.DraftModel(TINY_TEST, params, quantize=None)


@pytest.fixture(scope="module")
def mismatched_draft():
    # a different random model: proposals are mostly rejected, the
    # deterministic reject/sync-path fixture
    bad = model.init_params(TINY_TEST, jax.random.PRNGKey(9),
                            dtype=jnp.float32)
    return spec.DraftModel(TINY_TEST, bad, quantize=None)


def make_engine(params, **kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("max_context", 128)
    kw.setdefault("cache_dtype", jnp.float32)
    return TPUEngine(TINY_TEST, params, **kw)


PROMPT = [5, 9, 13, 27, 40]
DL = 3  # draft_len for every dispatch in this file (shared graph keys)


@pytest.fixture(scope="module")
def draft_engine(params, self_draft):
    """ONE warmed draft-paired engine shared by the accept-path tests.
    Warmup + every batcher below use the same sizes (steps/rounds 2 and
    4, draft_len DL), so the fused draft-spec, draft-ingest and n-gram
    twin graphs compile exactly once for the whole module."""
    eng = make_engine(params, draft=self_draft)
    eng.warmup(step_sizes=(2, 4), prefill_chunk=0, spec_sizes=(2, 4),
               spec_draft_len=DL)
    yield eng
    eng.close()


def _batcher(eng, speculative, **kw):
    return ContinuousBatcher(eng, chunk_steps=4, admit_chunk_steps=2,
                             speculative=speculative, spec_draft_len=DL,
                             **kw)


@pytest.fixture(scope="module")
def plain_ref(draft_engine):
    # plain greedy on the SAME engine/params — the identity baseline
    # (generate without speculative never touches the draft)
    return draft_engine.generate(PROMPT, max_new_tokens=41, chunk=4)


# ---------------------------------------------------------------------------
# engine-level identity (accept path, reject path, paged + bulk ingest)
# ---------------------------------------------------------------------------


def test_draft_generate_matches_plain_greedy(draft_engine, plain_ref):
    out = draft_engine.generate(PROMPT, max_new_tokens=41, chunk=4,
                                speculative="draft", draft_len=DL)
    assert out == plain_ref
    st = draft_engine.stats()
    # an identical draft accepts (nearly) everything: far fewer verify
    # rounds than tokens, and a measured acceptance
    assert st["spec_draft_rounds"] < len(plain_ref)
    assert st["draft_acceptance"] > 0.6


def test_mismatched_draft_still_token_identical(params, mismatched_draft,
                                                plain_ref):
    """The reject path IS the correctness path: a draft that agrees with
    the serving model on (almost) nothing must still stream the exact
    plain-greedy sequence — rejected rows fall beyond the clamped draft
    length and the serving verify emits its own argmax."""
    eng = make_engine(params, draft=mismatched_draft)
    try:
        out = eng.generate(PROMPT, max_new_tokens=41, chunk=4,
                           speculative="draft", draft_len=DL)
        assert out == plain_ref
        assert eng.stats()["draft_acceptance"] < 0.5
    finally:
        eng.close()


@pytest.mark.slow
def test_paged_engine_bulk_ingest_identity(params, self_draft):
    """Paged serving cache + a prompt longer than the fused rounds'
    catch-up width: the draft KV rebuilds through the bucketed ingest
    dispatches before the first propose, and the stream still matches
    plain decode on the same paged layout. Slow tier: the dense-cache
    tests above already exercise ingest tier-1 (PROMPT's gap exceeds
    the catch-up width), this adds the paged-layout twin."""
    long_prompt = [int(t) for t in
                   np.random.RandomState(3).randint(1, 250, size=70)]
    eng = make_engine(params, draft=self_draft,
                      paged_pool_rows=4 * 128, page_size=16)
    try:
        # plain-path reference on the SAME engine (generate without
        # speculative never touches the draft); the second run may HIT
        # the prefix cache the first registered — prefix-hit admission
        # identity is its own invariant (test_paged), and the draft's
        # history still backfills so the ingest path is exercised
        ref = eng.generate(long_prompt, max_new_tokens=24, chunk=4)
        out = eng.generate(long_prompt, max_new_tokens=24, chunk=4,
                           speculative="draft", draft_len=DL)
        assert out == ref
        assert eng.draft_ingest_dispatches >= 1
        assert int(eng._draft_host_lengths[0]) == 0  # released
    finally:
        eng.close()


def test_draft_sampling_slots_one_token_per_round(draft_engine):
    """temp > 0 slots never draft: proposed stays 0, each round emits
    exactly one (sampled) token — numerically a plain decode step — and
    the draft pays NOTHING for them: neither catch-up nor ingest builds
    draft KV the ok gate guarantees is never read."""
    eng = draft_engine
    eng.prefill(0, PROMPT, temperature=0.9, top_p=0.95)
    eng.prefill(1, PROMPT, temperature=0.0)  # greedy co-resident
    try:
        tokens, counts, proposed = eng.spec_step_draft(4, draft_len=DL)
        assert counts.shape == (4, 4)
        assert (counts[:, 0] == 1).all()
        assert (proposed[:, 0] == 0).all()
        # the sampled slot's draft KV was never built...
        assert int(np.asarray(eng.draft_state["lengths"])[0]) == 0
        # ...while the greedy co-resident's was (and proposed)
        assert int(np.asarray(eng.draft_state["lengths"])[1]) > 0
        assert proposed[:, 1].sum() > 0
    finally:
        eng.release(0)
        eng.release(1)
    # release() resets the draft mirror for the next occupant
    assert int(np.asarray(eng.draft_state["lengths"])[1]) == 0
    assert int(eng._draft_host_lengths[1]) == 0


def test_draft_vocab_mismatch_raises(params):
    bad_cfg = TINY_TEST.scaled(vocab_size=TINY_TEST.vocab_size * 2)
    bad = spec.DraftModel(
        bad_cfg,
        model.init_params(bad_cfg, jax.random.PRNGKey(2),
                          dtype=jnp.float32),
        quantize=None,
    )
    with pytest.raises(ValueError, match="vocab"):
        make_engine(params, draft=bad)


def test_draft_requires_history_falls_back(params, self_draft):
    """track_history=False cannot carry any speculative proposer; the
    draft detaches with a warning instead of corrupting state."""
    eng = make_engine(params, draft=self_draft, track_history=False)
    try:
        assert eng.draft is None
        with pytest.raises(ValueError, match="draft"):
            eng.spec_step_draft(1)
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# batcher: proposer ladder, auto-disable fallback, knobs
# ---------------------------------------------------------------------------


def test_batcher_draft_greedy_identical(draft_engine):
    """Draft speculation through the production batcher, multi-request,
    vs a plain batcher on the SAME engine: identical greedy streams."""
    prompts = [[3 + i, 7, 11] for i in range(3)]

    def wave(speculative):
        b = _batcher(draft_engine, speculative)
        try:
            handles = [
                b.submit(Request(prompt_ids=p, max_tokens=20,
                                 temperature=0.0))
                for p in prompts
            ]
            return [h.tokens() for h in handles], b.spec_proposers
        finally:
            b.shutdown()

    ref, _ = wave(False)
    rounds0 = draft_engine.spec_proposer_rounds["draft"]
    out, proposers = wave(True)
    assert out == ref
    assert proposers == ("draft", "ngram")
    assert draft_engine.spec_proposer_rounds["draft"] > rounds0


def test_ladder_falls_draft_to_ngram_to_off(draft_engine):
    """Per-proposer auto-disable: a collapsed draft EWMA suspends ONLY
    the draft rung (n-gram keeps serving); a collapsed n-gram EWMA then
    turns speculation off — and each proposer re-probes on its own
    window. Unit drive, no dispatches."""
    b = _batcher(draft_engine, True, spec_min_accept=0.5)
    try:
        assert b._spec_proposer() == "draft"
        counts = np.ones((2, 4), np.int64)
        proposed = np.full((2, 4), DL, np.int64)
        b._spec_measure("draft", counts, {0: 2, 1: 2}, proposed)
        assert b.spec_ewma["draft"] == 0.0
        assert b._spec_proposer() == "ngram", (
            "a collapsed draft must fall back to n-gram, not to off"
        )
        b._spec_measure("ngram", counts, {0: 2, 1: 2})
        assert b._spec_proposer() is None and not b._spec_active()
        # the draft's window expires first -> the draft rung returns
        b._spec_off_until["draft"] = time.monotonic() - 1
        assert b._spec_proposer() == "draft"
        # ... but with no greedy slot live the tick skips the draft rung
        assert b._spec_proposer(greedy_live=False) is None
    finally:
        b.shutdown()


def test_draft_acceptance_denominator_counts_only_proposals(draft_engine):
    """Sampled-heavy batches must not read as draft rejection: rounds
    where nothing was proposed contribute nothing to the denominator."""
    b = _batcher(draft_engine, True, spec_min_accept=0.5)
    try:
        counts = np.ones((2, 4), np.int64)
        proposed = np.zeros((2, 4), np.int64)  # nothing offered
        b._spec_measure("draft", counts, {0: 2, 1: 2}, proposed)
        assert b.spec_ewma["draft"] is None  # no measurement, no verdict
        assert b._spec_proposer() == "draft"
    finally:
        b.shutdown()


def test_reprobe_env_knob(draft_engine, monkeypatch):
    monkeypatch.setenv("AIOS_TPU_SPEC_REPROBE_SECS", "3.5")
    b = _batcher(draft_engine, True)
    try:
        assert b.spec_reprobe_secs == 3.5
    finally:
        b.shutdown()
    monkeypatch.setenv("AIOS_TPU_SPEC_REPROBE_SECS", "junk")
    b = _batcher(draft_engine, True)
    try:
        assert b.spec_reprobe_secs == 10.0  # lenient fallback
    finally:
        b.shutdown()


def test_no_compile_after_warmup_with_draft(draft_engine):
    """The PR 6 flat-compile-counters invariant extended to the draft
    graphs: warmup + batcher attach AOT-compiled the fused draft-spec
    and ingest graphs (module fixture), so serving a draft-speculated
    stream compiles NOTHING new. Runs LAST of the shared-engine tests —
    the snapshot covers whatever earlier tests built."""
    eng = draft_engine
    b = _batcher(eng, True)
    try:
        compiles = eng.stats()["xla_compiles"]
        rounds0 = eng.spec_proposer_rounds["draft"]
        out = b.submit(Request(prompt_ids=PROMPT, max_tokens=16,
                               temperature=0.0)).tokens()
        assert len(out) == 16
        assert eng.spec_proposer_rounds["draft"] > rounds0
        assert eng.stats()["xla_compiles"] == compiles, (
            "a draft-speculated stream compiled mid-serving"
        )
    finally:
        b.shutdown()


# ---------------------------------------------------------------------------
# live-gRPC e2e: draft ON vs OFF byte-identical through the full stack
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_e2e_grpc_draft_on_off_identical(monkeypatch):
    """ISSUE 11 acceptance: the full serving stack (RuntimeService ->
    pool -> batcher -> engine) with AIOS_TPU_DRAFT_MODEL paired streams
    byte-identical greedy completions to the same stack on the n-gram
    proposer, with compile counters flat through serving (the warmup
    gate covers the draft graphs).

    Why draft-vs-ngram and not draft-vs-plain here: a greedy slot's
    emitted chain is [g_0, g_1, ...] — the verify forward's own argmax
    at each accepted position — which is a pure function of the prefix
    and INDEPENDENT of what any proposer offered (acceptance admits a
    draft token iff it equals that argmax). So the two spec stacks must
    match to the byte in ANY dtype, while spec-vs-plain additionally
    requires verify_step/decode_step argmax agreement — exact in the
    fp32 unit tests above, but bf16 near-ties on synthetic random
    weights (this stack's serving dtype) can legally flip it."""
    from aios_tpu import rpc, services
    from aios_tpu.proto_gen import runtime_pb2
    from aios_tpu.runtime.model_manager import ModelManager
    from aios_tpu.runtime.service import serve

    monkeypatch.setenv("AIOS_TPU_SPECULATIVE", "1")

    def run_stack(draft: str):
        if draft:
            monkeypatch.setenv("AIOS_TPU_DRAFT_MODEL", draft)
        else:
            monkeypatch.delenv("AIOS_TPU_DRAFT_MODEL", raising=False)
        manager = ModelManager(num_slots=2, warm_compile=True)
        server, service, port = serve(
            address="127.0.0.1:0", manager=manager, block=False
        )
        try:
            channel = rpc.insecure_channel(f"127.0.0.1:{port}")
            stub = services.AIRuntimeStub(channel)
            status = stub.LoadModel(runtime_pb2.LoadModelRequest(
                model_name="tiny-draft-e2e",
                model_path="synthetic://tiny-test",
                context_length=128,
            ))
            assert status.status == "ready"
            managed = manager.get("tiny-draft-e2e")
            compiles = managed.engine.stats()["xla_compiles"]
            texts = []
            for prompt in ("hello there", "draft me"):
                # temperature 0 maps to the service's 0.7 default
                # (reference parity); a positive sub-GREEDY_EPS value
                # survives the mapping AND decodes greedy
                chunks = list(stub.StreamInfer(runtime_pb2.InferRequest(
                    prompt=prompt, max_tokens=12, temperature=1e-6,
                    model="tiny-draft-e2e",
                )))
                texts.append("".join(c.text for c in chunks))
            stats = managed.engine.stats()
            assert stats["xla_compiles"] == compiles, (
                "serving compiled new graphs past the readiness gate"
            )
            return texts, stats
        finally:
            server.stop(grace=None)

    on_texts, on_stats = run_stack("tiny-test")
    off_texts, off_stats = run_stack("")
    assert on_texts == off_texts, (
        "the draft proposer changed a greedy stream"
    )
    assert on_stats.get("spec_draft_rounds", 0) > 0, (
        "the draft proposer never actually served"
    )
    assert off_stats.get("spec_ngram_rounds", 0) > 0, (
        "the control stack never actually speculated"
    )
