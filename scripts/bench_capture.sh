#!/bin/bash
# Opportunistic bench capture (VERDICT r4 item 1b): run the bench suite NOW
# and persist the full transcript + JSON lines under docs/bench_runs/,
# labeled as a non-driver run so the artifact trail stays falsifiable even
# if the driver's end-of-round run hits a wedged TPU tunnel.
#
# Usage: scripts/bench_capture.sh [label] [extra bench.py args...]
#   AIOS_BENCH_PROBE_SECS caps the probe window (default 600 here — an
#   opportunistic run should fail fast; the driver's run uses the 2 h
#   default baked into bench.py).
set -u
cd "$(dirname "$0")/.."
LABEL="${1:-manual}"
shift 2>/dev/null || true
TS=$(date -u +%Y%m%dT%H%M%SZ)
OUT_DIR="docs/bench_runs"
mkdir -p "$OUT_DIR"
STEM="$OUT_DIR/${TS}_${LABEL}"
export AIOS_BENCH_PROBE_SECS="${AIOS_BENCH_PROBE_SECS:-600}"

{
  echo "# bench_capture: NON-DRIVER opportunistic run"
  echo "# label: $LABEL"
  echo "# utc: $TS"
  echo "# host: $(uname -a)"
  echo "# commit: $(git rev-parse HEAD 2>/dev/null || echo unknown)"
  echo "# dirty: $(git status --porcelain 2>/dev/null | wc -l) files"
  echo "# cmd: python bench.py $*"
} > "${STEM}.log"

python bench.py "$@" > "${STEM}.jsonl" 2>> "${STEM}.log"
RC=$?
echo "# exit: $RC" >> "${STEM}.log"
echo "captured: ${STEM}.jsonl (rc=$RC)"
exit $RC
