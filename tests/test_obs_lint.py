"""Metric-name lint: every instrument in the catalog follows the naming
convention, so future PRs adding instruments can't drift.

Rules (docs/OBSERVABILITY.md "naming"):
  * prefix ``aios_tpu_``, snake_case ``[a-z0-9_]`` only;
  * a unit suffix from the approved set — ``_seconds``, ``_bytes``,
    ``_total`` (primary trio), plus ``_ratio`` and ``_per_second`` for
    unitless/rate gauges, ``_pages`` for KV page-pool occupancy
    gauges (pages are the pool's native capacity unit — converting to
    bytes at scrape time would bake in dtype/geometry and break A/B
    comparisons across cache dtypes), and ``_info`` for identity
    gauges (the Prometheus *_info convention: constant value 1, the
    payload entirely in labels — a unit suffix would claim a
    measurement the series deliberately does not make);
  * label names snake_case, bounded per-metric label count;
  * non-empty help text.
"""

import re

import aios_tpu.obs.instruments  # noqa: F401 - registers the catalog
from aios_tpu.obs.metrics import REGISTRY

NAME_RE = re.compile(r"^aios_tpu_[a-z0-9_]+$")
LABEL_RE = re.compile(r"^[a-z][a-z0-9_]*$")
UNIT_SUFFIXES = ("_seconds", "_bytes", "_total", "_ratio", "_per_second",
                 "_pages", "_info")


def _catalog():
    metrics = [
        m for m in REGISTRY.collect() if m.name.startswith("aios_tpu_")
    ]
    assert metrics, "instrument catalog registered nothing"
    return metrics


def test_metric_names_are_prefixed_snake_case():
    for m in _catalog():
        assert NAME_RE.match(m.name), (
            f"{m.name}: must match aios_tpu_[a-z0-9_]+ (snake_case)"
        )


def test_metric_names_carry_a_unit_suffix():
    for m in _catalog():
        assert m.name.endswith(UNIT_SUFFIXES), (
            f"{m.name}: metric names end in a unit suffix "
            f"{UNIT_SUFFIXES} (add the unit, or extend the approved set "
            f"in docs/OBSERVABILITY.md AND here with a reviewed rationale)"
        )


def test_histograms_are_timed_in_seconds():
    for m in _catalog():
        if m.kind == "histogram":
            assert m.name.endswith("_seconds"), (
                f"{m.name}: histograms in this codebase measure durations; "
                f"use base-unit seconds"
            )


def test_counters_end_in_total():
    for m in _catalog():
        if m.kind == "counter":
            assert m.name.endswith("_total"), (
                f"{m.name}: counters use the _total suffix"
            )


def test_label_names_snake_case_and_bounded():
    for m in _catalog():
        assert len(m.labelnames) <= 4, (
            f"{m.name}: {len(m.labelnames)} labels — cardinality budget is "
            f"4; aggregate instead"
        )
        for ln in m.labelnames:
            assert LABEL_RE.match(ln), f"{m.name}: bad label name {ln!r}"
            assert ln not in ("le", "overflow"), (
                f"{m.name}: label {ln!r} collides with reserved names"
            )


def test_help_text_present():
    for m in _catalog():
        assert m.help.strip(), f"{m.name}: empty help text"


# -- the serving family (aios_tpu/serving/) --------------------------------

SERVING_EXPECTED = {
    "aios_tpu_serving_replicas_total": "gauge",
    "aios_tpu_serving_replica_occupancy_ratio": "gauge",
    "aios_tpu_serving_routing_decisions_total": "counter",
    "aios_tpu_serving_shed_total": "counter",
    "aios_tpu_serving_quota_rejections_total": "counter",
    "aios_tpu_serving_queue_wait_seconds": "histogram",
    "aios_tpu_serving_replica_restarts_total": "counter",
    "aios_tpu_serving_failover_total": "counter",
}


def test_serving_family_complete_and_typed():
    """The replica-pool instruments the ISSUE 2 catalog promises exist,
    with the promised kinds — and any NEW aios_tpu_serving_* metric must
    be added here (and to docs/SERVING.md) so the family stays reviewed."""
    serving = {
        m.name: m.kind for m in _catalog()
        if m.name.startswith("aios_tpu_serving_")
    }
    assert serving == SERVING_EXPECTED


# -- the long-context tier family (window+sink compression + sp prefill) --

KV_COMPRESS_EXPECTED = {
    "aios_tpu_kv_compress_slots_total": "gauge",
    "aios_tpu_kv_compress_pages_pruned_total": "gauge",
    "aios_tpu_kv_compress_resident_pages": "gauge",
}


def test_kv_compress_family_complete_and_typed():
    """The window+sink compression instruments the ISSUE 13 catalog
    promises exist, with the promised kinds — and any NEW
    aios_tpu_kv_compress_* metric must be added here (and to
    docs/ENGINE_PERF.md + OBSERVABILITY.md) so the family stays
    reviewed. slots/pages_pruned are monotonic engine counters summed
    over the per-model engine WeakSet; resident_pages reads live
    allocator state at scrape time."""
    family = {
        m.name: m.kind for m in _catalog()
        if m.name.startswith("aios_tpu_kv_compress_")
    }
    assert family == KV_COMPRESS_EXPECTED
    for m in _catalog():
        if m.name.startswith("aios_tpu_kv_compress_") or \
                m.name == "aios_tpu_prefill_seq_sharded_total":
            assert tuple(m.labelnames) == ("model",), (
                f"{m.name}: long-context metrics carry exactly the model "
                f"label (replicas aggregate through the engine WeakSet)"
            )


def test_seq_prefill_counter_registered_over_engine_weakset():
    """aios_tpu_prefill_seq_sharded_total and the compression counters
    must register through the WeakSet-summed callbacks in
    _register_gauges (set_function is last-writer-wins across replica
    engines — the aios_tpu_prefix_host_* lesson)."""
    from aios_tpu.analysis.core import module_info_for, names_used_in
    from aios_tpu.engine import engine as engine_mod

    assert any(
        m.name == "aios_tpu_prefill_seq_sharded_total" for m in _catalog()
    )
    mi = module_info_for(engine_mod)
    used = names_used_in(mi.functions["TPUEngine._register_gauges"].node)
    for name in ("KV_COMPRESS_SLOTS", "KV_COMPRESS_PAGES_PRUNED",
                 "KV_COMPRESS_RESIDENT", "PREFILL_SEQ_SHARDED"):
        assert name in used, f"{name} not registered over the WeakSet"


# -- the prefix-cache host tier family (engine/paged.py HostPageStore) -----

PREFIX_HOST_EXPECTED = {
    "aios_tpu_prefix_host_resident_bytes": "gauge",
    "aios_tpu_prefix_host_spills_total": "gauge",
    "aios_tpu_prefix_host_restores_total": "gauge",
    "aios_tpu_prefix_host_hits_total": "gauge",
    "aios_tpu_prefix_host_misses_total": "gauge",
    "aios_tpu_prefix_host_corrupt_total": "gauge",
    "aios_tpu_prefix_host_restore_seconds": "histogram",
}


def test_prefix_host_family_complete_and_typed():
    """The host spill tier instruments the ISSUE 4 catalog promises
    exist, with the promised kinds — and any NEW aios_tpu_prefix_host_*
    metric must be added here (and to docs/OBSERVABILITY.md) so the
    family stays reviewed."""
    family = {
        m.name: m.kind for m in _catalog()
        if m.name.startswith("aios_tpu_prefix_host_")
    }
    assert family == PREFIX_HOST_EXPECTED


def test_prefix_host_labels_are_model_only():
    """Host-tier series stay one-per-model: the store is per engine
    (replica stats sum through pool.stats()), so nothing here may grow a
    per-hash or per-replica label."""
    for m in _catalog():
        if m.name.startswith("aios_tpu_prefix_host_"):
            assert tuple(m.labelnames) == ("model",), (
                f"{m.name}: host-tier metrics carry exactly the model label"
            )


# -- the grammar jump-ahead family (engine.jump_step, ISSUE 7) -------------

ENGINE_JUMP_EXPECTED = {
    "aios_tpu_engine_jump_ahead_dispatches_total": "gauge",
    "aios_tpu_engine_jump_ahead_tokens_total": "gauge",
}


def test_engine_jump_ahead_family_complete_and_typed():
    """The jump-ahead instruments the ISSUE 7 catalog promises exist,
    with the promised kinds — and any NEW aios_tpu_engine_jump_ahead_*
    metric must be added here (and to docs/ENGINE_PERF.md +
    OBSERVABILITY.md) so the family stays reviewed. They are monotonic
    engine counters read at scrape time over a per-model WeakSet of
    replica engines (set_function is last-writer-wins — the
    aios_tpu_prefix_host_* lesson, not repeated a third time)."""
    family = {
        m.name: m.kind for m in _catalog()
        if m.name.startswith("aios_tpu_engine_jump_ahead_")
    }
    assert family == ENGINE_JUMP_EXPECTED
    for m in _catalog():
        if m.name.startswith("aios_tpu_engine_jump_ahead_"):
            assert tuple(m.labelnames) == ("model",), (
                f"{m.name}: jump-ahead metrics carry exactly the model "
                f"label (replicas aggregate through the engine WeakSet)"
            )


def test_engine_jump_ahead_gauges_aggregate_over_engine_weakset():
    """The scrape callbacks must SUM over _ENGINES_BY_MODEL — a bare
    weakref.ref(self) registration would report only the last replica.
    Checked on the AST (analysis.core walker), not a source grep."""
    from aios_tpu.analysis.core import module_info_for, names_used_in
    from aios_tpu.engine import engine as engine_mod

    mi = module_info_for(engine_mod)
    fn = mi.functions["TPUEngine._register_gauges"]
    used = names_used_in(fn.node)
    assert "_ENGINES_BY_MODEL" in used
    for name in ("ENGINE_JUMP_DISPATCHES", "ENGINE_JUMP_TOKENS",
                 "SPEC_ROUNDS", "SPEC_ACCEPTED"):
        assert name in used, f"{name} not registered over the WeakSet"


# -- the multi-tick megagraph family (engine.mega_step, ISSUE 19) ----------

ENGINE_MEGA_EXPECTED = {
    "aios_tpu_engine_mega_dispatches_total": "gauge",
    "aios_tpu_engine_mega_ticks_total": "gauge",
}


def test_engine_mega_family_complete_and_typed():
    """The megagraph instruments the ISSUE 19 catalog promises exist,
    with the promised kinds — and any NEW aios_tpu_engine_mega_* metric
    must be added here (and to docs/ENGINE_PERF.md + OBSERVABILITY.md)
    so the family stays reviewed. Like the jump family they are
    monotonic engine counters summed at scrape time over the per-model
    WeakSet of replica engines."""
    family = {
        m.name: m.kind for m in _catalog()
        if m.name.startswith("aios_tpu_engine_mega_")
    }
    assert family == ENGINE_MEGA_EXPECTED
    for m in _catalog():
        if m.name.startswith("aios_tpu_engine_mega_"):
            assert tuple(m.labelnames) == ("model",), (
                f"{m.name}: megagraph metrics carry exactly the model "
                f"label (replicas aggregate through the engine WeakSet)"
            )


def test_engine_mega_gauges_aggregate_over_engine_weakset():
    """Same WeakSet-sum contract as the jump family, on the AST."""
    from aios_tpu.analysis.core import module_info_for, names_used_in
    from aios_tpu.engine import engine as engine_mod

    mi = module_info_for(engine_mod)
    fn = mi.functions["TPUEngine._register_gauges"]
    used = names_used_in(fn.node)
    for name in ("ENGINE_MEGA_DISPATCHES", "ENGINE_MEGA_TICKS"):
        assert name in used, f"{name} not registered over the WeakSet"


# -- the speculative-decode family (engine.spec_step + batcher EWMA) -------

SPEC_EXPECTED = {
    "aios_tpu_spec_rounds_total": "gauge",
    "aios_tpu_spec_accepted_total": "gauge",
    "aios_tpu_spec_acceptance_ratio": "gauge",
}


def test_spec_family_complete_and_typed():
    """The speculative-decode instruments the ROADMAP item promises
    exist, with the promised kinds — rounds/accepted are WeakSet-summed
    engine counters; the acceptance ratio is the per-batcher EWMA that
    drives the AIOS_TPU_SPEC_MIN_ACCEPT auto-disable, averaged over
    replica batchers. Since the draft-model proposer landed, every
    series carries the (model, proposer) label pair."""
    family = {
        m.name: m.kind for m in _catalog()
        if m.name.startswith("aios_tpu_spec_")
    }
    assert family == SPEC_EXPECTED
    for m in _catalog():
        if m.name.startswith("aios_tpu_spec_"):
            assert tuple(m.labelnames) == ("model", "proposer"), (
                f"{m.name}: spec metrics carry exactly the "
                f"(model, proposer) label pair"
            )


def test_spec_proposers_are_a_closed_enum():
    """The ``proposer`` label values come from spec.SPEC_PROPOSERS and
    nowhere else — the engine and batcher gauge registrations iterate
    the tuple (the SLO OBJECTIVES pattern), so a new proposer is a
    reviewed enum change, not a stray string that grows the label set."""
    from aios_tpu.analysis.core import module_info_for, names_used_in
    from aios_tpu.engine import batching, engine, spec

    assert spec.SPEC_PROPOSERS == ("ngram", "draft")
    mi = module_info_for(engine)
    fn = mi.functions["TPUEngine._register_gauges"]
    assert "SPEC_PROPOSERS" in names_used_in(fn.node), (
        "engine spec gauges must be registered by iterating the "
        "SPEC_PROPOSERS enum"
    )
    bi = module_info_for(batching)
    init = bi.functions["ContinuousBatcher.__init__"]
    assert "SPEC_PROPOSERS" in names_used_in(init.node), (
        "batcher acceptance gauges must be registered by iterating the "
        "SPEC_PROPOSERS enum"
    )


# -- the decode dispatch family (pipelined batcher, engine/batching.py) ----

ENGINE_DISPATCH_EXPECTED = {
    "aios_tpu_engine_dispatch_host_gap_seconds": "histogram",
    "aios_tpu_engine_dispatch_inflight_total": "gauge",
    "aios_tpu_engine_dispatch_flushes_total": "counter",
}


def test_engine_dispatch_family_complete_and_typed():
    """The decode-dispatch instruments the ISSUE 6 catalog promises
    exist, with the promised kinds — and any NEW
    aios_tpu_engine_dispatch_* metric must be added here (and to
    docs/ENGINE_PERF.md + OBSERVABILITY.md) so the family stays
    reviewed. The kind map doubles as the unsuffixed-unit gate for this
    PR's additions: a dispatch metric not ending in an approved unit
    suffix fails test_metric_names_carry_a_unit_suffix AND this
    equality."""
    family = {
        m.name: m.kind for m in _catalog()
        if m.name.startswith("aios_tpu_engine_dispatch_")
    }
    assert family == ENGINE_DISPATCH_EXPECTED
    for name in family:
        assert name.endswith(UNIT_SUFFIXES), (
            f"{name}: dispatch metrics carry a unit suffix like every "
            f"other family"
        )


def test_engine_dispatch_flush_causes_bounded():
    """Flush causes are a fixed enum (see ContinuousBatcher
    _flush_pending call sites) — the label must never grow a per-request
    or per-slot dimension. Call sites are enumerated on the AST."""
    from aios_tpu.analysis.core import module_info_for, string_call_args
    from aios_tpu.engine import batching

    mi = module_info_for(batching)
    causes = {
        lit for lit, _ in string_call_args(mi.tree, ("_flush_pending",))
    }
    assert causes, "no _flush_pending call sites found"
    assert causes <= {"constrained", "spec", "evict", "idle"}


# -- the device-time attribution family (obs/devprof.py, ISSUE 14) ---------

DEVPROF_EXPECTED = {
    "aios_tpu_devprof_dispatches_total": "gauge",
    "aios_tpu_devprof_device_seconds_total": "gauge",
    "aios_tpu_devprof_mfu_ratio": "gauge",
    "aios_tpu_devprof_hbm_bandwidth_utilization_ratio": "gauge",
    "aios_tpu_devprof_tenant_device_seconds_total": "counter",
}


def test_devprof_family_complete_and_typed():
    """The device-time attribution instruments the ISSUE 14 catalog
    promises exist, with the promised kinds and unit suffixes — and any
    NEW aios_tpu_devprof_* metric must be added here (and to
    docs/OBSERVABILITY.md) so the family stays reviewed. Per-graph
    series carry exactly (model, graph) and are WeakSet-summed over
    replica ledgers; ONLY the tenant counter carries the tenant label,
    and it carries it ALONE (the quota-metric precedent — a tenant x
    model label product is unbounded; the per-model breakdown lives in
    /debug/devprof JSON)."""
    family = {
        m.name: m.kind for m in _catalog()
        if m.name.startswith("aios_tpu_devprof_")
    }
    assert family == DEVPROF_EXPECTED
    for m in _catalog():
        if m.name == "aios_tpu_devprof_tenant_device_seconds_total":
            assert tuple(m.labelnames) == ("tenant",)
        elif m.name.startswith("aios_tpu_devprof_"):
            assert tuple(m.labelnames) == ("model", "graph"), (
                f"{m.name}: devprof series carry exactly (model, graph)"
            )
        if m.name.startswith("aios_tpu_devprof_"):
            assert m.name.endswith(UNIT_SUFFIXES)


def test_devprof_graph_kinds_closed_enum():
    """The ``graph`` label values come from devprof.GRAPH_KINDS and
    nowhere else: the engine's gauge registration iterates the tuple
    (the SLO-objectives pattern) over the per-model ledger WeakSet, and
    every ledger call site — the ``_devprof_note(<kind>, ...)`` hooks on
    the dispatch paths — passes a literal member of the enum (checked on
    the AST, so a stray string cannot mint a new series)."""
    from aios_tpu.analysis.core import (
        iter_calls, module_info_for, names_used_in, string_call_args,
    )
    from aios_tpu.engine import engine as engine_mod
    from aios_tpu.obs import devprof

    mi = module_info_for(engine_mod)
    used = names_used_in(mi.functions["TPUEngine._register_gauges"].node)
    assert "GRAPH_KINDS" in used, (
        "devprof gauge children must be registered by iterating the "
        "GRAPH_KINDS enum"
    )
    assert "ledgers_for" in used, (
        "devprof gauges must aggregate over the per-model ledger WeakSet"
    )
    for name in ("DEVPROF_DISPATCHES", "DEVPROF_DEVICE_SECONDS",
                 "DEVPROF_MFU", "DEVPROF_HBM_UTIL"):
        assert name in used, f"{name} not registered over the WeakSet"
    kinds = {
        lit for lit, _ in string_call_args(mi.tree, ("_devprof_note",), 0)
    }
    assert kinds, "no _devprof_note call sites found in the engine"
    unknown = kinds - set(devprof.GRAPH_KINDS)
    assert not unknown, (
        f"ledger call sites use kinds {sorted(unknown)} not in the "
        f"closed GRAPH_KINDS enum — extend the enum (reviewed) instead "
        f"of inventing strings"
    )
    # the graph kinds the BATCHER attributes by (its _rec_dispatch
    # graph= argument and the spec/jump attribution) are members too
    from aios_tpu.engine import batching
    import ast as ast_mod

    bi = module_info_for(batching)
    batcher_kinds = set()
    for call in iter_calls(bi.tree):
        for kw in call.keywords:
            if kw.arg == "graph" and isinstance(kw.value, ast_mod.Constant):
                batcher_kinds.add(kw.value.value)
    batcher_kinds |= {
        lit for lit, _ in string_call_args(bi.tree, ("devprof_est_s",), 0)
    }
    assert batcher_kinds, "no batcher attribution call sites found"
    assert batcher_kinds <= set(devprof.GRAPH_KINDS)


# -- the SLO family (obs/slo.py, fed by the flight recorder, ISSUE 8) ------

SLO_EXPECTED = {
    "aios_tpu_slo_attainment_ratio": "gauge",
    "aios_tpu_slo_burn_rate_ratio": "gauge",
    "aios_tpu_slo_breaches_total": "counter",
}


def test_slo_family_complete_and_typed():
    """The SLO instruments the ISSUE 8 catalog promises exist, with the
    promised kinds — and any NEW aios_tpu_slo_* metric must be added
    here (and to docs/OBSERVABILITY.md) so the family stays reviewed.
    Labels are exactly (model, objective): the per-tenant breakdown
    stays in /debug/slo JSON because a tenant x model label product is
    unbounded (the test_serving_label_conventions rationale)."""
    family = {
        m.name: m.kind for m in _catalog()
        if m.name.startswith("aios_tpu_slo_")
    }
    assert family == SLO_EXPECTED
    for m in _catalog():
        if m.name.startswith("aios_tpu_slo_"):
            assert tuple(m.labelnames) == ("model", "objective"), (
                f"{m.name}: SLO metrics carry exactly (model, objective)"
            )


def test_slo_objectives_are_a_closed_enum():
    """The ``objective`` label values come from slo.OBJECTIVES and
    nowhere else — the gauge registrations iterate the tuple, so a new
    objective is a reviewed enum change, not a stray string."""
    from aios_tpu.analysis.core import module_info_for, names_used_in
    from aios_tpu.obs import slo

    assert slo.OBJECTIVES == ("ttft", "tpot", "availability")
    mi = module_info_for(slo)
    fn = mi.functions["SLOEngine._register_gauges"]
    assert "OBJECTIVES" in names_used_in(fn.node), (
        "SLO gauge children must be registered by iterating the "
        "OBJECTIVES enum"
    )


# -- flight-recorder closed enums (obs/flightrec.py, ISSUE 8) --------------
# The bounded-flush-cause pattern (ISSUE 6), extended: every event kind,
# shed cause, and abort cause the recorder can emit comes from ONE shared
# closed enum, so neither the recorder output nor any aios_tpu_slo_* /
# aios_tpu_serving_* label built on it can grow free-form label sets.


def _call_site_kinds(*modules):
    """Event kinds used at ``.event("<kind>", ...)`` /
    ``.model_event(<model>, "<kind>", ...)`` call sites in the given
    modules — AST call-argument extraction via the analysis walker, so
    wrapped lines and keyword noise can't hide a call site the way they
    could from the old regexes."""
    from aios_tpu.analysis.core import module_info_for, string_call_args

    kinds = set()
    for mod in modules:
        mi = module_info_for(mod)
        kinds |= {
            lit for lit, _ in string_call_args(mi.tree, ("event",), 0)
        }
        kinds |= {
            lit for lit, _ in string_call_args(mi.tree, ("model_event",), 1)
        }
    return kinds


def test_recorder_event_kinds_bounded():
    """Every event-kind string at every recorder call site — batcher,
    pool, engine, runtime service, the failover controller, the fault
    injector, and flightrec itself — is a member of the closed
    flightrec.EVENT_KINDS enum."""
    from aios_tpu.engine import batching, engine as engine_mod
    from aios_tpu.faults import inject as faults_inject
    from aios_tpu.faults import net as faults_net
    from aios_tpu.fleet import breaker as fleet_breaker
    from aios_tpu.fleet import disagg as fleet_disagg
    from aios_tpu.fleet import drain as fleet_drain
    from aios_tpu.fleet import kvx as fleet_kvx
    from aios_tpu.fleet import router as fleet_router
    from aios_tpu.obs import fleet, flightrec, incidents, tsdb
    from aios_tpu.runtime import service as runtime_service
    from aios_tpu.serving import autoscale, failover, pool

    kinds = _call_site_kinds(
        batching, engine_mod, pool, runtime_service, flightrec,
        failover, faults_inject, faults_net, autoscale, fleet,
        fleet_breaker, fleet_disagg, fleet_drain, fleet_kvx, fleet_router,
        incidents, tsdb,
    )
    assert kinds, "no recorder event call sites found"
    unknown = kinds - set(flightrec.EVENT_KINDS)
    assert not unknown, (
        f"event kinds {sorted(unknown)} not in the closed EVENT_KINDS "
        f"enum — extend the enum (reviewed) instead of inventing strings"
    )


def test_shed_causes_one_shared_enum():
    """Admission, the pool's shed tallies, and the recorder's shed
    events all draw from the SAME tuple object —
    obs.flightrec.SHED_CAUSES — so the aios_tpu_serving_shed_total label
    set and the timeline shed_cause field cannot drift apart."""
    from aios_tpu.analysis.core import (
        module_info_for, names_used_in, string_call_args,
    )
    from aios_tpu.obs import flightrec
    from aios_tpu.serving import admission, pool

    assert pool.SHED_CAUSES is flightrec.SHED_CAUSES
    assert admission.SHED_CAUSES is flightrec.SHED_CAUSES
    adm_mi = module_info_for(admission)
    init = adm_mi.functions["AdmissionController.__init__"]
    assert "SHED_CAUSES" in names_used_in(init.node), (
        "the shed-counter children must be built from the shared enum"
    )
    # every cause raised anywhere must be a member (`.shed("<cause>", ...)`
    # call sites in admission AND pool, via the shared AST walker)
    pool_mi = module_info_for(pool)
    causes = {
        lit
        for mi in (adm_mi, pool_mi)
        for lit, _ in string_call_args(mi.tree, ("shed",), 0)
    }
    assert causes, "no shed call sites found"
    assert causes <= set(flightrec.SHED_CAUSES)


def test_abort_reasons_normalize_onto_closed_enum():
    """Every abort_reason string the batcher can set maps to a
    NON-'other' member of flightrec.ABORT_CAUSES — a new abort path must
    extend the mapping (reviewed), or its timelines and SLO samples
    degrade to the catch-all bucket."""
    from aios_tpu.analysis.core import (
        assigned_string_literals, call_string_heads, module_info_for,
    )
    from aios_tpu.engine import batching
    from aios_tpu.obs import flightrec

    mi = module_info_for(batching)
    literals = {
        lit for lit, _ in assigned_string_literals(mi.tree, "abort_reason")
    }
    literals |= {
        lit for lit, _ in call_string_heads(mi.tree, "_terminate_outstanding")
    }
    assert literals, "no abort_reason literals found in the batcher"
    for reason in literals:
        cause = flightrec.abort_cause(reason)
        assert cause in flightrec.ABORT_CAUSES
        assert cause != "other", (
            f"abort_reason {reason!r} falls into the catch-all bucket; "
            f"extend flightrec.abort_cause/ABORT_CAUSES"
        )


def test_faults_family_complete_and_typed():
    """The fault-injection instrument the ISSUE 10 catalog promises:
    one counter, labeled (point, mode), both drawn from the closed
    faults.POINTS / faults.MODES enums — a fired fault must never mint
    a free-form label value."""
    from aios_tpu import faults

    family = {
        m.name: m.kind for m in _catalog()
        if m.name.startswith("aios_tpu_faults_")
    }
    assert family == {"aios_tpu_faults_injected_total": "counter"}
    for m in _catalog():
        if m.name.startswith("aios_tpu_faults_"):
            assert tuple(m.labelnames) == ("point", "mode")
    # the only strings handed to the point label come from the catalog:
    # FaultPlan.check validates the name against the parsed schedule,
    # whose keys _parse restricts to faults.POINTS
    from aios_tpu.analysis.core import module_info_for, names_used_in
    from aios_tpu.faults import inject

    mi = module_info_for(inject)
    assert "POINTS" in names_used_in(mi.functions["_parse"].node)
    assert set(faults.MODES) == {"nth", "prob", "after"}


AUTOSCALE_EXPECTED = {
    "aios_tpu_autoscale_actions_total": "counter",
}


def test_autoscale_family_complete_and_typed():
    """The SLO-autoscaler instrument the ISSUE 15 catalog promises, with
    labels exactly (model, action, cause) — any NEW aios_tpu_autoscale_*
    metric must be added here (and to docs/OBSERVABILITY.md) so the
    family stays reviewed."""
    family = {
        m.name: m.kind for m in _catalog()
        if m.name.startswith("aios_tpu_autoscale_")
    }
    assert family == AUTOSCALE_EXPECTED
    for m in _catalog():
        if m.name.startswith("aios_tpu_autoscale_"):
            assert tuple(m.labelnames) == ("model", "action", "cause")


def test_autoscale_enums_closed_and_iterated_at_registration():
    """``action`` and ``cause`` label values come from the closed
    autoscale.ACTIONS / CAUSES tuples and nowhere else: the controller
    pre-registers every (action, cause) child by iterating both enums
    (the SLO-objectives pattern), and every ``_record(action, cause)``
    call site's literals are members."""
    from aios_tpu.analysis.core import (
        call_string_heads, module_info_for, names_used_in,
    )
    from aios_tpu.serving import autoscale

    assert autoscale.ACTIONS == (
        "scale_up", "scale_down", "degrade", "restore",
    )
    assert autoscale.CAUSES == (
        "burn", "ceiling", "recovery", "kill_switch",
    )
    assert autoscale.LADDER == (
        "spec_off", "jump_off", "shed_best_effort",
    )
    mi = module_info_for(autoscale)
    init = mi.functions["AutoscaleController.__init__"]
    used = names_used_in(init.node)
    assert "ACTIONS" in used and "CAUSES" in used, (
        "autoscale metric children must be pre-registered by iterating "
        "the closed enums"
    )
    # every action literal handed to _record is an ACTIONS member (the
    # cause rides the second positional arg; heads() yields the first)
    heads = {lit for lit, _ in call_string_heads(mi.tree, "_record")}
    assert heads, "no _record call sites found"
    assert heads <= set(autoscale.ACTIONS)
    import ast as ast_mod

    from aios_tpu.analysis.core import iter_calls

    causes = set()
    for call in iter_calls(mi.tree):
        fn = call.func
        name = getattr(fn, "attr", getattr(fn, "id", ""))
        if name == "_record" and len(call.args) >= 2 and isinstance(
            call.args[1], ast_mod.Constant
        ):
            causes.add(call.args[1].value)
    assert causes and causes <= set(autoscale.CAUSES)


# -- the fleet telemetry family (obs/fleet.py, ISSUE 16) -------------------

# Every aios_tpu_fleet_* family, pinned name -> (kind, labelnames):
# the ISSUE 16 membership plane carries (host, role) — the per-process
# identity axes — while the ISSUE 17 data plane (kvx transfers, fleet
# routing) carries model plus ONE closed-enum dimension, the serving
# metric convention. Any NEW fleet metric must be added here (and to
# docs/OBSERVABILITY.md) so the family stays reviewed.
FLEET_EXPECTED = {
    "aios_tpu_fleet_member_up_total": ("gauge", ("host", "role")),
    "aios_tpu_fleet_member_transitions_total": (
        "counter", ("host", "role", "state")),
    "aios_tpu_fleet_scrape_failures_total": ("counter", ("host", "role")),
    "aios_tpu_fleet_kvx_pages_total": ("counter", ("model", "direction")),
    "aios_tpu_fleet_kvx_bytes_total": ("counter", ("model", "direction")),
    "aios_tpu_fleet_kvx_failures_total": ("counter", ("model", "cause")),
    "aios_tpu_fleet_route_total": ("counter", ("model", "reason")),
    # ISSUE 18 fault domains: the breaker gauge is an EDGE series —
    # host is the OBSERVING side, peer the judged side (value = index
    # into the closed BREAKER_STATES enum); the announce counter keys
    # by peer address alone (the asymmetric-partition signature)
    "aios_tpu_fleet_peer_breaker_state_total": ("gauge", ("host", "peer")),
    "aios_tpu_fleet_announce_failures_total": ("counter", ("peer",)),
}


def test_fleet_family_complete_and_typed():
    """The fleet-plane instruments the ISSUE 16/17 catalogs promise
    exist with the promised kinds AND exactly the pinned label sets —
    membership metrics on (host, role), data-plane metrics on (model,
    <closed enum>). An unreviewed aios_tpu_fleet_* metric fails here."""
    family = {
        m.name: (m.kind, tuple(m.labelnames)) for m in _catalog()
        if m.name.startswith("aios_tpu_fleet_")
    }
    assert family == FLEET_EXPECTED


def test_fleet_member_states_closed_and_iterated_at_registration():
    """The ``state`` label values come from the closed
    fleet.MEMBER_STATES tuple and nowhere else: the registry
    pre-registers every (host, role, state) child by iterating the enum
    (the autoscale/SLO registration pattern), so a new lifecycle state
    is a reviewed enum change, never a stray label value."""
    from aios_tpu.analysis.core import module_info_for, names_used_in
    from aios_tpu.obs import fleet

    assert fleet.MEMBER_STATES == ("up", "suspect", "dead")
    mi = module_info_for(fleet)
    fn = mi.functions["FleetRegistry._register_member_metrics"]
    assert "MEMBER_STATES" in names_used_in(fn.node), (
        "fleet transition children must be pre-registered by iterating "
        "the MEMBER_STATES enum"
    )
    # the failure detector compares states by enum POSITION (a detector
    # may only worsen a state) — it must read the same tuple
    tick = mi.functions["FleetRegistry.tick"]
    assert "MEMBER_STATES" in names_used_in(tick.node)


def test_fleet_kvx_and_route_enums_closed_and_iterated_at_registration():
    """The data-plane label values come from the closed enum tuples and
    nowhere else: ``direction``/``cause`` from kvx.KVX_DIRECTIONS /
    KVX_FAIL_CAUSES, ``reason`` from router.FLEET_ROUTE_REASONS — and
    each registration helper pre-registers every child by iterating its
    enum (the MEMBER_STATES/autoscale pattern), so a new transfer
    failure mode or routing outcome is a reviewed enum change, never a
    stray label value."""
    from aios_tpu.analysis.core import module_info_for, names_used_in
    from aios_tpu.fleet import kvx, router

    assert kvx.KVX_DIRECTIONS == ("push", "pull")
    assert kvx.KVX_FAIL_CAUSES == (
        "unavailable", "timeout", "crc_mismatch", "decode_error", "empty",
        "breaker_open",
    )
    assert router.FLEET_ROUTE_REASONS == (
        "local", "no_peer", "remote_pull", "handoff", "handoff_resume",
        "fallback_local",
    )
    kmi = module_info_for(kvx)
    used = names_used_in(kmi.functions["register_kvx_metrics"].node)
    assert "KVX_DIRECTIONS" in used and "KVX_FAIL_CAUSES" in used, (
        "kvx metric children must be pre-registered by iterating the "
        "closed enums"
    )
    rmi = module_info_for(router)
    assert "FLEET_ROUTE_REASONS" in names_used_in(
        rmi.functions["register_route_metrics"].node
    ), (
        "route metric children must be pre-registered by iterating "
        "FLEET_ROUTE_REASONS"
    )


def test_fault_domain_enums_closed_and_pinned():
    """The ISSUE 18 fault-domain vocabularies are closed enums, pinned
    here so growing any of them is a reviewed change: breaker states
    (the gauge VALUE is an index into the tuple — order is part of the
    contract), drain phases (descriptor ``phase`` values and the
    /fleet/drain response vocabulary), the per-edge net fault points
    (a subset of the faults.POINTS catalog), and the net surface /
    string-param scoping keys the injector recognizes."""
    from aios_tpu.analysis.core import module_info_for, names_used_in
    from aios_tpu import faults
    from aios_tpu.faults import inject, net
    from aios_tpu.fleet import breaker, drain

    assert breaker.BREAKER_STATES == ("closed", "open", "half_open")
    assert drain.DRAIN_PHASES == ("serving", "draining", "leaving")
    assert net.NET_POINTS == (
        "net.partition", "net.partition_oneway", "net.delay",
        "net.drop_after",
    )
    assert set(net.NET_POINTS) <= set(faults.POINTS), (
        "every net point must live in the faults.POINTS catalog so "
        "_parse accepts it and the injected-total label stays closed"
    )
    assert net.SURFACES == ("rpc", "http")
    assert inject._STR_PARAMS == ("src", "dst", "surface"), (
        "the per-edge scoping params are the ONLY string-valued fault "
        "params; anything else must stay a float"
    )
    # the gauge value and the emitted transition both come from the
    # SAME tuple: _emit indexes BREAKER_STATES (checked on the AST)
    bmi = module_info_for(breaker)
    assert "BREAKER_STATES" in names_used_in(
        bmi.functions["BreakerBoard._emit"].node
    ), "breaker gauge values must be indices into BREAKER_STATES"


def test_process_info_gauge_is_an_identity_series():
    """aios_tpu_process_info is the catalog's one *_info gauge: identity
    entirely in labels (host, rank, role, version), value pinned to 1 by
    fleet.stamp_process_info — the join key for every federated series
    and every bench.py JSON line."""
    family = [m for m in _catalog() if m.name == "aios_tpu_process_info"]
    assert len(family) == 1
    m = family[0]
    assert m.kind == "gauge"
    assert tuple(m.labelnames) == ("host", "rank", "role", "version")


def test_failover_outcomes_closed_enum():
    """The failover counter's outcome label values are members of the
    closed failover.FAILOVER_OUTCOMES tuple at every call site."""
    from aios_tpu.analysis.core import iter_calls, module_info_for
    import ast as ast_mod

    from aios_tpu.serving import failover

    mi = module_info_for(failover)
    outcomes = set()
    for call in iter_calls(mi.tree):
        for kw in call.keywords:
            if kw.arg == "outcome" and isinstance(
                kw.value, ast_mod.Constant
            ):
                outcomes.add(kw.value.value)
    assert outcomes, "no failover outcome call sites found"
    assert outcomes <= set(failover.FAILOVER_OUTCOMES)


# -- the tsdb + incident families (obs/tsdb.py, obs/incidents.py, ISSUE 20) -

# The black-box ring's self-accounting: sample passes and per-verb query
# counts are monotonic counters; the live/dropped series counts are
# gauges (they can fall on clear()). Any NEW aios_tpu_tsdb_* metric must
# be added here (and to docs/OBSERVABILITY.md) so the family stays
# reviewed.
TSDB_EXPECTED = {
    "aios_tpu_tsdb_sample_passes_total": ("counter", ()),
    "aios_tpu_tsdb_series_total": ("gauge", ()),
    "aios_tpu_tsdb_dropped_series_total": ("gauge", ()),
    "aios_tpu_tsdb_queries_total": ("counter", ("verb",)),
}

INCIDENTS_EXPECTED = {
    "aios_tpu_incidents_total": ("counter", ("cause",)),
    "aios_tpu_incidents_suppressed_total": ("counter", ("cause",)),
}


def test_tsdb_family_complete_and_typed():
    family = {
        m.name: (m.kind, tuple(m.labelnames)) for m in _catalog()
        if m.name.startswith("aios_tpu_tsdb_")
    }
    assert family == TSDB_EXPECTED


def test_incidents_family_complete_and_typed():
    family = {
        m.name: (m.kind, tuple(m.labelnames)) for m in _catalog()
        if m.name.startswith("aios_tpu_incidents_")
    }
    assert family == INCIDENTS_EXPECTED


def test_tsdb_query_verbs_closed_and_iterated_at_registration():
    """The ``verb`` label values come from the closed tsdb.QUERY_VERBS
    tuple and nowhere else: the ring pre-registers every verb child by
    iterating the enum (the autoscale/SLO registration pattern), and
    query() validates against the same tuple — so a new query verb is a
    reviewed enum change, never a stray label value."""
    from aios_tpu.analysis.core import module_info_for, names_used_in
    from aios_tpu.obs import tsdb

    assert tsdb.QUERY_VERBS == (
        "raw", "rate", "avg", "min", "max", "p50", "p90", "p95", "p99",
    )
    assert tsdb.SERIES_KINDS == ("delta", "gauge")
    mi = module_info_for(tsdb)
    assert "QUERY_VERBS" in names_used_in(
        mi.functions["Tsdb._register_metrics"].node
    ), "tsdb query children must be pre-registered by iterating QUERY_VERBS"
    assert "QUERY_VERBS" in names_used_in(mi.functions["Tsdb.query"].node), (
        "query() must validate verbs against the same closed enum"
    )


def test_incident_trigger_causes_closed_and_iterated_at_registration():
    """The ``cause`` label values come from the closed
    incidents.TRIGGER_CAUSES tuple and nowhere else: the store
    pre-registers every cause child by iterating the enum, notify()
    normalizes unknown strings onto it, every literal a trigger hook
    hands to notify() is a member (checked on the AST across the three
    non-flightrec hooks), and the flightrec snapshot causes — which ride
    through notify() verbatim — are a subset."""
    from aios_tpu.analysis.core import (
        module_info_for, names_used_in, string_call_args,
    )
    from aios_tpu.faults import inject as faults_inject
    from aios_tpu.fleet import breaker as fleet_breaker
    from aios_tpu.obs import flightrec, incidents
    from aios_tpu.serving import autoscale

    assert incidents.TRIGGER_CAUSES == (
        "abort", "autoscale", "breaker_open", "crash_respawn", "fault",
        "manual", "shed_spike", "slo_breach",
    )
    mi = module_info_for(incidents)
    assert "TRIGGER_CAUSES" in names_used_in(
        mi.functions["IncidentStore._register_metrics"].node
    ), "incident children must be pre-registered by iterating the enum"
    assert "TRIGGER_CAUSES" in names_used_in(
        mi.functions["IncidentStore.notify"].node
    ), "notify() must normalize causes against the same closed enum"
    causes = set()
    for mod in (autoscale, fleet_breaker, faults_inject):
        hmi = module_info_for(mod)
        causes |= {
            lit for lit, _ in string_call_args(hmi.tree, ("notify",), 1)
        }
    assert causes == {"autoscale", "breaker_open", "fault"}, (
        f"trigger hooks emit causes {sorted(causes)} — each hook owns "
        f"exactly one TRIGGER_CAUSES member"
    )
    assert set(flightrec.SNAPSHOT_CAUSES) <= set(incidents.TRIGGER_CAUSES), (
        "snapshot causes ride through notify() verbatim, so every one "
        "must be a TRIGGER_CAUSES member"
    )


def test_debug_route_index_complete():
    """Every route the HTTP handler dispatches on (the ``path == "/..."``
    comparisons, collected on the AST) appears in the ROUTES index that
    GET /debug renders, and vice versa — a new endpoint that skips the
    index fails here."""
    import ast as ast_mod

    from aios_tpu.analysis.core import module_info_for
    from aios_tpu.obs import http as http_mod

    mi = module_info_for(http_mod)
    dispatched = set()
    for node in ast_mod.walk(mi.tree):
        if not isinstance(node, ast_mod.Compare):
            continue
        for cand in [node.left, *node.comparators]:
            if isinstance(cand, ast_mod.Constant) and isinstance(
                cand.value, str
            ) and cand.value.startswith("/"):
                dispatched.add(cand.value)
    indexed = {route for _, route, _ in http_mod.ROUTES}
    assert dispatched == indexed, (
        f"route index out of sync: dispatched-but-unindexed "
        f"{sorted(dispatched - indexed)}, indexed-but-undispatched "
        f"{sorted(indexed - dispatched)}"
    )


def test_serving_label_conventions():
    """Serving labels stay low-cardinality by construction: routing
    reasons and shed causes are fixed enums (see serving/pool.py); only
    the quota metric carries the tenant label, and nothing carries both
    tenant and model (series count = tenants x models would blow the
    child cap under many co-resident models)."""
    for m in _catalog():
        if not m.name.startswith("aios_tpu_serving_"):
            continue
        assert not ("tenant" in m.labelnames and "model" in m.labelnames), (
            f"{m.name}: tenant x model label product is unbounded"
        )
