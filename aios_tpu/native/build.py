"""Build the native shared library with g++ (no cmake needed for one TU)."""

from __future__ import annotations

import subprocess
from pathlib import Path

HERE = Path(__file__).parent
SRC = HERE / "src" / "aios_native.cpp"
OUT = HERE / "libaios_native.so"


def build(force: bool = False) -> Path:
    if OUT.exists() and not force:
        if OUT.stat().st_mtime >= SRC.stat().st_mtime:
            return OUT
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC",
        "-o", str(OUT), str(SRC),
    ]
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    return OUT


if __name__ == "__main__":
    print(build(force=True))
