#!/usr/bin/env python
"""Headline benchmark: TPU decode throughput for the runtime's model tiers.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "tokens/sec/chip", "vs_baseline": N}

Baseline: the reference runs llama.cpp on CPU at 5-15 tokens/sec for <=7B Q4
models (docs/HARDWARE.md:148, BASELINE.md); vs_baseline divides by the top of
that range (15 tok/s), i.e. the most favorable reading for the reference.

Method: TinyLlama-1.1B architecture (synthetic weights — throughput is
weight-value-independent), int8 serving weights (the production default;
the reference serves Q4 GGUF, so int8 is more precise than its default),
8 concurrent slots (the reference's 8-agent mixed load), 64-token prompts,
then steady-state batched decode measured over multi-step scan dispatches so
host/relay latency is amortized exactly as the production continuous-batching
path does.
"""

from __future__ import annotations

import json
import sys
import time


def log(*args):
    print(*args, file=sys.stderr, flush=True)


def main() -> int:
    import jax
    import jax.numpy as jnp

    from aios_tpu.engine import model as model_mod
    from aios_tpu.engine.config import TINYLLAMA_1_1B
    from aios_tpu.engine.engine import TPUEngine

    backend = jax.default_backend()
    log(f"backend={backend} devices={jax.devices()}")

    cfg = TINYLLAMA_1_1B
    num_slots = 8
    prompt_len = 64
    chunk = 32
    measure_chunks = 6

    t0 = time.time()
    params = model_mod.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
    engine = TPUEngine(
        cfg, params, num_slots=num_slots, max_context=1024, quantize=True
    )
    log(f"params+engine in {time.time() - t0:.1f}s")

    # prefill all slots (compiles the 64-bucket prefill once)
    t0 = time.time()
    prompt = list(range(1, prompt_len + 1))
    ttfts = []
    for s in range(num_slots):
        t1 = time.time()
        engine.prefill(s, prompt, temperature=0.7, top_p=0.95)
        ttfts.append(time.time() - t1)
    log(f"prefill x{num_slots} in {time.time() - t0:.1f}s (first incl. compile)")

    # compile + warm the decode chunk
    t0 = time.time()
    engine.step(chunk)
    log(f"decode chunk compile+run in {time.time() - t0:.1f}s")
    engine.step(chunk)  # warm

    # measured region
    t0 = time.time()
    for _ in range(measure_chunks):
        engine.step(chunk)
    dt = time.time() - t0
    total_tokens = num_slots * chunk * measure_chunks
    tps = total_tokens / dt

    p50_ttft_ms = sorted(ttfts)[len(ttfts) // 2] * 1000.0

    log(
        f"decode: {total_tokens} tokens in {dt:.2f}s -> {tps:.1f} tok/s/chip "
        f"(batch {num_slots}); p50 warm TTFT {p50_ttft_ms:.0f} ms"
    )

    baseline_cpu_tps = 15.0  # top of the reference's published range
    print(
        json.dumps(
            {
                "metric": "tinyllama-1.1b batched decode throughput (8 slots, int8 serving)",
                "value": round(tps, 1),
                "unit": "tokens/sec/chip",
                "vs_baseline": round(tps / baseline_cpu_tps, 1),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
