"""JSON-Schema-guided decoding (engine/jsonschema.py): structured outputs.

The reference's autonomy loop re-prompts through JSON-repair rounds when
tool_calls don't parse (autonomy.rs:290-328); schema-guided masks make the
first round parse by construction. These tests cover the compiled automaton
(accept/reject), the budget-feasibility gate (outputs ALWAYS complete when
the budget can fit them), and the gRPC surface (wire-compatible
InferRequest.json_schema extension field).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aios_tpu.engine import jsonmode, jsonschema
from aios_tpu.engine import model as M
from aios_tpu.engine.batching import ContinuousBatcher, Request
from aios_tpu.engine.config import TINY_TEST
from aios_tpu.engine.engine import TPUEngine
from aios_tpu.engine.jsonmode import JsonConstraint
from aios_tpu.engine.tokenizer import ByteTokenizer

# compile-heavy tier: excluded from the fast commit gate (pytest -m fast)
pytestmark = pytest.mark.slow

TOOL_SCHEMA = {
    "type": "object",
    "properties": {
        "tool_calls": {
            "type": "array",
            "items": {
                "type": "object",
                "properties": {
                    "tool": {
                        "type": "string",
                        "enum": ["fs.read", "fs.write", "net.ping"],
                    },
                    "args": {},
                },
                "required": ["tool"],
            },
        },
        "done": {"type": "boolean"},
        "count": {"type": "integer"},
    },
    "required": ["done"],
}


def _machine(schema):
    table, root = jsonschema.compile_schema(schema)
    return jsonschema.SchemaMachine(table, root)


def _run(m, text):
    st = m.start()
    for b in text.encode():
        st = m.step(st, b)
        if st is None:
            return None
    return st


ACCEPT = [
    '{"done": true}',
    '{"tool_calls": [], "done": false}',
    '{"tool_calls": [{"tool": "fs.read"}], "done": true}',
    '{"tool_calls": [{"tool": "net.ping", "args": {"host": "8.8.8.8", '
    '"n": [1, 2.5]}}], "done": true}',
    '{"count": -42, "done": false}',
    '{ "done"\t:\ntrue }',
]

REJECT = [
    "{}",  # missing required
    '{"done": 1}',  # wrong type
    '{"done": true, "done": false}',  # duplicate key
    '{"unknown": 1, "done": true}',  # unknown key
    '{"tool_calls": [{"tool": "bad"}], "done": true}',  # enum violation
    '{"tool_calls": [{"args": {}}], "done": true}',  # missing inner required
    '{"count": 1.5, "done": true}',  # integer violated
    '{"count": 01, "done": true}',  # leading zero
    '[{"done": true}]',  # root must be the object
]


@pytest.mark.parametrize("text", ACCEPT)
def test_schema_accepts(text):
    m = _machine(TOOL_SCHEMA)
    st = _run(m, text)
    assert st is not None and m.terminal(st), text


@pytest.mark.parametrize("text", REJECT)
def test_schema_rejects(text):
    m = _machine(TOOL_SCHEMA)
    st = _run(m, text)
    assert st is None or not m.terminal(st), text


def test_compile_rejects_unsupported():
    for bad in (
        {"type": "object", "properties": {"a": {"type": "string"}},
         "required": ["b"]},
        {"type": "string", "enum": []},
        {"type": "array", "minItems": 3},
        {"type": "frobnicate"},
    ):
        with pytest.raises(ValueError):
            jsonschema.compile_schema(bad)


def test_open_object_is_still_an_object():
    """{"type": "object"} with no properties means free-form KEYS, not
    free-form VALUE: a number/string/array must not satisfy it."""
    m = _machine({"type": "object", "properties": {
        "args": {"type": "object"}}, "required": ["args"]})
    for bad in ('{"args": 42}', '{"args": "s"}', '{"args": [1]}'):
        st = _run(m, bad)
        assert st is None or not m.terminal(st), bad
    ok = _run(m, '{"args": {"x": [1, {"y": null}]}}')
    assert ok is not None and m.terminal(ok)


def test_compile_malformed_inputs_raise_value_error():
    """Client-supplied schemas must fail as ValueError (the service maps
    it to INVALID_ARGUMENT), never TypeError/AttributeError."""
    for bad in (
        {"type": "string", "enum": ["a", 1]},
        {"type": "object", "properties": {"a": {}}, "required": 5},
        {"type": "object", "properties": 3},
        {"const": 5},
        {"type": "string", "enum": 7},
    ):
        with pytest.raises(ValueError):
            jsonschema.compile_schema(bad)


def test_enum_values_needing_escapes_rejected():
    for v in ('say "hi"', "a\\b", "nl\n"):
        with pytest.raises(ValueError, match="escape"):
            jsonschema.compile_schema({"type": "string", "enum": [v]})


def test_escape_feasibility_generic(cpu_devices):
    """Adversarial walk through the GENERIC grammar with tight budgets:
    \\uXXXX escapes must never strand the output (the distance for X/U
    states counts the full escape; regression for the budget gate)."""
    from aios_tpu.engine.jsonmode import JsonConstraint, JsonMaskCache
    from aios_tpu.engine.tokenizer import ByteTokenizer

    tok = ByteTokenizer()
    table = jsonmode.token_bytes_table(tok, tok.vocab_size)
    cache = JsonMaskCache(table, tok.eos_id)
    for mt in (6, 10, 14, 20):
        c = JsonConstraint(cache)
        out = []
        for _ in range(mt):
            row = c.mask_row(remaining=mt - len(out))
            cand = [
                a for a in np.flatnonzero(row == 0.0) if a != tok.eos_id
            ]
            if not cand:
                break
            fd = cache.dist_row(c.state)
            pick = max(cand, key=lambda a: int(fd[a]))
            out.append(pick)
            c.advance(pick)
        assert c.satisfied, (mt, bytes(
            b for t in out for b in table[t]
        ))
        json.loads(bytes(b for t in out for b in table[t]).decode())


def test_min_items_one():
    m = _machine({"type": "object", "properties": {
        "xs": {"type": "array", "items": {"type": "integer"},
               "minItems": 1}}, "required": ["xs"]})
    assert _run(m, '{"xs": [1]}') is not None
    st = _run(m, '{"xs": []}')
    assert st is None or not m.terminal(st)


def test_const_string():
    m = _machine({"type": "object", "properties": {
        "v": {"const": "fixed"}}, "required": ["v"]})
    ok = _run(m, '{"v": "fixed"}')
    assert ok is not None and m.terminal(ok)
    assert _run(m, '{"v": "other"}') is None


# ---------------------------------------------------------------------------
# budget feasibility: constrained walks ALWAYS complete when they can
# ---------------------------------------------------------------------------


def _cache(schema):
    tok = ByteTokenizer()
    table = jsonmode.token_bytes_table(tok, tok.vocab_size)
    return jsonschema.SchemaMaskCache(table, tok.eos_id, schema), tok, table


@pytest.mark.parametrize("mode", ["worst", "rand", "best"])
@pytest.mark.parametrize("max_tokens", [16, 20, 32, 64])
def test_adversarial_walks_always_complete(mode, max_tokens):
    """Feasibility-gated masks guarantee completion by induction — even an
    adversary that always picks the allowed token FARTHEST from terminal
    must produce a conforming object within the budget."""
    schema = {
        "type": "object",
        "properties": {"status": {"type": "string", "enum": ["ok", "error"]},
                       "value": {"type": "integer"}},
        "required": ["status"],
    }
    cache, tok, table = _cache(schema)
    rng = np.random.default_rng(max_tokens)
    c = JsonConstraint(cache)
    emitted = []
    for _ in range(max_tokens):
        remaining = max_tokens - len(emitted)
        row = c.mask_row(remaining=remaining)
        cand = [a for a in np.flatnonzero(row == 0.0) if a != tok.eos_id]
        if not cand:
            break
        fd = cache.dist_row(c.state)
        if mode == "worst":
            pick = max(cand, key=lambda a: int(fd[a]))
        elif mode == "rand":
            pick = int(rng.choice(cand))
        else:
            pick = min(cand, key=lambda a: int(fd[a]))
        emitted.append(pick)
        c.advance(pick)
    assert c.satisfied
    obj = json.loads(bytes(b for t in emitted for b in table[t]).decode())
    assert obj["status"] in ("ok", "error")


def test_distance_monotone_along_closing():
    cache, tok, table = _cache(TOOL_SCHEMA)
    st = cache.start()
    d = cache._distance(st)
    seen = 0
    while not cache._terminal(st):
        fd = cache.dist_row(st)
        best = int(np.argmin(fd))
        st2 = cache.run(st, table[best])
        assert st2 is not None
        d2 = cache._distance(st2)
        assert d2 < d, (st, d, st2, d2)  # every closing byte strictly helps
        st, d = st2, d2
        seen += 1
        assert seen < 64


# ---------------------------------------------------------------------------
# generation + service surface
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def serving():
    cfg = TINY_TEST
    params = M.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    eng = TPUEngine(cfg, params, num_slots=2, max_context=256,
                    cache_dtype=jnp.float32, seed=7)
    tok = ByteTokenizer()
    batcher = ContinuousBatcher(eng, tokenizer=tok)
    yield eng, tok, batcher
    batcher.shutdown()
    eng.close()


def test_generations_conform(serving):
    _, tok, batcher = serving
    for i in range(8):
        mt = (16, 24, 48, 96)[i % 4]
        h = batcher.submit(Request(
            prompt_ids=tok.encode(f"q{i}"), max_tokens=mt, temperature=1.0,
            top_p=0.95, stop_ids=(tok.eos_id,), json_schema=TOOL_SCHEMA,
        ))
        obj = json.loads(tok.decode(h.tokens()))
        assert isinstance(obj["done"], bool)
        assert set(obj) <= {"tool_calls", "done", "count"}
        for call in obj.get("tool_calls", []):
            assert call["tool"] in ("fs.read", "fs.write", "net.ping")


def test_infeasible_budget_fails_fast(serving):
    _, tok, batcher = serving
    with pytest.raises(ValueError, match="minimal completion"):
        batcher.submit(Request(
            prompt_ids=tok.encode("x"), max_tokens=4,
            json_schema=TOOL_SCHEMA,
        ))


def test_schema_over_grpc():
    import grpc

    from aios_tpu import rpc, services
    from aios_tpu.proto_gen import runtime_pb2
    from aios_tpu.runtime.model_manager import ModelManager
    from aios_tpu.runtime.service import serve

    schema = json.dumps({
        "type": "object",
        "properties": {"status": {"type": "string", "enum": ["ok", "error"]}},
        "required": ["status"],
    })
    manager = ModelManager(num_slots=2, warm_compile=False)
    server, _s, port = serve(
        address="127.0.0.1:0", manager=manager, block=False
    )
    try:
        stub = services.AIRuntimeStub(
            rpc.insecure_channel(f"127.0.0.1:{port}")
        )
        r = stub.LoadModel(runtime_pb2.LoadModelRequest(
            model_name="tiny", model_path="synthetic://tiny-test",
            context_length=256,
        ))
        assert r.status == "ready"
        resp = stub.Infer(runtime_pb2.InferRequest(
            model="tiny", prompt="status?", max_tokens=32, temperature=1.0,
            json_schema=schema,
        ))
        assert json.loads(resp.text)["status"] in ("ok", "error")
        for bad in ("{not json", '{"type": "string"}'):
            with pytest.raises(grpc.RpcError) as e:
                stub.Infer(runtime_pb2.InferRequest(
                    model="tiny", prompt="x", max_tokens=20,
                    json_schema=bad,
                ))
            assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    finally:
        server.stop(0)


def test_guided_toolcalls_end_to_end(monkeypatch):
    """AIOS_TPU_GUIDED_TOOLCALLS=1: the autonomy reasoning loop sends the
    tool_calls schema (tool names = live catalog enum) with every infer,
    the runtime grammar-constrains the reply, and any tool the model
    calls is catalog-valid by construction."""
    monkeypatch.setenv("AIOS_TPU_GUIDED_TOOLCALLS", "1")
    from aios_tpu import rpc, services
    from aios_tpu.orchestrator.agent_router import AgentRouter
    from aios_tpu.orchestrator.autonomy import AutonomyLoop, guided_toolcalls
    from aios_tpu.orchestrator.goal_engine import GoalEngine, Task
    from aios_tpu.orchestrator.task_planner import TaskPlanner
    from aios_tpu.proto_gen import runtime_pb2
    from aios_tpu.runtime.model_manager import ModelManager
    from aios_tpu.runtime.service import serve

    assert guided_toolcalls()
    manager = ModelManager(num_slots=2, warm_compile=False)
    server, _s, port = serve(
        address="127.0.0.1:0", manager=manager, block=False
    )
    try:
        stub = services.AIRuntimeStub(
            rpc.insecure_channel(f"127.0.0.1:{port}")
        )
        r = stub.LoadModel(runtime_pb2.LoadModelRequest(
            model_name="tiny", model_path="synthetic://tiny-test",
            context_length=512,
        ))
        assert r.status == "ready"
        catalog = ["fs.read", "net.ping", "monitor.cpu"]
        calls_made = []
        schemas_seen = []

        def execute_tool(tool, agent_id, args):
            calls_made.append(tool)
            return {"success": True, "output": "done", "error": ""}

        def runtime_infer(prompt, level="", max_tokens=0, json_schema=""):
            schemas_seen.append(json_schema)
            assert json_schema, "schema must ride on every reasoning call"
            resp = stub.Infer(runtime_pb2.InferRequest(
                prompt=prompt, max_tokens=min(max_tokens or 256, 200),
                intelligence_level=level or "tactical",
                json_schema=json_schema,
            ))
            return resp.text

        engine = GoalEngine()
        loop = AutonomyLoop(
            engine, TaskPlanner(), AgentRouter(), execute_tool,
            runtime_infer=runtime_infer, tool_catalog=lambda: catalog,
        )
        g = engine.submit_goal("investigate anomaly", "desc")
        task = Task(id="t1", goal_id=g.id, description="investigate",
                    intelligence_level="tactical")
        engine.add_tasks(g.id, [task])
        loop.run_reasoning_loop(task)
        assert schemas_seen
        sch = json.loads(schemas_seen[0])
        enum = sch["properties"]["tool_calls"]["items"]["properties"][
            "tool"
        ]["enum"]
        assert enum == catalog
        assert all(c in catalog for c in calls_made)
    finally:
        server.stop(0)


def test_schema_through_gateway_to_runtime_sockets():
    """Two live services: ApiGateway.Infer (json_schema field) -> local
    provider -> AIRuntime gRPC -> grammar-guided engine. The full
    cross-service structured-output path the guided autonomy loop rides."""
    from aios_tpu import rpc, services
    from aios_tpu.gateway.router import RequestRouter
    from aios_tpu.gateway.service import serve as serve_gateway
    from aios_tpu.proto_gen import api_gateway_pb2, runtime_pb2
    from aios_tpu.runtime.model_manager import ModelManager
    from aios_tpu.runtime.service import serve as serve_runtime

    schema = json.dumps({
        "type": "object",
        "properties": {"status": {"type": "string", "enum": ["ok", "error"]}},
        "required": ["status"],
    })
    manager = ModelManager(num_slots=2, warm_compile=False)
    rt_server, _s, rt_port = serve_runtime(
        address="127.0.0.1:0", manager=manager, block=False
    )
    gw_server = None
    try:
        stub = services.AIRuntimeStub(
            rpc.insecure_channel(f"127.0.0.1:{rt_port}")
        )
        r = stub.LoadModel(runtime_pb2.LoadModelRequest(
            model_name="tiny", model_path="synthetic://tiny-test",
            context_length=256,
        ))
        assert r.status == "ready"
        router = RequestRouter(runtime_address=f"127.0.0.1:{rt_port}")
        gw_server, _gs, gw_port = serve_gateway(
            address="127.0.0.1:0", router=router, block=False
        )
        gw = services.ApiGatewayStub(
            rpc.insecure_channel(f"127.0.0.1:{gw_port}")
        )
        resp = gw.Infer(api_gateway_pb2.ApiInferRequest(
            prompt="status?", max_tokens=32, temperature=1.0,
            preferred_provider="local", json_schema=schema,
        ))
        obj = json.loads(resp.text)
        assert obj["status"] in ("ok", "error"), resp.text
    finally:
        if gw_server is not None:
            gw_server.stop(0)
        rt_server.stop(0)
